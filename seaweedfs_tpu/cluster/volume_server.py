"""Volume server — mirror of weed/server/volume_server.go, the HTTP needle
handlers (volume_server_handlers_read.go/_write.go), the heartbeat loop
(volume_grpc_client_to_master.go), and the full EC RPC surface
(volume_grpc_erasure_coding.go) [VERIFY: mount empty; SURVEY.md §2.1, §2.4,
§3.2, §3.5].

Data path: HTTP GET/POST/DELETE /<vid>,<fid> against the local Store, with
EC degraded reads falling back master-lookup -> remote VolumeEcShardRead ->
reconstruction (the p50 north-star path). Control path: weedtpu.VolumeServer
RPC service. Membership: a periodic full-state Heartbeat unary to the
master (the reference's bidi stream collapsed; deltas ride the next tick).
"""

from __future__ import annotations

import base64
import http.server
import json
import os
import queue
import random
import shutil
import socketserver
import threading
import urllib.error
import urllib.parse
import urllib.request
from concurrent import futures
from contextlib import ExitStack
from typing import Optional

import time

import grpc

from seaweedfs_tpu import rpc, stats
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.ec import convert as convert_mod
from seaweedfs_tpu.ec import scrub as scrub_mod
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.security import Guard
from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ec.ec_volume import (
    EcDegradedReadError,
    EcVolume,
    NeedleDeleted,
    NeedleNotFound,
)
from seaweedfs_tpu.pb import MASTER_SERVICE, VOLUME_SERVICE, Heartbeat
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.needle import CrcError, Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import VolumeReadOnly
from seaweedfs_tpu.security import tls
from seaweedfs_tpu.utils import config

_COPY_CHUNK = 1024 * 1024
_EC_EXTS = [".ecx", ".ecj", ".eci"]
EC_SHARD_READ_TIMEOUT = 10.0  # s; per-holder cap on one interval read
# bulk slab streams (rebuild input): larger windows, so a longer per-call
# deadline — but still bounded, so a hung holder fails over instead of
# pinning a rebuild forever
EC_SLAB_READ_TIMEOUT = 120.0
_SLAB_CHUNK = 4 * 1024 * 1024  # bound on one CRC-framed slab-stream chunk
#: parallel survivor-fetch threads for a distributed rebuild (RTT-bound)
EC_REBUILD_FETCH_WORKERS = 16
#: longest a slab stream may WAIT for a rebuild-lane token before being
#: refused outright — an unbounded blocking acquire would pin this gRPC
#: worker and re-create the very starvation the gate exists to prevent
EC_SLAB_ADMISSION_WAIT = 15.0


def _first_multipart_file(body: bytes, ctype: str):
    """(bytes, filename, mime) of the first file part of a form upload,
    or None. email.parser handles the RFC 2046 framing (boundaries,
    part headers, trailing CRLF) so the needle stores exactly the file
    bytes the client attached."""
    import email.parser

    msg = email.parser.BytesParser().parsebytes(
        b"Content-Type: "
        + ctype.encode("latin-1", "replace")  # header charset; never raises
        + b"\r\n\r\n"
        + body
    )
    if not msg.is_multipart():
        return None
    parts = msg.get_payload()
    chosen = next(
        (p for p in parts if p.get_filename()), parts[0] if parts else None
    )
    if chosen is None:
        return None
    payload = chosen.get_payload(decode=True)
    if payload is None:
        return None
    fname = (chosen.get_filename() or "").encode("utf-8", "surrogateescape")
    return payload, fname, chosen.get_content_type()


class VolumeServer:
    def __init__(
        self,
        directories: list[str],
        master_address: str,
        port: int = 0,
        grpc_port: int = 0,
        host: str = "127.0.0.1",
        public_url: str = "",
        data_center: str = "DefaultDataCenter",
        rack: str = "DefaultRack",
        max_volume_count: int = 8,
        heartbeat_interval: float = 5.0,
        encoder=None,
        guard: Optional[Guard] = None,
        needle_map_kind: str = "memory",
        ec_lookup_ttl: float = 30.0,
        replicate_timeout: float = 5.0,
    ):
        self.guard = guard or Guard()
        # Short per-replica timeout: the fan-out is parallel, so a dead
        # replica costs one `replicate_timeout`, never a serial sum.
        self.replicate_timeout = replicate_timeout
        self.store = Store(directories, encoder=encoder, needle_map_kind=needle_map_kind)
        self.store.load()
        self.master_address = master_address
        self.host = host
        self.data_center = data_center
        self.rack = rack
        self.max_volume_count = max_volume_count
        self._hb_interval = heartbeat_interval
        self._stop = threading.Event()

        self._grpc = rpc.RpcServer(port=grpc_port, host=host)
        self._grpc.add_service(self._build_service())
        self.grpc_port = self._grpc.port

        self._http = _ThreadingHTTPServer((host, port), _Handler)
        tls.maybe_wrap_https(self._http)  # data-path HTTPS when configured
        self._http.volume_server = self
        self.port = self._http.server_address[1]
        self.public_url = public_url or f"{host}:{self.port}"
        self._http_thread = threading.Thread(target=self._http.serve_forever, daemon=True)
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        # HA quorum: heartbeat every master (topology is soft state on
        # each; a raft-promoted follower already has a live view)
        self._master_addresses = [
            a.strip() for a in master_address.split(",") if a.strip()
        ]
        self._masters = {a: rpc.RpcClient(a) for a in self._master_addresses}
        self._master = self._masters[self._master_addresses[0]]
        # Per-volume maintenance mutex: compact, EC-shard generation, and
        # the .dat/.idx copy streams all read/rewrite the volume FILES
        # outside the Volume's needle lock — two of them interleaving on
        # one volume (auto-vacuum racing ec.encode, balance racing compact)
        # would stream/encode a half-swapped .dat. Serializing them here
        # closes the race no matter which actor (timer or operator) fires.
        self._maint_locks: dict[int, threading.Lock] = {}
        self._maint_mu = threading.Lock()
        # degraded-read plumbing: LookupEcVolume answers are cached per vid
        # with expiry (the reference caches ShardLocations on the EcVolume)
        # and peer channels are pooled — an uncached lookup + fresh dial per
        # interval read would dominate remote-reconstruct p50
        self._peer_pool = rpc.ClientPool()
        self._shard_locs: dict[int, tuple[float, dict[int, list[str]]]] = {}
        self._shard_locs_lock = threading.Lock()
        # single-flight dedup: vid -> Event set when an in-flight master
        # lookup lands (or fails); concurrent misses wait on it instead of
        # each paying their own LookupEcVolume round-trip
        self._shard_locs_inflight: dict[int, threading.Event] = {}
        # per-vid invalidation generation: a leader whose lookup was in
        # flight when an invalidation landed must not write its (possibly
        # pre-invalidation) result into the cache
        self._shard_locs_gen: dict[int, int] = {}
        self.ec_lookup_ttl = ec_lookup_ttl
        # admission control for the rebuild lane: a storm of bulk
        # VolumeEcShardSlabRead streams (several concurrent rebuilds
        # targeting this holder) would otherwise occupy every RPC worker
        # and starve foreground interval reads. Tokens are taken for the
        # LIFE of a slab stream; waiters queue and are counted.
        self._rebuild_gate = threading.BoundedSemaphore(
            config.env("WEEDTPU_REBUILD_MAX_INFLIGHT")
        )
        # trace-repair stance, latched per server instance so tests can
        # model mixed-version clusters (an "off" peer neither advertises
        # nor serves the projection read — the capability-negotiation
        # fallback path): on | off | auto
        self._trace_repair = config.env("WEEDTPU_TRACE_REPAIR")
        # peer-unreachable accounting for the heartbeat report: the repair
        # scheduler cross-checks these against heartbeat silence, so a
        # dead holder is discovered in read-path time instead of waiting
        # for the topology reaper (initialized before scrub — its repair
        # threads exercise the peer paths from __init__ onward)
        self._peer_fail_mu = threading.Lock()
        self._peer_failures: dict[str, int] = {}
        # scrub & self-heal: the background integrity scanner (when the
        # policy is on) plus the quarantine/repair machinery it feeds.
        # Repair workers start LAZILY on the first quarantine — ec.verify
        # with quarantine:true must heal even on servers running with the
        # continuous scrubber off.
        self._scrub: Optional[scrub_mod.Scrubber] = None
        self._repair_q: "queue.Queue[tuple[int, int]]" = queue.Queue()
        self._repair_threads: list[threading.Thread] = []
        self._repair_mu = threading.Lock()
        backoff = float(config.env("WEEDTPU_SCRUB_REPAIR_BACKOFF"))
        self._repair_policy = scrub_mod.RepairPolicy(
            base=backoff, max_backoff=12.0 * backoff
        )
        # ONE quarantine ledger per server, owned here — NOT by the scan
        # thread — so pending repairs survive restarts even on servers
        # running with the continuous scrubber off (ec.verify -quarantine
        # and verify-on-read quarantine too)
        self._scrub_cursor = scrub_mod.ScrubCursor(self._scrub_cursor_path())
        for ent in list(self._scrub_cursor.quarantine):
            ev = self.store.get_ec_volume(ent["vid"])
            if ev is not None:
                ev.quarantine_shard(ent["shard"], ent["reason"])
            self._enqueue_repair(ent["vid"], ent["shard"])
        # single-flight guard for verify-on-read healing: concurrent
        # corrupt-needle reads of one volume must not each launch their
        # own cluster-wide verify fan-out
        self._heal_mu = threading.Lock()
        self._heal_locks: dict[int, threading.Lock] = {}
        if config.env("WEEDTPU_SCRUB") == "on":
            self._start_scrub()
        # inline-EC ingest (encode-on-write): when the policy is on, every
        # acked append polls the volume's stripe builder through the
        # Store.on_write seam, so a sealing volume is born EC'd instead of
        # paying a warm batch conversion; crossing the auto-seal threshold
        # finalizes in a background thread. Policy off = no hook, no cost.
        self._ingest = None
        if config.env("WEEDTPU_INLINE_EC") == "on":
            from seaweedfs_tpu.ec.ingest import IngestManager

            self._ingest = IngestManager(
                self.store,
                seal_trigger=self._auto_inline_seal,
                spread_factory=(
                    self._spread_factory
                    if config.env("WEEDTPU_INLINE_EC_SPREAD") == "on"
                    else None
                ),
            )
            self.store.on_write = self._ingest.on_write

    # -- lifecycle -----------------------------------------------------------

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def grpc_address(self) -> str:
        return f"{self.host}:{self.grpc_port}"

    def start(self) -> None:
        self._grpc.start()
        self._http_thread.start()
        self.heartbeat_once()
        self._hb_thread.start()

    def _leave_cluster(self) -> None:
        """Stop heartbeating and depart the master topology (shared by
        stop() and the VolumeServerLeave RPC). Setting _stop first also
        gates heartbeat_once(): an admin RPC landing after leave must not
        re-register the drained node."""
        self._stop.set()
        try:
            self._masters_fanout("LeaveCluster", {"url": self.url}, timeout=2)
        except Exception:  # noqa: BLE001 — masters may already be gone
            pass

    def stop(self) -> None:
        self._leave_cluster()
        if self._scrub is not None:
            self._scrub.stop()  # persists the cursor; quarantine entries
            # survive on disk for the next generation's repair queue
        self._http.shutdown()
        self._http.server_close()
        self._grpc.stop()
        for c in self._masters.values():
            c.close()
        self._peer_pool.close_all()
        if self._ingest is not None:
            self._ingest.close()  # journaled state stays on disk for resume
        self.store.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- heartbeat -----------------------------------------------------------

    def _note_peer_failure(self, addr: str) -> None:
        """One unreachable-peer observation (degraded fetch, slab stream,
        shard pull failed at the transport). Crossing the report
        threshold puts the addr in the next heartbeat's
        unreachable_peers — the repair scheduler's fast death signal."""
        with self._peer_fail_mu:
            self._peer_failures[addr] = self._peer_failures.get(addr, 0) + 1

    def _note_peer_success(self, addr: str) -> None:
        if not self._peer_failures:
            return
        with self._peer_fail_mu:
            self._peer_failures.pop(addr, None)

    def _unreachable_peers(self) -> list[str]:
        threshold = int(config.env("WEEDTPU_REPAIR_REPORT_FAILURES"))
        with self._peer_fail_mu:
            return sorted(
                a for a, n in self._peer_failures.items() if n >= threshold
            )

    def _make_heartbeat(self) -> Heartbeat:
        stats.VolumeServerVolumeGauge.labels("normal").set(
            sum(len(loc.volumes) for loc in self.store.locations)
        )
        stats.VolumeServerVolumeGauge.labels("ec").set(
            sum(len(loc.ec_volumes) for loc in self.store.locations)
        )
        return Heartbeat(
            ip=self.host,
            port=self.port,
            grpc_port=self.grpc_port,
            public_url=self.public_url,
            data_center=self.data_center,
            rack=self.rack,
            max_volume_count=self.max_volume_count,
            volumes=self.store.volume_infos(),
            ec_shards=[i.to_dict() for i in self.store.ec_volume_infos()],
            unreachable_peers=self._unreachable_peers(),
        )

    def _masters_fanout(self, method: str, req: dict, timeout: float) -> int:
        """Call every master in PARALLEL (a firewalled master must not
        stall the round by its full RPC deadline); returns success count,
        raising the last error when none succeeded."""
        ok = [0]
        errs: list[Exception] = []
        lock = threading.Lock()

        def one(c: rpc.RpcClient) -> None:
            try:
                c.call(MASTER_SERVICE, method, req, timeout=timeout)
                with lock:
                    ok[0] += 1
            except Exception as e:  # noqa: BLE001 — that master may be down
                with lock:
                    errs.append(e)

        threads = [
            threading.Thread(target=one, args=(c,)) for c in self._masters.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout + 1.0)
        if not ok[0] and errs:
            raise errs[-1]
        return ok[0]

    def heartbeat_once(self) -> None:
        if self._stop.is_set():  # left the cluster: never re-register
            return
        self._masters_fanout("Heartbeat", self._make_heartbeat().to_dict(), timeout=10)

    def _master_query(self, method: str, req: dict, timeout: float = 5.0) -> dict:
        """Read query against any reachable master (soft state is on all)."""
        last_err: Exception | None = None
        for c in self._masters.values():
            try:
                return c.call(MASTER_SERVICE, method, req, timeout=timeout)
            except Exception as e:  # noqa: BLE001
                last_err = e
        raise last_err  # type: ignore[misc]

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._hb_interval):
            try:
                # prune whole TTL volumes whose newest write aged out; the
                # heartbeat that follows drops them from the topology
                self._reap_expired_volumes()
                self.heartbeat_once()
            except Exception:  # noqa: BLE001 — keep beating; master reappears
                continue

    # -- helpers -------------------------------------------------------------

    def _base_path_for(self, vid: int, collection: str = "") -> str:
        """Existing base path for vid, else a fresh one on the emptiest disk."""
        for loc in self.store.locations:
            for candidate in (f"{collection}_{vid}" if collection else None, str(vid)):
                if candidate and (
                    os.path.exists(os.path.join(loc.directory, candidate + ".dat"))
                    or stripe.find_local_shards(os.path.join(loc.directory, candidate))
                    or os.path.exists(os.path.join(loc.directory, candidate + ".ecx"))
                ):
                    return os.path.join(loc.directory, candidate)
        loc = min(
            self.store.locations,
            key=lambda l: len(l.volumes) + len(l.ec_volumes),
        )
        base = f"{collection}_{vid}" if collection else str(vid)
        return os.path.join(loc.directory, base)

    def _lookup_shard_locations(self, vid: int) -> dict[int, list[str]]:
        """shard_id -> [grpc addresses], via the per-vid cache with expiry.
        The reference caches ShardLocations on the EcVolume and refreshes on
        an interval; an expired or missing entry pays one master round-trip,
        every other interval read within the TTL is lookup-free.

        Misses are SINGLE-FLIGHT: a burst of degraded reads against an
        uncached vid (cold start, post-invalidation) elects one leader to
        do the master round-trip; the rest wait on its Event and read the
        fresh cache. A failed leader wakes the waiters with the cache still
        cold — each retries the loop and the next one through becomes
        leader, so failures propagate per caller without a thundering herd
        on the healthy path."""
        while True:
            now = time.monotonic()
            with self._shard_locs_lock:
                hit = self._shard_locs.get(vid)
                if hit is not None and hit[0] > now:
                    return hit[1]
                ev = self._shard_locs_inflight.get(vid)
                if ev is None:
                    ev = self._shard_locs_inflight[vid] = threading.Event()
                    leader = True
                    gen0 = self._shard_locs_gen.get(vid, 0)
                else:
                    leader = False
            if not leader:
                with trace_mod.span("ec.lookup", volume=vid, role="waiter"):
                    ev.wait(timeout=30.0)
                continue  # re-check the cache; become leader if still cold
            with trace_mod.span(
                "ec.lookup", volume=vid, role="leader"
            ):
                try:
                    # bounded retry with decorrelated jitter: ONE transient
                    # master hiccup must not fail the leader AND every waiter
                    # of the burst (each would retry the loop, elect a new
                    # leader, and hammer the recovering master in lockstep).
                    # Only TRANSIENT failures retry — an application-level
                    # fault from a healthy master is final on first answer,
                    # and re-asking would just hold the single-flight
                    # leadership while every waiter queues behind a sleep.
                    retries = int(config.env("WEEDTPU_LOOKUP_RETRIES"))
                    delay = 0.05
                    for attempt in range(retries + 1):
                        try:
                            resp = self._master_query(
                                "LookupEcVolume", {"volume_id": vid}
                            )
                            break
                        except grpc.RpcError as e:
                            if attempt >= retries or e.code() not in (
                                grpc.StatusCode.UNAVAILABLE,
                                grpc.StatusCode.DEADLINE_EXCEEDED,
                            ):
                                raise
                            delay = min(1.0, random.uniform(0.05, delay * 3.0))
                            time.sleep(delay)
                        except Exception:  # noqa: BLE001 — transport-level
                            # (ConnectionError & co. from a dying channel)
                            if attempt >= retries:
                                raise
                            delay = min(1.0, random.uniform(0.05, delay * 3.0))
                            time.sleep(delay)
                    locs: dict[int, list[str]] = {}
                    for entry in resp.get("shard_id_locations", []):
                        # domain-locality ladder: the master annotates each
                        # holder with its rack/DC, so ties in the failover
                        # ladder (and the hedge's alternate pick) prefer
                        # same-rack, then same-DC holders — the cheap fetch
                        # — without any lookup at read time. Stable within
                        # a tier: the master's ordering is preserved.
                        def _locality(locd: dict) -> int:
                            if not locd.get("rack") and not locd.get("data_center"):
                                return 1  # unlabeled reply: neutral
                            if (
                                locd.get("data_center") == self.data_center
                                and locd.get("rack") == self.rack
                            ):
                                return 0
                            if locd.get("data_center") == self.data_center:
                                return 1
                            return 2
                        addrs = [
                            f"{locd['url'].rsplit(':', 1)[0]}:{locd['grpc_port']}"
                            for locd in sorted(
                                entry["locations"], key=_locality
                            )
                            if locd["url"] != self.url  # not a remote for ourselves
                        ]
                        if addrs:
                            locs[int(entry["shard_id"])] = addrs
                    with self._shard_locs_lock:
                        # an invalidation that landed mid-lookup means this
                        # answer may predate it: serve it to OUR callers (they
                        # asked before the invalidation) but leave the cache
                        # cold so the invalidator's own lookup goes to the
                        # master fresh
                        if self._shard_locs_gen.get(vid, 0) == gen0:
                            self._shard_locs[vid] = (now + self.ec_lookup_ttl, locs)
                    return locs
                finally:
                    with self._shard_locs_lock:
                        self._shard_locs_inflight.pop(vid, None)
                    ev.set()

    def _invalidate_shard_locations(self, vid: int) -> None:
        with self._shard_locs_lock:
            self._shard_locs.pop(vid, None)
            self._shard_locs_gen[vid] = self._shard_locs_gen.get(vid, 0) + 1

    def _remote_reader_for(self, vid: int):
        """RemoteReader closure for EC degraded reads: cached master
        LookupEcVolume -> pooled VolumeEcShardRead on a holder
        (SURVEY.md §3.2)."""
        # Peer-identity state for the process-wide suspicion registry.
        # THREE layers, most-accurate first:
        #   `attempts` — one PER-CALL token per live read, naming the addr
        #     that call is inside right now + when it entered. A capped
        #     timeout fires while the pool thread still sits in the wedged
        #     holder, so the LONGEST-RUNNING live attempt for the shard is
        #     exact blame — per-call tokens mean a concurrent fast-failing
        #     read can neither clobber nor erase a blocked read's entry.
        #   `slowest` — per shard, the addr that consumed the most wall
        #     time in the most recent COMPLETED read. The slow-miss signal
        #     (recover_suspect_after) fires after the read returned; the
        #     attempt that ate the time is the wedge suspect, NOT whichever
        #     holder happened to be tried last before the miss.
        #   `last_locs` — the most recent successful lookup; deliberately
        #     survives _invalidate_shard_locations (failed reads invalidate
        #     the SERVING cache, but identity keying must not collapse to
        #     per-volume scope exactly when a peer goes bad).
        attempts: dict[object, tuple[int, str, float]] = {}
        slowest: dict[int, str] = {}
        last_locs: dict[int, list[str]] = {}

        def read(shard_id: int, offset: int, size: int) -> Optional[bytes]:
            try:
                locs = self._lookup_shard_locations(vid)
            except Exception:  # noqa: BLE001
                return None
            last_locs.update(locs)
            token = object()
            slow_addr, slow_dur = None, -1.0
            failed = False
            try:
                for addr in locs.get(shard_id, ()):
                    t0 = time.monotonic()
                    attempts[token] = (shard_id, addr, t0)
                    with trace_mod.span(
                        "ec.fetch.holder", addr=addr, shard=shard_id
                    ):
                        try:
                            chunks = self._peer_pool.get(addr).stream(
                                VOLUME_SERVICE,
                                "VolumeEcShardRead",
                                {
                                    "volume_id": vid,
                                    "shard_id": shard_id,
                                    "offset": offset,
                                    "size": size,
                                },
                                # one interval, not a bulk copy: a hung holder
                                # must not pin a degraded read for the 600s
                                # bulk-stream default — the recover fan-out
                                # treats a timeout as a miss and uses another
                                # survivor
                                timeout=EC_SHARD_READ_TIMEOUT,
                            )
                            buf = b"".join(chunks)
                            if len(buf) == size:
                                self._note_peer_success(addr)
                                return buf
                            failed = True  # holder answered short: stale layout
                            trace_mod.annotate(short=len(buf))
                        except Exception:  # noqa: BLE001 — try next holder
                            self._peer_pool.invalidate(addr)
                            self._note_peer_failure(addr)
                            failed = True
                            trace_mod.annotate(failed=True)
                        finally:
                            dur = time.monotonic() - t0
                            if dur > slow_dur:
                                slow_addr, slow_dur = addr, dur
                return None
            finally:
                attempts.pop(token, None)
                if slow_addr is not None:
                    slowest[shard_id] = slow_addr
                if failed:
                    # shards may have moved; next read re-asks the master
                    self._invalidate_shard_locations(vid)

        def peer_for(shard_id: int) -> Optional[str]:
            """Peer identity behind `shard_id` for suspicion keying —
            LOCAL-STATE-ONLY (checks run per candidate on the read ladder
            and must never add a master round-trip). Precedence: the addr
            the LONGEST-RUNNING live attempt is blocked on, then the addr
            that consumed the most time in the last completed read, then
            the primary holder from the last successful lookup. None until
            this reader has looked up at least once (EcVolume then keys
            suspicion per-volume, the narrower fallback)."""
            live = [
                (started, addr)
                for (s, addr, started) in list(attempts.values())
                if s == shard_id
            ]
            if live:
                return min(live)[1]
            addrs = last_locs.get(shard_id) or ()
            slow = slowest.get(shard_id)
            if slow and (not addrs or slow in addrs):
                # still a listed holder (or no fresher list exists): the
                # addr that ate the last read's wall time is best blame
                return slow
            if addrs:
                return addrs[0]
            # this reader never completed a read, but the SERVER may have
            # the locations cached (serving cache, possibly TTL-stale —
            # identity doesn't care): without this, a volume's FIRST
            # degraded read can't see a peer another volume already marked
            # wedged and pays its own capped attempt anyway
            with self._shard_locs_lock:
                hit = self._shard_locs.get(vid)
            if hit is not None:
                cached = hit[1].get(shard_id)
                if cached:
                    return cached[0]
            return None

        def holders_for(shard_id: int) -> list[str]:
            """Known holder addrs behind `shard_id`, LOCAL-STATE-ONLY like
            peer_for (the hedge decision runs mid-read and must never add
            a master round-trip): serving cache first (fresher after an
            invalidation), then this reader's last successful lookup."""
            with self._shard_locs_lock:
                hit = self._shard_locs.get(vid)
            if hit is not None and hit[1].get(shard_id):
                return list(hit[1][shard_id])
            return list(last_locs.get(shard_id, ()))

        def via(addr: str, shard_id: int, offset: int, size: int) -> Optional[bytes]:
            """One single-holder interval read — the hedge backup path:
            same transport, timeout, and live-attempt bookkeeping as the
            ladder, but pinned at `addr` so the backup provably lands on a
            DIFFERENT holder than the primary it is racing."""
            token = object()
            attempts[token] = (shard_id, addr, time.monotonic())
            try:
                with trace_mod.span("ec.fetch.holder", addr=addr, shard=shard_id):
                    chunks = self._peer_pool.get(addr).stream(
                        VOLUME_SERVICE,
                        "VolumeEcShardRead",
                        {
                            "volume_id": vid,
                            "shard_id": shard_id,
                            "offset": offset,
                            "size": size,
                        },
                        timeout=EC_SHARD_READ_TIMEOUT,
                    )
                    buf = b"".join(chunks)
                if len(buf) == size:
                    self._note_peer_success(addr)
                    return buf
                return None
            except Exception:  # noqa: BLE001 — a failed backup is a miss
                self._peer_pool.invalidate(addr)
                self._note_peer_failure(addr)
                return None
            finally:
                attempts.pop(token, None)

        read.peer_for = peer_for
        read.holders_for = holders_for
        read.via = via
        return read

    def _open_ec_volume(self, vid: int) -> Optional[EcVolume]:
        ev = self.store.get_ec_volume(vid)
        if ev is not None and ev.remote_reader is None:
            ev.remote_reader = self._remote_reader_for(vid)
        return ev

    # -- scrub & self-heal ----------------------------------------------------

    def _ec_volumes_snapshot(self) -> dict[int, EcVolume]:
        return {
            vid: ev
            for loc in self.store.locations
            for vid, ev in list(loc.ec_volumes.items())
        }

    def _scrub_cursor_path(self) -> str:
        path = config.env("WEEDTPU_SCRUB_CURSOR")
        if path:
            return path
        return os.path.join(
            self.store.locations[0].directory, ".scrub_cursor.json"
        )

    def _scrub_admit(self) -> bool:
        """Admission hook for scrub chunk reads: the scan yields whenever
        the rebuild lane (WEEDTPU_REBUILD_MAX_INFLIGHT) is saturated —
        integrity scanning is repair traffic and queues behind both
        foreground reads (via the rate cap) and actual rebuild streams
        (via this gate check). The token is probed, not held: a local
        chunk read is milliseconds, and pinning a slab-stream slot for a
        whole shard scan would do the starving this hook prevents."""
        if self._rebuild_gate.acquire(blocking=False):
            self._rebuild_gate.release()
            return True
        return False

    def _start_scrub(self) -> None:
        # (quarantine entries persisted by a previous generation were
        # already re-marked and re-queued at __init__ — that recovery must
        # not depend on the scan thread being enabled)
        self._scrub = scrub_mod.Scrubber(
            volumes=self._ec_volumes_snapshot,
            on_finding=self._scrub_finding,
            cursor_path=self._scrub_cursor_path(),
            rate_mb=float(config.env("WEEDTPU_SCRUB_RATE_MB")),
            chunk_bytes=int(config.env("WEEDTPU_SCRUB_CHUNK")),
            interval=float(config.env("WEEDTPU_SCRUB_INTERVAL")),
            admit=self._scrub_admit,
            cursor=self._scrub_cursor,
        )
        self._scrub.start()

    def _scrub_finding(self, vid: int, shard: int, verdict: str) -> None:
        """Quarantine one failed shard and schedule its automatic repair
        (called from the scrub thread and the verify RPC). The damaged
        file moves aside to `.bad` so shard discovery — and the rebuild
        that is about to run — treats it as missing rather than as a
        survivor; the bytes stay on disk for forensics until the repair
        verifies its replacement."""
        ev = self.store.get_ec_volume(vid)
        if ev is None:
            return
        with self.maintenance_lock(vid):
            ev.quarantine_shard(shard, verdict)
            p = stripe.shard_file_name(ev.base, shard)
            if os.path.exists(p):
                try:
                    os.replace(p, p + ".bad")
                except OSError:
                    pass  # missing-class findings have nothing to move
        self._scrub_cursor.add_quarantine(vid, shard, verdict)
        try:
            # push the shard delta to the master NOW: peers' degraded
            # reads re-route to clean holders on their next lookup
            # instead of burning an attempt on our quarantined copy
            self.heartbeat_once()
        except Exception:  # noqa: BLE001 — masters may be down mid-chaos
            pass
        self._enqueue_repair(vid, shard)

    def _enqueue_repair(self, vid: int, shard: int) -> None:
        with self._repair_mu:
            want = int(config.env("WEEDTPU_SCRUB_MAX_REPAIRS"))
            while len(self._repair_threads) < want:
                t = threading.Thread(
                    target=self._repair_loop,
                    daemon=True,
                    name=f"ec-scrub-repair-{len(self._repair_threads)}",
                )
                t.start()
                self._repair_threads.append(t)
        self._repair_q.put((vid, shard))

    def _repair_loop(self) -> None:
        """One repair worker: drain quarantined shards, honoring the
        per-shard backoff clock. Failures re-queue; the worker count
        (WEEDTPU_SCRUB_MAX_REPAIRS) is the concurrency cap."""
        while not self._stop.is_set():
            try:
                vid, shard = self._repair_q.get(timeout=0.5)
            except queue.Empty:
                continue
            key = (vid, shard)
            delay = self._repair_policy.delay(key)
            if delay > 0:
                # not due yet: wait a beat, then put it back (bounded at
                # ~2 requeues/s per pending shard, not a spin)
                self._stop.wait(min(delay, 0.5))
                self._repair_q.put(key)
                continue
            ok = False
            try:
                ok = self._repair_shard(vid, shard)
            except Exception:  # noqa: BLE001 — any failure re-queues
                ok = False
            if ok:
                self._repair_policy.succeeded(key)
                # ledger first: the ok counter is the observable "repair
                # finished" signal (tests and operators poll it), so the
                # persisted quarantine entry must already be gone when it
                # ticks
                self._scrub_cursor.remove_quarantine(vid, shard)
                stats.ScrubRepairs.labels("ok").inc()
            else:
                stats.ScrubRepairs.labels("failed").inc()
                self._repair_policy.failed(key)
                self._repair_q.put(key)

    def _repair_shard(self, vid: int, shard: int) -> bool:
        """One automatic repair attempt for a quarantined shard: pull a
        clean replica from another holder when one exists (cheapest),
        else trace-mode rebuild from survivors (slab fallback inside
        `_ec_rebuild_remote`); either way the bytes ON DISK are
        re-verified against the `.eci` CRC before the shard re-enters
        serving. True = repaired (or nothing left to repair)."""
        with trace_mod.ensure("scrub.repair", klass="scrub"):
            trace_mod.annotate(volume=vid, shard=shard)
            return self._repair_shard_inner(vid, shard)

    def _repair_shard_inner(self, vid: int, shard: int) -> bool:
        ev = self.store.get_ec_volume(vid)
        if ev is None:
            return True  # volume unmounted/deleted since: nothing to heal
        base = ev.base
        from seaweedfs_tpu.storage.store import parse_base_name

        parsed = parse_base_name(os.path.basename(base))
        collection = parsed[0] if parsed else ""
        info = stripe.read_ec_info(base)
        recorded = (info or {}).get("shard_crc32")
        want_len = stripe.geometry_from_info(info).total_shards
        if not isinstance(recorded, list) or len(recorded) != want_len:
            return False  # nothing to verify a repair against
        want_size = scrub_mod.expected_shard_size(info)
        path = stripe.shard_file_name(base, shard)
        produced = os.path.exists(path)  # an earlier repair's rebuild may
        # already have regenerated this shard (one rebuild call fills
        # EVERY missing shard of the volume)
        if not produced:
            try:
                self._invalidate_shard_locations(vid)
                locs = self._lookup_shard_locations(vid)
            except Exception:  # noqa: BLE001 — master down: try a rebuild
                locs = {}
            for addr in locs.get(shard, ()):
                if self._pull_clean_shard(
                    addr, vid, collection, base, shard, recorded[shard]
                ):
                    produced = True
                    break
        if not produced:
            resp = self._ec_rebuild_remote(
                vid, collection, base, {"trace_mode": self._trace_repair}
            )
            if shard not in resp.get("rebuilt_shard_ids", []):
                return False
        # belt + braces: the rebuild CRC-verified its STREAM; this pass
        # verifies the BYTES ON DISK (a torn local write must not remount)
        verdict = scrub_mod.scan_shard_file(path, recorded[shard], want_size)
        if verdict != scrub_mod.OK:
            try:
                os.unlink(path)
            except OSError:
                pass
            return False
        with self.maintenance_lock(vid):
            if not ev.mount_local_shard(shard):
                return False
            try:
                os.unlink(path + ".bad")
            except OSError:
                pass
        try:
            self.heartbeat_once()  # the shard is a holder again
        except Exception:  # noqa: BLE001
            pass
        return True

    def _heal_needle_read(self, vid: int, needle_id: int, cookie=None):
        """A needle read failed its body crc32c (Needle.from_bytes) — some
        interval of it was served from a corrupt copy BEFORE the
        background scrubber reached it. Verify-on-read is the second
        detection layer: identify the damaged shard (scan the needle's
        local shards against .eci; failing that, ask every remote holder
        of the touched shards to verify-and-quarantine via the
        VolumeEcShardsVerify RPC), quarantine it, and retry the read —
        with the bad copy out of serving, the ladder reconstructs from
        clean survivors and the CLIENT NEVER SEES THE CORRUPT BYTES.
        Raises when no culprit can be identified (nothing left to heal
        with) — a 500, not silently-served garbage.

        Healing is SINGLE-FLIGHT per volume: concurrent corrupt-needle
        reads serialize on a per-vid lock and re-try the read first —
        whoever got there before us likely already quarantined the
        culprit, so one flipped bit costs one verify fan-out, never a
        scan storm across every holder per concurrent reader."""
        with self._heal_mu:
            lk = self._heal_locks.setdefault(vid, threading.Lock())
        with lk:
            try:
                return self.store.read_ec_needle(vid, needle_id, cookie)
            except CrcError:
                pass  # still corrupt: we are the healer
            return self._heal_needle_read_locked(vid, needle_id, cookie)

    def _heal_needle_read_locked(self, vid: int, needle_id: int, cookie=None):
        with trace_mod.ensure("heal.verify", klass="scrub"):
            trace_mod.annotate(volume=vid, needle=needle_id)
            return self._heal_needle_read_hunt(vid, needle_id, cookie)

    def _heal_needle_read_hunt(self, vid: int, needle_id: int, cookie=None):
        ev = self._open_ec_volume(vid)
        if ev is None:
            raise IOError(f"needle {needle_id:x}: body crc mismatch")
        _, _, intervals = ev.locate_needle(needle_id)
        touched = sorted(
            {iv.to_shard_id_and_offset(ev.large, ev.small)[0] for iv in intervals}
        )
        info = stripe.read_ec_info(ev.base)
        recorded = (info or {}).get("shard_crc32")
        found = False
        if isinstance(recorded, list) and len(recorded) == stripe.geometry_from_info(info).total_shards:
            want_size = scrub_mod.expected_shard_size(info)
            for s in touched:
                if s not in ev._shard_files:
                    continue
                verdict = scrub_mod.scan_shard_file(
                    stripe.shard_file_name(ev.base, s), recorded[s], want_size
                )
                if verdict != scrub_mod.OK:
                    stats.ScrubCorruptionsFound.labels(verdict).inc()
                    self._scrub_finding(vid, s, verdict)
                    found = True
        if not found:
            # the corrupt interval may have been FETCHED from a peer
            # holder whose scrubber has not reached it: ask every holder
            # of the touched shards to verify-and-quarantine its copies,
            # then re-route — the retry lands on a clean replica (or
            # reconstructs around the quarantined one)
            try:
                locs = self._lookup_shard_locations(vid)
            except Exception:  # noqa: BLE001 — master down: nothing to ask
                locs = {}
            for addr in sorted({a for s in touched for a in locs.get(s, ())}):
                try:
                    r = self._peer_pool.get(addr).call(
                        VOLUME_SERVICE,
                        "VolumeEcShardsVerify",
                        {"volume_id": vid, "quarantine": True},
                        timeout=30,
                    )
                    if r.get("quarantined"):
                        found = True
                except Exception:  # noqa: BLE001 — holder down: next
                    continue
            if found:
                self._invalidate_shard_locations(vid)
        if not found:
            raise IOError(
                f"needle {needle_id:x}: body crc mismatch and no corrupt "
                "shard could be identified on any holder"
            )
        try:
            return self.store.read_ec_needle(vid, needle_id, cookie)
        except CrcError as e:
            # a second corrupt copy survived the quarantine round (e.g.
            # damage outside the touched shards, or a peer's verify raced
            # its own repair): surface a typed IOError — the HTTP handler
            # answers 500 JSON, never a dropped connection
            raise IOError(
                f"needle {needle_id:x}: still failing body crc after "
                "quarantining a corrupt shard — repair in progress"
            ) from e

    def _pull_clean_shard(
        self,
        addr: str,
        vid: int,
        collection: str,
        base: str,
        shard: int,
        want_crc: int,
    ) -> bool:
        """Re-pull one shard file from a peer holder, CRC-verifying the
        stream against the `.eci` record BEFORE it replaces anything —
        the peer's copy may be silently corrupt too (its own scrubber
        just hasn't reached it), and a repair must never launder bad
        bytes back into serving."""
        import zlib

        tmp = base + stripe.to_ext(shard) + ".cpy"
        try:
            chunks = self._peer_pool.get(addr).stream(
                VOLUME_SERVICE,
                "VolumeEcShardFileCopy",
                {"volume_id": vid, "collection": collection,
                 "ext": stripe.to_ext(shard)},
                timeout=EC_SLAB_READ_TIMEOUT,
            )
            crc = 0
            with open(tmp, "wb") as f:
                for chunk in chunks:
                    crc = zlib.crc32(chunk, crc)
                    f.write(chunk)
                f.flush()
                os.fsync(f.fileno())
            if crc != (want_crc & 0xFFFFFFFF):
                return False  # replica is damaged too: rebuild instead
            os.replace(tmp, base + stripe.to_ext(shard))
            return True
        except Exception:  # noqa: BLE001 — holder down/short: next option
            return False
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    # -- RPC service ---------------------------------------------------------

    def _build_service(self) -> rpc.Service:
        svc = rpc.Service(VOLUME_SERVICE)
        add = svc.add
        add("VolumeCreate", self._rpc_volume_create)
        add("VolumeDelete", self._rpc_volume_delete)
        add("VolumeMarkReadonly", self._rpc_mark_readonly)
        add("VolumeMarkWritable", self._rpc_mark_writable)
        add("VolumeCompact", self._rpc_compact)
        add("VolumeCopy", self._rpc_volume_copy)
        add("VolumeStatus", self._rpc_volume_status)
        add("WriteNeedle", self._rpc_write_needle)
        add("DeleteNeedle", self._rpc_delete_needle)
        add("VolumeEcShardsGenerate", self._rpc_ec_generate)
        add("VolumeEcShardsCopy", self._rpc_ec_copy)
        add("VolumeEcShardsRebuild", self._rpc_ec_rebuild)
        add("VolumeEcShardsRebuildBatch", self._rpc_ec_rebuild_batch)
        add("VolumeEcShardPartialWrite", self._rpc_ec_partial_write)
        add("VolumeEcShardSpreadCommit", self._rpc_ec_spread_commit)
        add("VolumeEcShardsConvert", self._rpc_ec_convert)
        add("VolumeEcShardsVerify", self._rpc_ec_verify)
        add("VolumeEcShardsMount", self._rpc_ec_mount)
        add("VolumeEcShardsUnmount", self._rpc_ec_unmount)
        add("VolumeEcShardRead", self._rpc_ec_shard_read, kind="unary_stream", resp_format="bytes")
        add("VolumeEcShardSlabRead", self._rpc_ec_slab_read, kind="unary_stream", resp_format="bytes")
        add("VolumeEcShardFileCopy", self._rpc_ec_file_copy, kind="unary_stream", resp_format="bytes")
        add("VolumeEcBlobDelete", self._rpc_ec_blob_delete)
        add("VolumeEcShardsToVolume", self._rpc_ec_to_volume)
        add("VolumeEcShardsDelete", self._rpc_ec_delete)
        add("VolumeTierMove", self._rpc_tier_move)
        add("VolumeTierFetch", self._rpc_tier_fetch)
        add("VolumeMount", self._rpc_volume_mount)
        add("VolumeUnmount", self._rpc_volume_unmount)
        add("VolumeConfigure", self._rpc_volume_configure)
        add("VolumeNeedleIds", self._rpc_needle_ids)
        add("VolumeNeedleTs", self._rpc_needle_ts)
        add("ReadNeedle", self._rpc_read_needle)
        add("VolumeServerLeave", self._rpc_server_leave)
        return svc

    # volume admin

    def _rpc_volume_create(self, req: dict, ctx) -> dict:
        self.store.create_volume(
            int(req["volume_id"]),
            collection=req.get("collection", ""),
            replication=req.get("replication") or "000",
            ttl=req.get("ttl", ""),
        )
        return {}

    def _rpc_volume_delete(self, req: dict, ctx) -> dict:
        if self._ingest is not None:  # partial stripe state dies with the .dat
            v = self.store.get_volume(int(req["volume_id"]))
            self._ingest.discard(
                int(req["volume_id"]), v.base_path if v is not None else None
            )
        self.store.remove_volume(int(req["volume_id"]))
        self.heartbeat_once()  # push the deletion to the master now
        return {}

    def _rpc_mark_readonly(self, req: dict, ctx) -> dict:
        v = self.store.get_volume(int(req["volume_id"]))
        if v is None:
            raise rpc.NotFoundFault(f"volume {req['volume_id']} not found")
        v.read_only = True
        return {}

    def _rpc_mark_writable(self, req: dict, ctx) -> dict:
        v = self.store.get_volume(int(req["volume_id"]))
        if v is None:
            raise rpc.NotFoundFault(f"volume {req['volume_id']} not found")
        v.read_only = False
        return {}

    def _reap_expired_volumes(self) -> None:
        """TTL reap under the per-volume maintenance mutex: a volume that
        is frozen (balance/ec.encode in flight) or mid-copy must not have
        its files unlinked underneath the operation — it stays for the
        next sweep. The expiry re-check happens under the VOLUME lock and
        flips read_only before any unlink, so a write acked after the
        sweep's scan either refreshed the mtime (volume survives) or is
        refused — an acknowledged write is never deleted."""
        for vid in self.store.expired_volume_ids():
            with self.maintenance_lock(vid):
                vol = self.store.get_volume(vid)
                if vol is None or vol.read_only:
                    continue  # frozen: an operator operation owns it
                with vol._lock:
                    if not vol.is_expired():
                        continue  # a write landed since the scan
                    vol.read_only = True  # fence out further writes
                self.store.remove_volume(vid)

    def maintenance_lock(self, vid: int) -> threading.Lock:
        with self._maint_mu:
            lk = self._maint_locks.get(vid)
            if lk is None:
                lk = self._maint_locks[vid] = threading.Lock()
            return lk

    def _rpc_compact(self, req: dict, ctx) -> dict:
        vid = int(req["volume_id"])
        v = self.store.get_volume(vid)
        if v is None:
            raise rpc.NotFoundFault(f"volume {req['volume_id']} not found")
        with self.maintenance_lock(vid):
            v = self.store.get_volume(vid)
            if v is None:
                raise rpc.NotFoundFault(f"volume {vid} not found")
            if v.read_only:
                # frozen volumes are frozen for a reason (ec.encode, copy in
                # flight): compacting one would shift every needle offset
                raise rpc.RpcFault(f"volume {vid} is read-only; not compacting")
            if self._ingest is not None:
                # compaction rewrites the whole .dat: every encoded inline
                # row is stale — drop the state (journal + partials too),
                # a fresh builder restarts from the compacted file on the
                # next write
                self._ingest.discard(vid, v.base_path)
            before, after = v.compact()
            if self._ingest is not None:
                # again AFTER the rewrite: a write that acked just before
                # the compact may have raced a builder back into existence
                # from the PRE-compact .dat between the first discard and
                # the offset-shifting rewrite
                self._ingest.discard(vid, v.base_path)
        return {"bytes_before": before, "bytes_after": after}

    def _rpc_volume_copy(self, req: dict, ctx) -> dict:
        """VolumeCopy: pull a volume's .dat/.idx from source_data_node and
        load it locally (volume_grpc_copy.go analog; serves
        volume.fix.replication)."""
        vid = int(req["volume_id"])
        collection = req.get("collection", "")
        if self.store.get_volume(vid) is not None:
            raise rpc.RpcFault(f"volume {vid} already exists locally")
        base = self._base_path_for(vid, collection)
        # pull BOTH files to temp names, rename only once both are complete:
        # a half-copied volume must never be discoverable by Store.load()
        tmps = {ext: base + ext + ".cpy" for ext in (".dat", ".idx")}
        try:
            with rpc.RpcClient(req["source_data_node"]) as c:
                for ext, tmp in tmps.items():
                    chunks = c.stream(
                        VOLUME_SERVICE,
                        "VolumeEcShardFileCopy",
                        {"volume_id": vid, "collection": collection, "ext": ext},
                    )
                    with open(tmp, "wb") as f:
                        for chunk in chunks:
                            f.write(chunk)
                        f.flush()
                        os.fsync(f.fileno())
            for ext, tmp in tmps.items():
                os.replace(tmp, base + ext)
        finally:
            for tmp in tmps.values():
                if os.path.exists(tmp):
                    os.remove(tmp)
        from seaweedfs_tpu.storage.volume import Volume

        loc = next(
            l for l in self.store.locations if os.path.dirname(base) == l.directory
        )
        v = Volume(loc.directory, vid, collection)
        v.read_only = bool(req.get("read_only", False))
        loc.volumes[vid] = v
        self.heartbeat_once()
        return {"size": os.path.getsize(base + ".dat")}

    def _rpc_tier_move(self, req: dict, ctx) -> dict:
        """VolumeTierMove: upload the .dat to remote storage and reopen
        the volume through the remote backend (tiering, SURVEY.md §2.1
        'Remote storage tiering')."""
        from seaweedfs_tpu.remote_storage import make_remote_client
        from seaweedfs_tpu.remote_storage.tier import tier_move
        from seaweedfs_tpu.storage.volume import Volume

        vid = int(req["volume_id"])
        v = self.store.get_volume(vid)
        if v is None:
            raise rpc.NotFoundFault(f"volume {vid} not found")
        if v.tiered:
            raise rpc.RpcFault(f"volume {vid} is already tiered")
        client = make_remote_client(req["destination"])
        if self._ingest is not None:  # the local .dat is leaving this disk
            self._ingest.discard(vid, v.base_path)
        was_read_only = v.read_only
        v.read_only = True  # freeze writes; READS keep serving during upload
        try:
            info = tier_move(
                v.base_path,
                client,
                key_prefix=req.get("key_prefix") or "volumes/",
                keep_local=True,
            )
        except Exception:
            v.read_only = was_read_only
            raise
        # upload verified: swap to the remote backend (the only offline
        # window is this close/remove/reopen, not the upload itself)
        v.close()
        os.remove(v.base_path + ".dat")
        for loc in self.store.locations:
            if loc.volumes.get(vid) is v:
                loc.volumes[vid] = Volume(loc.directory, vid, v.collection)
        self.heartbeat_once()
        return {"size": info["size"], "key": info["key"]}

    def _rpc_tier_fetch(self, req: dict, ctx) -> dict:
        """VolumeTierFetch: bring a tiered .dat back to local disk."""
        from seaweedfs_tpu.remote_storage.tier import tier_fetch
        from seaweedfs_tpu.storage.volume import Volume

        vid = int(req["volume_id"])
        v = self.store.get_volume(vid)
        if v is None:
            raise rpc.NotFoundFault(f"volume {vid} not found")
        if not v.tiered:
            raise rpc.RpcFault(f"volume {vid} is not tiered")
        v.close()
        tier_fetch(v.base_path)
        for loc in self.store.locations:
            if loc.volumes.get(vid) is v:
                loc.volumes[vid] = Volume(loc.directory, vid, v.collection)
        self.heartbeat_once()
        return {"size": os.path.getsize(v.base_path + ".dat")}

    def _rpc_volume_status(self, req: dict, ctx) -> dict:
        vid = int(req["volume_id"])
        v = self.store.get_volume(vid)
        if v is not None:
            return {
                "volume_id": vid,
                "kind": "normal",
                "size": v.content_size(),
                "file_count": v.needle_count(),
                "read_only": v.read_only,
                "rack": self.rack,
                "data_center": self.data_center,
            }
        ev = self.store.get_ec_volume(vid)
        if ev is not None:
            per_shard: dict[str, int] = {}
            for s in ev.shard_ids:
                try:
                    per_shard[str(s)] = os.path.getsize(
                        stripe.shard_file_name(ev.base, s)
                    )
                except OSError:  # racing unmount/delete: omit, don't fault
                    continue
            return {
                "volume_id": vid,
                "kind": "ec",
                "shard_ids": ev.shard_ids,
                "shard_size": ev.shard_size,
                # per-shard, not the max: a remote rebuilder's geometry
                # preflight must see a truncated shard hiding behind a
                # healthy sibling on the same holder
                "shard_file_sizes": per_shard,
                # trace-repair planners only group shards onto holders
                # that advertise the projection read
                "capabilities": (
                    ["slab_projection"] if self._trace_repair != "off" else []
                ),
                # shards pulled from serving by failed integrity
                # verification (scrub/ec.verify), with WHY — operators and
                # rebuilding peers must be able to tell "quarantined,
                # repair pending" from "never held here"
                "quarantined": {
                    str(s): r for s, r in sorted(ev.quarantined.items())
                },
                # recorded geometry: ec.convert's pre-copy pulls only the
                # <= k shards the conversion reads, and shell maintenance
                # (ec.rebuild) scans missing shards over THIS volume's
                # total, not the legacy 14
                "data_shards": ev.data_shards,
                "total_shards": ev.total_shards,
                # failure-domain labels: placement planners and operator
                # audits read the holder's rack/zone straight off status
                "rack": self.rack,
                "data_center": self.data_center,
            }
        raise rpc.NotFoundFault(f"volume {vid} not found")

    # needle ops over RPC (HTTP is the primary data path; these serve
    # replication fan-out and tests)

    def _rpc_write_needle(self, req: dict, ctx) -> dict:
        import base64

        fid = FileId.parse(req["fid"])
        n = Needle(cookie=fid.cookie, id=fid.key, data=base64.b64decode(req["data"]))
        # *_b64 carry raw bytes losslessly (the check.disk repair path);
        # the plain fields remain for human callers with UTF-8 names
        if req.get("name_b64"):
            n.name = base64.b64decode(req["name_b64"])
        elif req.get("name"):
            n.name = req["name"].encode()
        if req.get("mime_b64"):
            n.mime = base64.b64decode(req["mime_b64"])
        elif req.get("mime"):
            n.mime = req["mime"].encode()
        offset, size = self.store.write_needle(fid.volume_id, n)
        return {"size": size}

    def _rpc_delete_needle(self, req: dict, ctx) -> dict:
        fid = FileId.parse(req["fid"])
        found = self.store.delete_needle(fid.volume_id, fid.key)
        return {"found": bool(found)}

    def _rpc_read_needle(self, req: dict, ctx) -> dict:
        """Read one needle by id (no cookie check) — serves volume.check.disk's
        replica sync, where the repairer must copy the needle verbatim
        (cookie included) from the replica that has it."""
        import base64

        try:
            # wire the remote reader first: an EC volume whose stripe is
            # partly remote (spread parity, lost local shards) must serve
            # this read through the same degraded ladder as the HTTP path
            self._open_ec_volume(int(req["volume_id"]))
            n = self.store.read_needle(int(req["volume_id"]), int(req["needle_id"]))
        except CrcError:
            # same verify-on-read healing as the HTTP path: a repairer
            # must get clean reconstructed bytes, never corrupt ones
            n = self._heal_needle_read(int(req["volume_id"]), int(req["needle_id"]))
        except KeyError as e:  # volume or needle gone (racing delete): typed fault
            raise rpc.NotFoundFault(str(e)) from e
        return {
            "cookie": n.cookie,
            "data": base64.b64encode(n.data).decode(),
            # b64, not a lossy text decode: names/mimes are raw bytes, and a
            # repair must round-trip them verbatim
            "name_b64": base64.b64encode(n.name or b"").decode(),
            "mime_b64": base64.b64encode(n.mime or b"").decode(),
            # volume.fsck's -cutoffTimeAgo filter reads this to spare
            # needles written while the check was running
            "append_at_ns": n.append_at_ns,
        }

    def _rpc_volume_mount(self, req: dict, ctx) -> dict:
        """Re-open an unmounted volume from disk (VolumeMount analog)."""
        if not self.store.mount_volume(int(req["volume_id"])):
            raise rpc.NotFoundFault(f"no files for volume {req['volume_id']}")
        self.heartbeat_once()
        return {}

    def _rpc_volume_unmount(self, req: dict, ctx) -> dict:
        """Stop serving a volume but keep its files (VolumeUnmount analog)
        — operators use it to fence a volume for offline inspection."""
        if not self.store.unmount_volume(int(req["volume_id"])):
            raise rpc.NotFoundFault(f"volume {req['volume_id']} not mounted")
        self.heartbeat_once()
        return {}

    def _rpc_volume_configure(self, req: dict, ctx) -> dict:
        """Change a volume's replica placement in its superblock
        (volume.configure.replication analog)."""
        v = self.store.get_volume(int(req["volume_id"]))
        if v is None:
            raise rpc.NotFoundFault(f"volume {req['volume_id']} not found")
        if getattr(v, "tiered", False):
            raise rpc.RpcFault(
                f"volume {v.id} is tiered — fetch it local first (volume.tier.fetch)",
                code=grpc.StatusCode.FAILED_PRECONDITION,
            )
        if self._ingest is not None:
            # the superblock rewrite is an IN-PLACE .dat overwrite inside
            # stripe row 0 — route it through the journaled delta-parity
            # path so the inline stripe stays exact instead of silently
            # stale (the end-to-end consumer of Encoder.parity_delta).
            # Under the maintenance lock: a seal (generate/auto-seal) holds
            # it while finalizing, so the rewrite can never land BETWEEN
            # the builder being popped and the shards being renamed — the
            # window where it would bypass the delta path silently.
            import dataclasses

            from seaweedfs_tpu.storage.super_block import ReplicaPlacement

            with self.maintenance_lock(int(req["volume_id"])):
                old = v.super_block.to_bytes()
                new = dataclasses.replace(
                    v.super_block,
                    replica_placement=ReplicaPlacement.parse(req["replication"]),
                ).to_bytes()
                self._ingest.overwrite(
                    int(req["volume_id"]), 0, old, new,
                    mutate=lambda: v.configure_replication(req["replication"]),
                )
        else:
            v.configure_replication(req["replication"])
        self.heartbeat_once()  # the topology keys layouts by (coll, rp, ttl)
        return {"replication": str(v.super_block.replica_placement)}

    def _rpc_needle_ids(self, req: dict, ctx) -> dict:
        """Page through a volume's LIVE needle ids (id, size ascending by id)
        — volume.check.disk diffs these across replicas. The first page also
        carries the volume's tombstoned ids (from the .idx history) so the
        repairer can tell "replica missed the write" from "replica processed
        the delete" and propagate the delete instead of resurrecting."""
        v = self.store.get_volume(int(req["volume_id"]))
        if v is None:
            raise rpc.NotFoundFault(f"volume {req['volume_id']} not found")
        limit = min(int(req.get("limit") or 65536), 65536)
        if req.get("tombstones"):  # tombstone-history page, same resume protocol
            rows, truncated = v.tombstone_history(
                int(req.get("deleted_start_from", 0)), limit
            )
            return {
                "deleted": [{"id": k, "final_dead": d} for k, d in rows],
                "deleted_truncated": truncated,
            }
        entries, truncated = v.needle_entries_page(int(req.get("start_from", 0)), limit)
        return {
            "entries": [{"id": k, "size": s} for k, s in entries],
            "truncated": truncated,
        }

    def _rpc_needle_ts(self, req: dict, ctx) -> dict:
        """Batch append_at_ns lookup (8-byte read per needle, no payload)
        — volume.fsck's -cutoffTimeAgo filter dates orphan candidates with
        one RPC per volume instead of a full ReadNeedle per orphan."""
        v = self.store.get_volume(int(req["volume_id"]))
        if v is None:
            raise rpc.NotFoundFault(f"volume {req['volume_id']} not found")
        ts = v.needle_append_ts([int(n) for n in req.get("needle_ids", [])])
        return {"ts": {str(k): v_ for k, v_ in ts.items()}}

    def _rpc_server_leave(self, req: dict, ctx) -> dict:
        """Stop heartbeating and depart the master's topology
        (volumeServer.leave analog). The process keeps serving reads so an
        operator can drain it; a later stop() is a no-op for the heartbeat."""
        self._leave_cluster()
        return {"left": True}

    # EC surface (SURVEY.md §2.4)

    def _rpc_ec_generate(self, req: dict, ctx) -> dict:
        """VolumeEcShardsGenerate: local .dat+.idx -> 14 shards + .ecx.

        With `inline: true` the shards are finalized from the encode-on-
        write stripe state (resumed from the journaled sidecar after a
        crash) instead of re-encoding the whole sealed .dat — byte-
        identical output, but the bulk of the encode already happened at
        ingest time. Any unusable inline state (policy off, geometry
        mismatch, broken/un-vouchable journal) falls back to the warm
        conversion inside the same call; the response's `mode` says which
        path actually produced the shards."""
        vid = int(req["volume_id"])
        v = self.store.get_volume(vid)
        if v is None:
            raise rpc.NotFoundFault(f"volume {vid} not found")
        kwargs = {}
        if req.get("large_block_size"):
            kwargs["large_block_size"] = int(req["large_block_size"])
        if req.get("small_block_size"):
            kwargs["small_block_size"] = int(req["small_block_size"])
        t0 = time.monotonic()
        info: dict = {"mode": "warm"}
        with self.maintenance_lock(vid):  # never interleave with compact/copy
            if req.get("inline") and self._inline_usable(kwargs):
                info = self._ingest.seal_volume(vid, v.base_path)
                # the SHELL owns this seal's cut-over (ec.encode copies +
                # spreads from here): discard any pre-spread partials so
                # its allocation starts from the full local set
                self._finalize_spread(vid, v.base_path, "shell")
            else:
                if self._ingest is not None:
                    # a warm generate supersedes any inline partial state:
                    # leftovers must not shadow the fresh shard set — base
                    # included, so journaled state from before a restart
                    # (no live builder) is scrubbed from disk too
                    self._ingest.discard(vid, v.base_path)
                stripe.write_ec_files(
                    v.base_path, encoder=self.store.encoder, **kwargs
                )
            stripe.write_sorted_file_from_idx(v.base_path)
        stats.EcEncodeSeconds.observe(time.monotonic() - t0)
        stats.EcEncodeBytes.inc(os.path.getsize(v.base_path + ".dat"))
        total = stripe.geometry_from_info(
            stripe.read_ec_info(v.base_path)
        ).total_shards
        return {
            "shard_ids": list(range(total)),
            "mode": info.get("mode", "warm"),
            "inline_rows": int(info.get("rows_inline", 0)),
            "delta_updates": int(info.get("delta_updates", 0)),
        }

    def _inline_usable(self, kwargs: dict) -> bool:
        """Inline finalize serves the request only when the policy is on
        and any explicitly-requested geometry matches what the builders
        encoded with — a mismatched request warm-encodes with ITS sizes."""
        if self._ingest is None:
            return False
        if kwargs.get("large_block_size", self._ingest.large) != self._ingest.large:
            return False
        if kwargs.get("small_block_size", self._ingest.small) != self._ingest.small:
            return False
        return True

    def _auto_inline_seal(self, vid: int) -> None:
        """Threshold auto-seal (WEEDTPU_INLINE_EC_SEAL_BYTES): freeze the
        volume, finalize its inline stripe (warm fallback inside
        seal_volume), write the sorted index, and mount the EC volume —
        the volume is born EC'd with no operator in the loop. Reads keep
        serving from the now read-only volume; spreading shards across
        the cluster stays the shell's (ec.encode) cut-over decision."""
        sealed = False
        froze = False  # only roll back a freeze THIS seal applied — the
        # early-return guard must never un-freeze a volume an operator
        # (or the shell's ec.encode) made read-only
        v = None
        try:
            with self.maintenance_lock(vid):
                v = self.store.get_volume(vid)
                if v is None or v.read_only or getattr(v, "tiered", False):
                    return
                with v._lock:
                    v.read_only = True
                    froze = True
                t0 = time.monotonic()
                seal_info = self._ingest.seal_volume(vid, v.base_path)
                stripe.write_sorted_file_from_idx(v.base_path)
                # spread cut-over BEFORE the local mount: committed parity
                # shards mount on their planned holders and vanish from
                # this node's discovery set — the volume is born spread,
                # the owner never hosts all k+m (broken/unplanned spreads
                # leave everything local exactly as before)
                spread_done = self._finalize_spread(
                    vid, v.base_path, seal_info.get("mode", "warm")
                )
                self.store.mount_ec_volume(vid, v.base_path)
                stats.EcEncodeSeconds.observe(time.monotonic() - t0)
                stats.EcEncodeBytes.inc(os.path.getsize(v.base_path + ".dat"))
                if spread_done:
                    trace_mod.annotate(spread=spread_done)
                sealed = True
            self.heartbeat_once()
        except Exception:  # noqa: BLE001 — auto-seal is opportunistic: the
            # volume must come back writable and the trigger re-arms, so a
            # transient failure costs a retry at the next threshold write
            pass
        finally:
            if not sealed and self._ingest is not None:
                if froze:
                    v.read_only = False
                self._ingest.seal_failed(vid)

    def _rpc_ec_copy(self, req: dict, ctx) -> dict:
        """VolumeEcShardsCopy: PULL the named shards (+index files) from the
        source node into local storage (streaming file copy)."""
        vid = int(req["volume_id"])
        collection = req.get("collection", "")
        shard_ids = [int(s) for s in req.get("shard_ids", [])]
        src = req["source_data_node"]  # grpc address host:port
        base = self._base_path_for(vid, collection)
        with rpc.RpcClient(src) as c:
            names = [stripe.to_ext(s) for s in shard_ids]
            if req.get("copy_ecx_file", True):
                names += _EC_EXTS
            for name in names:
                try:
                    chunks = c.stream(
                        VOLUME_SERVICE,
                        "VolumeEcShardFileCopy",
                        {"volume_id": vid, "collection": collection, "ext": name},
                    )
                    tmp = base + name + ".cpy"
                    try:
                        with open(tmp, "wb") as f:
                            for chunk in chunks:
                                f.write(chunk)
                            f.flush()
                            os.fsync(f.fileno())
                        os.replace(tmp, base + name)
                    finally:
                        if os.path.exists(tmp):
                            os.remove(tmp)
                except Exception:
                    if name in (".ecj", ".eci"):  # optional files
                        continue
                    raise
        return {}

    # -- inline-ingest parity spreading (WEEDTPU_INLINE_EC_SPREAD) -----------

    def _spread_factory(self, vid: int, base: str):
        """Build a SpreadSession for one ingesting volume: ask the master
        for the live topology, run the failure-domain planner over it,
        and tee each parity shard at its planned eventual holder. None
        (no spreading, seal stays fully local) when the cluster has no
        viable targets or the master is unreachable."""
        from seaweedfs_tpu.ec import placement
        from seaweedfs_tpu.ec import spread as spread_mod
        from seaweedfs_tpu.ec.shard_bits import ShardBits
        from seaweedfs_tpu.storage.store import parse_base_name

        topo = self._master_query("VolumeList", {})
        nodes: list[dict] = []
        for dc, racks in (topo.get("data_centers") or {}).items():
            for rack, nds in racks.items():
                for nd in nds:
                    nodes.append(
                        {
                            "url": nd["url"],
                            "grpc": f"{nd['url'].rsplit(':', 1)[0]}:{nd['grpc_port']}",
                            "data_center": dc,
                            "rack": rack,
                            "ec_load": sum(
                                ShardBits(e.get("shard_bits", 0)).shard_id_count()
                                for e in nd.get("ec_shards", [])
                            ),
                        }
                    )
        enc = self.store.encoder
        targets = placement.plan_parity_targets(
            nodes,
            self.url,
            enc.data_shards,
            enc.total_shards,
            cap_override=int(config.env("WEEDTPU_PLACEMENT_MAX_PER_DOMAIN")),
            load_of=lambda n: n["ec_load"],
        )
        if not targets:
            return None
        parsed = parse_base_name(os.path.basename(base))
        return spread_mod.SpreadSession(
            vid,
            parsed[0] if parsed else "",
            base,
            {sid: n["grpc"] for sid, n in targets.items()},
            self._peer_pool,
            enc.data_shards,
            self._ingest.large,
        )

    def _finalize_spread(self, vid: int, base: str, mode: str) -> list[int]:
        """Seal cut-over for a pre-spread volume: commit each target's
        parity partial (tail ship + CRC verify + rename + mount there)
        and unlink the owner's local copy of every committed shard, so
        the subsequent local mount hosts only the remaining shards.
        Inline/resumed seals only — a warm fallback re-encoded from
        scratch, so its spread partials are aborted instead."""
        if self._ingest is None:
            return []
        session = self._ingest.take_spread(vid)
        if session is None:
            return []
        if mode not in ("inline", "resumed"):
            session.abort()
            return []
        info = stripe.read_ec_info(base)
        recorded = (info or {}).get("shard_crc32")
        total = stripe.geometry_from_info(info).total_shards
        if not isinstance(recorded, list) or len(recorded) != total:
            session.abort()  # nothing to CRC-verify commits against
            return []
        shard_size = scrub_mod.expected_shard_size(info)
        done = session.finalize(self.grpc_address, recorded, shard_size)
        for s in done:
            try:
                os.unlink(stripe.shard_file_name(base, s))
            except OSError:
                pass  # already absent: the target still hosts it
        return done

    def _rpc_ec_partial_write(self, req: dict, ctx) -> dict:
        """VolumeEcShardPartialWrite: land one absolute-offset window of
        a parity shard being spread to this node into `<base>.ecNN.inp`
        (invisible to shard discovery until the commit renames it)."""
        from seaweedfs_tpu.ec.ingest import part_path

        vid = int(req["volume_id"])
        shard = int(req["shard_id"])
        offset = int(req.get("offset", 0))
        raw = req.get("data") or ""
        data = (
            base64.b64decode(raw) if isinstance(raw, str) else bytes(raw)
        )
        base = self._base_path_for(vid, req.get("collection", ""))
        p = part_path(base, shard)
        mode = "r+b" if os.path.exists(p) else "w+b"
        with open(p, mode) as f:
            f.seek(offset)
            f.write(data)
        return {}

    def _rpc_ec_spread_commit(self, req: dict, ctx) -> dict:
        """VolumeEcShardSpreadCommit: finalize (or, with size=0, discard)
        a spread parity partial. The bytes on disk must CRC32-match the
        owner's .eci record BEFORE the rename — a torn ship sequence
        must never mount as a real shard."""
        from seaweedfs_tpu.ec import spread as spread_mod
        from seaweedfs_tpu.ec.ingest import part_path

        vid = int(req["volume_id"])
        shard = int(req["shard_id"])
        size = int(req.get("size", 0))
        collection = req.get("collection", "")
        base = self._base_path_for(vid, collection)
        p = part_path(base, shard)
        if size <= 0:
            try:
                os.unlink(p)
            except OSError:
                pass
            return {"mounted": False}
        if not os.path.exists(p):
            raise rpc.NotFoundFault(f"no spread partial for {vid}.{shard:02d}")
        with self.maintenance_lock(vid):
            with open(p, "r+b") as f:
                f.truncate(size)
                f.flush()
                os.fsync(f.fileno())
            crc = spread_mod.local_crc(p)
            if crc != (int(req.get("crc32", 0)) & 0xFFFFFFFF):
                os.unlink(p)  # torn spread: the owner keeps its local copy
                raise rpc.RpcFault(
                    f"spread partial {vid}.{shard:02d} CRC mismatch",
                    code=grpc.StatusCode.FAILED_PRECONDITION,
                )
            src = req.get("source_data_node") or ""
            if src:
                self._ensure_ec_index_files(vid, collection, base, [src])
            os.replace(p, stripe.shard_file_name(base, shard))
            mounted = False
            if req.get("mount"):
                ev = self.store.get_ec_volume(vid)
                if ev is not None:
                    mounted = ev.mount_local_shard(shard)
                else:
                    self.store.mount_ec_volume(vid, base)
                    mounted = True
        if mounted:
            try:
                self.heartbeat_once()  # this node is a holder NOW
            except Exception:  # noqa: BLE001 — next beat carries it
                pass
        return {"mounted": mounted}

    def _rpc_ec_file_copy(self, req: dict, ctx):
        """Stream one local EC-related file (server side of ShardsCopy and
        of VolumeCopy's .dat/.idx pull). Streaming .dat/.idx holds the
        volume's maintenance mutex so a concurrent compact can never swap
        the file mid-stream (the destination would get a torn copy)."""
        vid = int(req["volume_id"])
        base = self._base_path_for(vid, req.get("collection", ""))
        path = base + req["ext"]
        lock = self.maintenance_lock(vid) if req["ext"] in (".dat", ".idx") else None
        if lock is not None:
            lock.acquire()
        try:
            if not os.path.exists(path):
                raise rpc.NotFoundFault(f"{path} not found")
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(_COPY_CHUNK)
                    if not chunk:
                        break
                    yield chunk
        finally:
            if lock is not None:
                lock.release()

    def _rpc_ec_rebuild(self, req: dict, ctx) -> dict:
        """VolumeEcShardsRebuild: reconstruct missing shards.

        Default mode reads >=10 LOCAL survivors (the pre-distributed shape:
        the shell first copies every survivor here). With `remote: true`
        this node becomes the rebuild target without any bulk pre-copy:
        survivors it lacks stream in over VolumeEcShardSlabRead while the
        decode runs — the network-overlapped distributed path."""
        vid = int(req["volume_id"])
        collection = req.get("collection", "")
        base = self._base_path_for(vid, collection)
        t0 = time.monotonic()
        with trace_mod.ensure("rebuild.run", klass="maint"):
            trace_mod.annotate(volume=vid, remote=bool(req.get("remote")))
            if not req.get("remote"):
                rebuilt = stripe.rebuild_ec_files(
                    base, encoder=stripe.encoder_for_base(base, self.store.encoder)
                )
                stats.EcRebuildSeconds.observe(time.monotonic() - t0)
                return {"rebuilt_shard_ids": rebuilt}
            resp = self._ec_rebuild_remote(vid, collection, base, req)
            trace_mod.annotate(
                mode=resp.get("mode"), wire_bytes=resp.get("wire_bytes")
            )
        stats.EcRebuildSeconds.observe(time.monotonic() - t0)
        return resp

    def _ec_rebuild_remote(
        self, vid: int, collection: str, base: str, req: dict
    ) -> dict:
        """Distributed rebuild: fetch survivors from peer holders through
        the triple-overlap pipeline (network prefetch / staging fill /
        device decode) and regenerate the missing `.ecNN` files locally,
        CRC-verified against the .eci record. Holder failover happens
        inside each RemoteSlabSource mid-rebuild; this method only decides
        WHO is a survivor and wires the transports."""
        with self.maintenance_lock(vid):
            # a rebuild wants the freshest holder map, not a TTL-stale one:
            # routing a GB-scale fetch at a node that dropped its shards
            # costs a failover round per batch window
            self._invalidate_shard_locations(vid)
            locs = self._lookup_shard_locations(vid)
            local = set(stripe.find_local_shards(base))
            present = sorted(local | set(locs))
            enc = stripe.encoder_for_base(base, self.store.encoder)
            missing = [s for s in range(enc.total_shards) if s not in present]
            if not missing:
                return {"rebuilt_shard_ids": []}
            if len(present) < enc.data_shards:
                raise rpc.RpcFault(
                    f"cannot rebuild volume {vid}: only {len(present)} survivors "
                    f"reachable, need {enc.data_shards}",
                    code=grpc.StatusCode.FAILED_PRECONDITION,
                )
            holders = sorted({a for addrs in locs.values() for a in addrs})
            self._ensure_ec_index_files(vid, collection, base, holders)
            shard_size, holder_caps = self._resolve_shard_size(
                vid, base, local, holders
            )
            tuning = {}
            if int(req.get("buffer_size") or 0) > 0:
                tuning["buffer_size"] = int(req["buffer_size"])
            if int(req.get("max_batch_bytes") or 0) > 0:
                tuning["max_batch_bytes"] = int(req["max_batch_bytes"])
            if int(req.get("prefetch_batches") or 0) > 0:
                tuning["prefetch_batches"] = int(req["prefetch_batches"])
            chosen = present[: enc.data_shards]
            remote_needed = [s for s in chosen if s not in local]
            resp = {
                "local_survivors": sorted(local & set(chosen)),
                "remote_survivors": remote_needed,
            }
            mode_req = str(req.get("trace_mode") or "").strip().lower()
            trace_mode = (
                mode_req if mode_req in ("on", "off", "auto") else self._trace_repair
            )
            trace_fallback = ""
            trace_wasted = 0  # bytes an aborted trace attempt already moved
            if trace_mode != "off" and remote_needed:
                # trace-repair first: every holder ships |missing| projected
                # rows for its whole survivor group instead of full slabs.
                # ANY failure (incapable peer, stale holder map, mid-rebuild
                # kill, torn stream) lands on the full-slab path below —
                # trace is a bandwidth optimization, never an availability
                # trade. `on` attempts projections wherever holders are
                # capable; `auto` additionally declines when the plan would
                # not actually move fewer bytes than the slabs it replaces
                # (fully-spread placements with several missing shards).
                groups, labels, plan_reason = self._plan_trace_groups(
                    vid, base, chosen, missing, locs, holder_caps, local, enc
                )
                if groups is not None and trace_mode == "auto":
                    remote_groups = sum(1 for g in groups if g.holder != "local")
                    if remote_groups * len(missing) >= len(remote_needed):
                        for g in groups:
                            g.close()
                        groups, labels = None, []
                        plan_reason = (
                            f"no bandwidth win: {remote_groups} holder "
                            f"groups x {len(missing)} missing rows >= "
                            f"{len(remote_needed)} survivor slabs"
                        )
                if groups is None:
                    trace_fallback = plan_reason
                else:
                    try:
                        try:
                            rebuilt = stripe.rebuild_ec_files_from_projections(
                                base,
                                groups,
                                shard_size,
                                missing,
                                encoder=enc,
                                **tuning,
                            )
                            wire = sum(g.bytes_fetched for g in groups)
                        finally:
                            for g in groups:
                                g.close()
                        stats.EcRepairNetworkBytes.labels("trace").inc(wire)
                        stats.EcRebuildRemoteBytes.inc(wire)
                        resp.update(
                            rebuilt_shard_ids=rebuilt,
                            wire_bytes=wire,
                            mode="trace",
                            trace_groups=labels,
                            failed_over=[],
                            trace_fallback="",
                        )
                        return resp
                    except Exception as e:  # noqa: BLE001 — fall back to slabs
                        trace_fallback = f"{type(e).__name__}: {e}"[:200]
                        # the aborted attempt's bytes DID cross the network:
                        # count them, or scraped trace-vs-slab comparisons
                        # would flatter trace exactly when fallbacks happen
                        trace_wasted = sum(g.bytes_fetched for g in groups)
                        if trace_wasted:
                            stats.EcRepairNetworkBytes.labels("trace").inc(
                                trace_wasted
                            )
                            stats.EcRebuildRemoteBytes.inc(trace_wasted)
            # full-slab path: the capability/chaos fallback and the
            # trace_mode=off shape — striped RemoteSlabSource per survivor.
            # fetch workers are RTT/IO-bound (they sleep on peer streams),
            # so size the pool above the survivor count: with prefetch
            # running `prefetch_batches` windows ahead, a tight pool would
            # serialize the very round-trips the pipeline exists to hide
            executor = futures.ThreadPoolExecutor(
                max_workers=EC_REBUILD_FETCH_WORKERS,
                thread_name_prefix=f"ec-rebuild-{vid}",
            )
            sources: dict[int, stripe.SlabSource] = {}
            try:
                for s in present:
                    if s in local:
                        sources[s] = stripe.LocalSlabSource(
                            stripe.shard_file_name(base, s)
                        )
                sources.update(
                    self._remote_slab_sources(
                        vid, [s for s in present if s not in local], executor
                    )
                )
                rebuilt = stripe.rebuild_ec_files_from_sources(
                    base,
                    sources,
                    shard_size,
                    encoder=enc,
                    missing=missing,
                    **tuning,
                )
                wire = sum(
                    src.bytes_fetched
                    for src in sources.values()
                    if isinstance(src, stripe.RemoteSlabSource)
                )
            finally:
                for src in sources.values():
                    src.close()
                executor.shutdown(wait=False, cancel_futures=True)
            if wire:
                stats.EcRepairNetworkBytes.labels("slab").inc(wire)
                stats.EcRebuildRemoteBytes.inc(wire)
            failed_over = [
                f"{src.shard_id}:{addr}"
                for src in sources.values()
                if isinstance(src, stripe.RemoteSlabSource)
                for addr in src.failovers
            ]
            resp.update(
                rebuilt_shard_ids=rebuilt,
                failed_over=failed_over,
                # total bytes THIS rebuild moved, aborted trace attempt
                # included — wire_bytes is a network-cost number, not a
                # successful-path number
                wire_bytes=wire + trace_wasted,
                mode="slab" if remote_needed else "local",
                trace_groups=[],
                trace_fallback=trace_fallback,
            )
            return resp

    def _rpc_ec_rebuild_batch(self, req: dict, ctx) -> dict:
        """VolumeEcShardsRebuildBatch: this node rebuilds MANY volumes'
        missing shards in one call — the fleet scheduler's dispatch unit.
        Each volume is planned like a single remote rebuild (fresh holder
        map, survivor choice, shard-size preflight, slab sources through
        the admission-gated bulk read), then same-signature volumes fuse
        into shared width-packed decode pipelines
        (`stripe.rebuild_ec_files_batch`). Rebuilt shards mount here and
        the delta heartbeats immediately. Per-volume failures are soft
        (reported in `results[].error`); the call only faults wholesale
        on malformed requests."""
        vols = list(req.get("volumes") or [])
        if not vols:
            raise rpc.RpcFault(
                "volumes required", code=grpc.StatusCode.INVALID_ARGUMENT
            )
        tuning = {}
        if int(req.get("buffer_size") or 0) > 0:
            tuning["buffer_size"] = int(req["buffer_size"])
        if int(req.get("max_batch_bytes") or 0) > 0:
            tuning["max_batch_bytes"] = int(req["max_batch_bytes"])
        t0 = time.monotonic()
        jobs: list[dict] = []
        meta: dict[str, dict] = {}  # base -> {vid, collection}
        errors: dict[int, str] = {}
        executor = futures.ThreadPoolExecutor(
            max_workers=EC_REBUILD_FETCH_WORKERS,
            thread_name_prefix="ec-rebuild-batch",
        )
        with ExitStack() as locks, trace_mod.ensure("rebuild.run", klass="maint"):
            trace_mod.annotate(batch=len(vols))
            # per-volume maintenance locks, vid-sorted so concurrent
            # batches can never deadlock on each other — but PLANNING runs
            # in request order below: the scheduler sent the batch in
            # priority order, and job order becomes the block order of the
            # fused dispatch (2-missing blocks before 1-missing)
            for v in sorted(vols, key=lambda d: int(d["volume_id"])):
                locks.enter_context(self.maintenance_lock(int(v["volume_id"])))
            for v in vols:
                vid = int(v["volume_id"])
                collection = v.get("collection", "")
                sources: dict[int, stripe.SlabSource] = {}
                try:
                    base = self._base_path_for(vid, collection)
                    self._invalidate_shard_locations(vid)
                    locs = self._lookup_shard_locations(vid)
                    local = set(stripe.find_local_shards(base))
                    present = sorted(local | set(locs))
                    enc = stripe.encoder_for_base(base, self.store.encoder)
                    missing = [
                        s for s in range(enc.total_shards) if s not in present
                    ]
                    if not missing:
                        meta.setdefault(base, {"vid": vid, "collection": collection})
                        jobs.append(
                            {"base": base, "sources": {}, "shard_size": 0,
                             "missing": [], "encoder": enc}
                        )
                        continue
                    if len(present) < enc.data_shards:
                        errors[vid] = (
                            f"only {len(present)} survivors reachable, "
                            f"need {enc.data_shards}"
                        )
                        continue
                    holders = sorted({a for aa in locs.values() for a in aa})
                    self._ensure_ec_index_files(vid, collection, base, holders)
                    shard_size, _caps = self._resolve_shard_size(
                        vid, base, local, holders
                    )
                    chosen = present[: enc.data_shards]
                    for s in chosen:
                        if s in local:
                            sources[s] = stripe.LocalSlabSource(
                                stripe.shard_file_name(base, s)
                            )
                    sources.update(
                        self._remote_slab_sources(
                            vid, [s for s in chosen if s not in local], executor
                        )
                    )
                    meta[base] = {"vid": vid, "collection": collection}
                    jobs.append(
                        {
                            "base": base,
                            "sources": sources,
                            "shard_size": shard_size,
                            "missing": missing,
                            "encoder": enc,
                        }
                    )
                except Exception as e:  # noqa: BLE001 — soft per-volume
                    # sources opened before the failure (local survivor
                    # handles) must not leak fds: the post-run cleanup
                    # only reaches jobs that were actually appended
                    for src in sources.values():
                        src.close()
                    errors[vid] = f"{type(e).__name__}: {e}"[:300]
            try:
                res = stripe.rebuild_ec_files_batch(jobs, **tuning)
            finally:
                for job in jobs:
                    for src in job["sources"].values():
                        src.close()
                executor.shutdown(wait=False, cancel_futures=True)
        results: list[dict] = []
        total_wire = 0
        for job in jobs:
            base = job["base"]
            m = meta[base]
            wire = sum(
                src.bytes_fetched
                for src in job["sources"].values()
                if isinstance(src, stripe.RemoteSlabSource)
            )
            total_wire += wire
            rebuilt = res["rebuilt"].get(base)
            err = res["errors"].get(base, "")
            if rebuilt and not err:
                try:
                    ev = self.store.get_ec_volume(m["vid"])
                    if ev is not None:
                        for s in rebuilt:
                            ev.mount_local_shard(s)
                    else:
                        self.store.mount_ec_volume(m["vid"], base)
                except Exception as e:  # noqa: BLE001 — rebuilt but dark
                    err = f"mount failed: {e}"[:300]
            results.append(
                {
                    "volume_id": m["vid"],
                    "rebuilt_shard_ids": rebuilt or [],
                    "error": err,
                    "wire_bytes": wire,
                }
            )
        for vid, err in errors.items():
            results.append(
                {"volume_id": vid, "rebuilt_shard_ids": [], "error": err,
                 "wire_bytes": 0}
            )
        if total_wire:
            stats.EcRepairNetworkBytes.labels("slab").inc(total_wire)
            stats.EcRebuildRemoteBytes.inc(total_wire)
        stats.EcRebuildSeconds.observe(time.monotonic() - t0)
        try:
            self.heartbeat_once()  # rebuilt shards are holders NOW
        except Exception:  # noqa: BLE001 — masters may be mid-chaos
            pass
        vid_of_base = {b: m["vid"] for b, m in meta.items()}
        return {
            "results": sorted(results, key=lambda r: r["volume_id"]),
            "wire_bytes": total_wire,
            "dispatch_groups": res["dispatch_groups"],
            "signature_groups": res.get("signature_groups", 0),
            "volumes_fused": res.get("volumes_fused", 0),
            "block_order": [
                vid_of_base[b] for b in res.get("block_order", [])
                if b in vid_of_base
            ],
        }

    def _plan_trace_groups(
        self,
        vid: int,
        base: str,
        chosen: list[int],
        missing: list[int],
        locs: dict[int, list[str]],
        holder_caps: dict[str, set],
        local: set[int],
        enc=None,
    ):
        """Group the chosen survivors onto projection-capable holders:
        -> (groups, labels, "") on success, (None, [], reason) when trace
        repair cannot be planned (capability negotiation's fallback).

        Greedy minimum-holder cover: each round assigns the holder that
        covers the most still-unassigned remote survivors (ties broken by
        address for determinism) — fewer groups = fewer projected-row
        streams = fewer moved bytes, since the wire cost is
        groups x |missing| x shard bytes. The target's own survivors form
        a zero-wire local group running the SAME projection math."""
        remote_needed = [s for s in chosen if s not in local]
        coverable: dict[str, set[int]] = {}
        for s in remote_needed:
            for addr in locs.get(s, ()):
                if "slab_projection" in holder_caps.get(addr, ()):
                    coverable.setdefault(addr, set()).add(s)
        uncovered = set(remote_needed) - {
            s for sids in coverable.values() for s in sids
        }
        if uncovered:
            return None, [], (
                f"survivors {sorted(uncovered)} have no projection-capable "
                "holder"
            )
        plan = (enc or self.store.encoder).repair_projection_plan(chosen, missing)
        rows = len(missing)
        assign: dict[str, list[int]] = {}
        remaining = set(remote_needed)
        while remaining:
            addr = max(
                coverable,
                key=lambda a: (len(coverable[a] & remaining), a),
            )
            got = sorted(coverable[addr] & remaining)
            if not got:  # unreachable given the cover check above
                return None, [], "trace planner could not cover survivors"
            assign[addr] = got
            remaining -= set(got)
        groups: list[stripe.SlabSource] = []
        labels: list[str] = []
        try:
            local_chosen = sorted(local & set(chosen))
            if local_chosen:
                import numpy as np

                groups.append(
                    stripe.LocalProjectionSource(
                        [stripe.shard_file_name(base, s) for s in local_chosen],
                        np.stack([plan[s] for s in local_chosen], axis=1),
                        enc or self.store.encoder,
                    )
                )
                labels.append("local=" + "+".join(str(s) for s in local_chosen))
            for addr in sorted(assign):
                sids = assign[addr]
                terms = [
                    {
                        "shard_id": s,
                        "coeffs": base64.b64encode(plan[s].tobytes()).decode(),
                    }
                    for s in sids
                ]
                groups.append(
                    stripe.TraceSlabSource(
                        addr,
                        sids,
                        rows,
                        self._projection_fetcher(addr, vid, terms, rows),
                    )
                )
                labels.append(f"{addr}=" + "+".join(str(s) for s in sids))
        except Exception as e:  # noqa: BLE001 — a bad group must not leak the rest
            for g in groups:
                g.close()
            return None, [], f"trace group setup failed: {e}"
        return groups, labels, ""

    def _projection_fetcher(self, addr: str, vid: int, terms: list, rows: int):
        """Transport closure for one holder group: the projection mode of
        the CRC-checked slab RPC. Short return on EOF (the source
        zero-fills); any fault propagates so the rebuild falls back to
        full slabs rather than failing over inside the group (the group's
        shards live on exactly this holder)."""

        def fetch(offset: int, size: int) -> bytes:
            import numpy as np

            frames = self._peer_pool.get(addr).stream(
                VOLUME_SERVICE,
                "VolumeEcShardSlabRead",
                {
                    "volume_id": vid,
                    "offset": offset,
                    "size": size,
                    "projection": terms,
                    "projection_rows": rows,
                },
                timeout=EC_SLAB_READ_TIMEOUT,
            )
            # each frame is its own row-major (rows, cols_i) block —
            # restitch column-wise so the caller sees one row-major
            # (rows, sum cols_i) window
            blocks = []
            got = 0
            for frame in frames:
                chunk = rpc.crc_unframe(frame)
                got += len(chunk)
                if got > size * rows:
                    raise IOError(
                        f"projection group@{addr}: stream over-answered "
                        f"({got} > {size * rows})"
                    )
                if len(chunk) % rows:
                    raise IOError(
                        f"projection group@{addr}: frame of {len(chunk)} "
                        f"bytes is not {rows} rows"
                    )
                blocks.append(
                    np.frombuffer(chunk, dtype=np.uint8).reshape(rows, -1)
                )
            if not blocks:
                return b""
            if len(blocks) == 1:
                return blocks[0].tobytes()
            return np.concatenate(blocks, axis=1).tobytes()

        return fetch

    def _ensure_ec_index_files(
        self, vid: int, collection: str, base: str, holders: list[str]
    ) -> None:
        """A rebuild target that never held this volume lacks .ecx/.ecj/.eci;
        pull them from any holder so the regenerated shards are mountable
        and CRC-verifiable. .ecj/.eci are optional upstream, so only a
        missing .ecx is fatal."""
        needed = [ext for ext in _EC_EXTS if not os.path.exists(base + ext)]
        if not needed:
            return
        errs: list[str] = []
        for ext in needed:
            done = False
            for addr in holders:
                try:
                    chunks = self._peer_pool.get(addr).stream(
                        VOLUME_SERVICE,
                        "VolumeEcShardFileCopy",
                        {"volume_id": vid, "collection": collection, "ext": ext},
                    )
                    tmp = base + ext + ".cpy"
                    try:
                        with open(tmp, "wb") as f:
                            for chunk in chunks:
                                f.write(chunk)
                            f.flush()
                            os.fsync(f.fileno())
                        os.replace(tmp, base + ext)
                    finally:
                        if os.path.exists(tmp):
                            os.remove(tmp)
                    done = True
                    break
                except Exception as e:  # noqa: BLE001 — try the next holder
                    errs.append(f"{addr}{ext}: {e}")
            if not done and ext == ".ecx":
                raise rpc.RpcFault(
                    f"volume {vid}: no holder could supply .ecx: {'; '.join(errs)[:400]}"
                )

    def _resolve_shard_size(
        self, vid: int, base: str, local: set[int], holders: list[str]
    ) -> int:
        """Uniform shard length from local survivors and holder
        VolumeStatus reports — and the remote mirror of the local path's
        survivors-agree-on-length preflight: a truncated survivor would
        otherwise zero-fill past its EOF exactly like a legitimate tail
        and decode into silently-wrong shards (the .eci CRC gate only
        fires after the whole volume has streamed, and only when CRCs
        were recorded). Returns (shard_size, capabilities-by-holder) —
        the same status round-trip feeds the trace-repair planner, so
        capability negotiation costs zero extra RPCs."""
        sizes: dict[str, int] = {}
        caps: dict[str, set[str]] = {}
        for s in local:
            sizes[f"local:.ec{s:02d}"] = os.path.getsize(
                stripe.shard_file_name(base, s)
            )
        last: Exception | None = None
        for addr in holders:
            try:
                st = self._peer_pool.get(addr).call(
                    VOLUME_SERVICE, "VolumeStatus", {"volume_id": vid}, timeout=10
                )
                if st.get("kind") != "ec":
                    continue
                caps[addr] = set(st.get("capabilities") or ())
                per_shard = st.get("shard_file_sizes") or {}
                if per_shard:
                    for k, v in per_shard.items():
                        sizes[f"{addr}:.ec{int(k):02d}"] = int(v)
                elif int(st.get("shard_size", 0)) > 0:
                    # pre-per-shard peers: their max is the best we get
                    sizes[addr] = int(st["shard_size"])
            except Exception as e:  # noqa: BLE001 — a dead holder reports nothing
                last = e
        if not sizes:
            raise rpc.RpcFault(
                f"volume {vid}: could not learn shard size from any holder"
                + (f" (last error: {last})" if last else "")
            )
        if len(set(sizes.values())) != 1:
            raise rpc.RpcFault(
                f"volume {vid}: survivors disagree on shard length: {sizes} "
                "— truncated shard?",
                code=grpc.StatusCode.FAILED_PRECONDITION,
            )
        return next(iter(sizes.values())), caps

    def _remote_slab_sources(
        self, vid: int, shard_ids: list[int], executor
    ) -> dict[int, stripe.RemoteSlabSource]:
        """RemoteSlabSource per shard, wired to the CRC-checked bulk slab
        RPC over pooled peer channels, with holder refresh re-asking the
        master after an invalidation."""
        locs = self._lookup_shard_locations(vid)

        def fetch_for(sid: int):
            def fetch(addr: str, offset: int, size: int) -> bytes:
                # NOTE: no _peer_pool.invalidate here — the pooled channel
                # is shared by every shard's concurrent slab streams to
                # this holder, and closing it over ONE stripe failure
                # (timeout, CRC mismatch) would cancel the other nine
                # mid-flight and cascade one transient error into a
                # whole-holder failover for all sources. The source marks
                # the holder dead for ITSELF; genuinely-broken channels
                # are redialed by the degraded-read path's invalidation.
                try:
                    data = self._fetch_slab(addr, vid, sid, offset, size)
                except Exception:
                    self._note_peer_failure(addr)
                    raise
                self._note_peer_success(addr)
                return data

            return fetch

        def refresh_for(sid: int):
            def refresh():
                self._invalidate_shard_locations(vid)
                return self._lookup_shard_locations(vid).get(sid, ())

            return refresh

        return {
            sid: stripe.RemoteSlabSource(
                sid,
                locs.get(sid, ()),
                fetch_for(sid),
                executor=executor,
                refresh_holders=refresh_for(sid),
                fetch_deadline=EC_SLAB_READ_TIMEOUT,
            )
            for sid in shard_ids
            if locs.get(sid)
        }

    def _fetch_slab(
        self, addr: str, vid: int, shard_id: int, offset: int, size: int
    ) -> bytes:
        """One bulk range via VolumeEcShardSlabRead: CRC-verified chunks,
        short return on EOF (the caller zero-fills, like a local read)."""
        frames = self._peer_pool.get(addr).stream(
            VOLUME_SERVICE,
            "VolumeEcShardSlabRead",
            {
                "volume_id": vid,
                "shard_id": shard_id,
                "offset": offset,
                "size": size,
            },
            timeout=EC_SLAB_READ_TIMEOUT,
        )
        parts: list[bytes] = []
        got = 0
        for frame in frames:
            chunk = rpc.crc_unframe(frame)
            got += len(chunk)
            if got > size:
                raise IOError(
                    f"shard {shard_id}@{addr}: slab stream over-answered "
                    f"({got} > {size})"
                )
            parts.append(chunk)
        return b"".join(parts)

    def _rpc_ec_convert(self, req: dict, ctx) -> dict:
        """VolumeEcShardsConvert: re-encode this node's shard set of one
        EC volume into a different registered code family WITHOUT a
        decode->re-encode round trip — data blocks regroup, new parity is
        a GF projection of surviving shards, and the staged target
        (<base>.cv.*) is built while the OLD geometry keeps serving.
        Rides the per-volume maintenance lock (never interleaves with
        compact/copy/generate), journals crash-resumable progress to the
        .ecc sidecar, and — with `cutover: true` — re-verifies the staged
        bytes on disk against the new .eci before atomically retiring the
        old geometry and remounting."""
        vid = int(req["volume_id"])
        collection = req.get("collection", "")
        base = self._base_path_for(vid, collection)
        family = str(req.get("target_family") or "")
        t0 = time.monotonic()
        kwargs: dict = {}
        if int(req.get("max_batch_bytes") or 0) > 0:
            kwargs["max_batch_bytes"] = int(req["max_batch_bytes"])
        if int(req.get("journal_bytes") or 0) > 0:
            kwargs["journal_bytes"] = int(req["journal_bytes"])
        with self.maintenance_lock(vid), trace_mod.ensure(
            "convert.run", klass="maint"
        ):
            trace_mod.annotate(volume=vid, family=family)
            if not stripe.find_local_shards(base):
                raise rpc.NotFoundFault(f"no local shards for volume {vid}")
            try:
                res = convert_mod.convert_ec_files(
                    base, family, encoder=self.store.encoder, **kwargs
                )
                if req.get("cutover") and res["mode"] != "noop":
                    # retire the old geometry under the same lock: the
                    # serving handles close, the staged set swaps in
                    # (.eci first — a crash window refuses to mount
                    # rather than misreads), and the volume remounts as
                    # its new geometry. Reads block only for the swap.
                    self.store.unmount_ec_volume(vid)
                    try:
                        if res["mode"] != "cutover":
                            convert_mod.cutover(base)
                    except BaseException:
                        # the swap did not happen (staged state torn/gone
                        # between stage and cut-over): the intact OLD
                        # geometry must come back into serving rather
                        # than leave a healthy volume dark until restart
                        try:
                            self.store.mount_ec_volume(vid, base)
                        except Exception:  # noqa: BLE001 — a half-swapped
                            pass  # set refuses to mount; resume heals it
                        raise
                    self.store.mount_ec_volume(vid, base)
            except (convert_mod.ConversionError, ValueError) as e:
                raise rpc.RpcFault(
                    f"convert volume {vid} -> {family!r}: {e}",
                    code=grpc.StatusCode.FAILED_PRECONDITION,
                )
        stats.EcConvertSeconds.observe(time.monotonic() - t0)
        try:
            self.heartbeat_once()  # shard-id delta (e.g. 14 -> 24 shards)
        except Exception:  # noqa: BLE001 — master down: next beat carries it
            pass
        return {
            "shard_ids": res["shard_ids"],
            "src_family": res["src_family"],
            "target_family": res["target_family"],
            "bytes_read": int(res["bytes_read"]),
            "bytes_written": int(res["bytes_written"]),
            "reconstructed_bytes": int(res["reconstructed_bytes"]),
            "mode": res["mode"],
        }

    def _rpc_ec_verify(self, req: dict, ctx) -> dict:
        """VolumeEcShardsVerify: CRC-verify this node's local shards of one
        EC volume against the `.eci` record — the orphaned
        `verify_local_shards` fsck math, wired into the control plane.
        With `quarantine: true`, any failing shard is pulled from serving
        and handed to the automatic-repair queue exactly as a background
        scrub finding would be; report-only otherwise."""
        vid = int(req["volume_id"])
        ev = self.store.get_ec_volume(vid)
        if ev is None:
            raise rpc.NotFoundFault(f"ec volume {vid} not mounted")
        verdicts, has_crcs = scrub_mod.verify_ec_volume(
            ev, chunk_bytes=int(config.env("WEEDTPU_SCRUB_CHUNK"))
        )
        quarantined_now: list[int] = []
        if req.get("quarantine") and has_crcs:
            for s, v in sorted(verdicts.items()):
                if v in scrub_mod.FINDING_CLASSES and s not in ev.quarantined:
                    stats.ScrubCorruptionsFound.labels(v).inc()
                    self._scrub_finding(vid, s, v)
                    quarantined_now.append(s)
        return {
            "verdicts": {str(s): v for s, v in sorted(verdicts.items())},
            "has_crcs": has_crcs,
            "quarantined": quarantined_now,
        }

    def _rpc_ec_mount(self, req: dict, ctx) -> dict:
        vid = int(req["volume_id"])
        base = self._base_path_for(vid, req.get("collection", ""))
        if not stripe.find_local_shards(base):
            raise rpc.NotFoundFault(f"no local shards for volume {vid}")
        self.store.mount_ec_volume(vid, base)
        self.heartbeat_once()  # push the shard delta to the master now
        return {}

    def _rpc_ec_unmount(self, req: dict, ctx) -> dict:
        self.store.unmount_ec_volume(int(req["volume_id"]))
        self.heartbeat_once()
        return {}

    def _rpc_ec_shard_read(self, req: dict, ctx):
        """Stream bytes from one local shard (remote interval reads)."""
        delay_ms = config.env("WEEDTPU_BENCH_RPC_DELAY_MS")
        if delay_ms:
            # bench-only network simulation: on a 1-core loopback host the
            # real cost of a remote fetch is CPU, so parallelism cannot
            # show; a server-side sleep models the RTT that dominates real
            # clusters (and releases the GIL, so overlap is measurable)
            time.sleep(delay_ms / 1e3)
        vid = int(req["volume_id"])
        shard_id = int(req["shard_id"])
        offset = int(req["offset"])
        size = int(req["size"])
        ev = self.store.get_ec_volume(vid)
        if ev is None:
            raise rpc.NotFoundFault(f"ec volume {vid} not mounted")
        f = ev._shard_files.get(shard_id)
        if f is None:
            raise rpc.NotFoundFault(f"shard {shard_id} of volume {vid} not local")
        remaining = size
        pos = offset
        while remaining > 0:
            n = min(_COPY_CHUNK, remaining)
            buf = ev._read_local(shard_id, pos, n)
            if buf is None:
                raise rpc.RpcFault(f"short read shard {shard_id} @{pos}")
            yield buf.tobytes()
            pos += n
            remaining -= n

    def _rpc_ec_slab_read(self, req: dict, ctx):
        """Bulk slab stream for the distributed rebuild pipeline — the big
        sibling of VolumeEcShardRead: large windows, bounded chunk size,
        a CRC32 on every chunk (rebuild input must not trust bare TCP),
        and a PRIVATE file handle so a long stream never seek-races the
        serving handles interval reads use. EOF ends the stream short;
        the client zero-fills, mirroring local read_padded_into."""
        # admission control: slab streams ride a token-gated lane
        # (WEEDTPU_REBUILD_MAX_INFLIGHT) so a rebuild storm queues here
        # instead of saturating the RPC worker pool foreground interval
        # reads (VolumeEcShardRead) share. Tokens are held for the life
        # of the stream; a non-immediate grant is a counted wait, and the
        # wait itself is BOUNDED — past it the stream is refused
        # (RESOURCE_EXHAUSTED, retryable: the rebuilder's slab source
        # fails over) rather than pinning this worker thread too.
        if not self._rebuild_gate.acquire(blocking=False):
            stats.RebuildAdmissionWaits.inc()
            if not self._rebuild_gate.acquire(timeout=EC_SLAB_ADMISSION_WAIT):
                raise rpc.RpcFault(
                    "rebuild slab-read lane saturated "
                    f"(WEEDTPU_REBUILD_MAX_INFLIGHT="
                    f"{config.env('WEEDTPU_REBUILD_MAX_INFLIGHT')}); retry",
                    code=grpc.StatusCode.RESOURCE_EXHAUSTED,
                )
        try:
            delay_ms = config.env("WEEDTPU_BENCH_RPC_DELAY_MS")
            if delay_ms:
                # bench-only RTT model, same rationale as VolumeEcShardRead:
                # one sleep per bulk window (the per-request latency a real
                # network charges), GIL-released so client-side overlap shows
                time.sleep(delay_ms / 1e3)
            vid = int(req["volume_id"])
            # projection requests carry terms instead of a shard_id; a
            # PLAIN slab read with no shard_id must still fault loudly
            # (silently serving shard 0 would decode wrong survivor data)
            shard_id = 0 if req.get("projection") else int(req["shard_id"])
            offset = int(req["offset"])
            size = int(req["size"])
            chunk_size = min(max(64 * 1024, int(req.get("chunk_size") or _SLAB_CHUNK)), 8 << 20)
            yield_s = config.env("WEEDTPU_REBUILD_YIELD_MS") / 1e3
            ev = self.store.get_ec_volume(vid)
            if ev is None:
                raise rpc.NotFoundFault(f"ec volume {vid} not mounted")
            if req.get("projection"):
                yield from self._slab_projection_stream(
                    ev, req, offset, size, chunk_size, yield_s
                )
                return
            if shard_id not in ev._shard_files:
                raise rpc.NotFoundFault(f"shard {shard_id} of volume {vid} not local")
            path = stripe.shard_file_name(ev.base, shard_id)
            with open(path, "rb") as f:
                f.seek(offset)
                remaining = size
                while remaining > 0:
                    buf = f.read(min(chunk_size, remaining))
                    if not buf:
                        break  # EOF: short stream, client zero-fills
                    yield rpc.crc_frame(buf)
                    remaining -= len(buf)
                    if yield_s > 0 and remaining > 0:
                        # cooperative yield between chunks: cede the GIL/
                        # disk to foreground reads under contention
                        time.sleep(yield_s)
        finally:
            self._rebuild_gate.release()

    def _slab_projection_stream(
        self, ev, req: dict, offset: int, size: int, chunk_size: int, yield_s: float
    ):
        """Trace-repair half of VolumeEcShardSlabRead: stream the GF(2^8)
        partial sum of the requested LOCAL shards through the supplied
        decode coefficients — `rows` projected rows per byte column,
        row-major per chunk, CRC-framed like a plain slab. Moves
        rows x window bytes for the whole holder group instead of one
        full slab per survivor; EOF ends the stream short (all shards of
        a volume share one length) and the client zero-fills.

        The projection itself is the codec's bit-plane GF(2)/GF(2^8)
        matmul (Encoder.project), so the survivor side reuses exactly the
        verified decode math rather than a second GF implementation."""
        import numpy as np

        if self._trace_repair == "off":
            raise rpc.RpcFault(
                "slab projection reads disabled (WEEDTPU_TRACE_REPAIR=off)",
                code=grpc.StatusCode.UNIMPLEMENTED,
            )
        rows = int(req.get("projection_rows") or 0)
        terms = req["projection"]
        if rows <= 0 or rows > ev.total_shards:
            raise rpc.RpcFault(f"bad projection_rows {rows}")
        sids: list[int] = []
        coeff_cols: list[bytes] = []
        for term in terms:
            sid = int(term["shard_id"])
            raw = term["coeffs"]
            coeffs = raw if isinstance(raw, (bytes, bytearray)) else base64.b64decode(raw)
            if len(coeffs) != rows:
                raise rpc.RpcFault(
                    f"projection term for shard {sid} carries {len(coeffs)} "
                    f"coefficients, want {rows}"
                )
            if sid in sids:
                raise rpc.RpcFault(f"duplicate projection term for shard {sid}")
            sids.append(sid)
            coeff_cols.append(bytes(coeffs))
        missing_local = [s for s in sids if s not in ev._shard_files]
        if missing_local:
            # the planner grouped against a stale holder map: refuse the
            # whole group so the rebuilder re-plans (or falls back) rather
            # than silently projecting a partial sum
            raise rpc.NotFoundFault(
                f"projection shards {missing_local} of volume "
                f"{int(req['volume_id'])} not local"
            )
        coeffs = np.frombuffer(b"".join(coeff_cols), dtype=np.uint8).reshape(
            len(sids), rows
        ).T.copy()  # (rows, n_terms)
        paths = [stripe.shard_file_name(ev.base, s) for s in sids]
        actual = max(0, min(size, min(os.path.getsize(p) for p in paths) - offset))
        if actual == 0:
            return  # whole window past EOF: empty stream, client zero-fills
        cols_per_chunk = max(64 * 1024 // rows, chunk_size // rows)
        enc = self.store.encoder
        with ExitStack() as stack:
            files = [stack.enter_context(open(p, "rb")) for p in paths]
            sent = 0
            while sent < actual:
                cols = min(cols_per_chunk, actual - sent)
                block = np.empty((len(sids), cols), dtype=np.uint8)
                for i, f in enumerate(files):
                    stripe.read_padded_into(f, offset + sent, block[i])
                projected = enc.project(coeffs, block)
                yield rpc.crc_frame(projected.tobytes())
                sent += cols
                if yield_s > 0 and sent < actual:
                    time.sleep(yield_s)

    def _rpc_ec_blob_delete(self, req: dict, ctx) -> dict:
        vid = int(req["volume_id"])
        fid = FileId.parse(req["fid"]) if "fid" in req else None
        needle_id = fid.key if fid else int(req["needle_id"])
        ev = self.store.get_ec_volume(vid)
        if ev is None:
            raise rpc.NotFoundFault(f"ec volume {vid} not mounted")
        return {"found": ev.delete_needle(needle_id)}

    def _rpc_ec_to_volume(self, req: dict, ctx) -> dict:
        """VolumeEcShardsToVolume: local shards -> normal .dat/.idx."""
        vid = int(req["volume_id"])
        collection = req.get("collection", "")
        base = self._base_path_for(vid, collection)
        present = stripe.find_local_shards(base)
        if any(s not in present for s in range(10)):
            stripe.rebuild_ec_files(base, encoder=self.store.encoder)
        stripe.write_dat_file(base)
        stripe.write_idx_file_from_ec_index(base)
        self.store.unmount_ec_volume(vid)
        # load as normal volume
        for loc in self.store.locations:
            if os.path.dirname(base) == loc.directory:
                from seaweedfs_tpu.storage.volume import Volume

                loc.volumes[vid] = Volume(loc.directory, vid, collection)
        self.heartbeat_once()
        return {}

    def _rpc_ec_delete(self, req: dict, ctx) -> dict:
        vid = int(req["volume_id"])
        shard_ids = [int(s) for s in req.get("shard_ids", [])]
        base = self._base_path_for(vid, req.get("collection", ""))
        self.store.unmount_ec_volume(vid)
        for s in shard_ids or stripe.find_local_shards(base):
            p = stripe.shard_file_name(base, s)
            if os.path.exists(p):
                os.remove(p)
            if os.path.exists(p + ".bad"):  # quarantined original, kept
                os.remove(p + ".bad")       # for forensics until deletion
        if not stripe.find_local_shards(base):
            for ext in _EC_EXTS:
                if os.path.exists(base + ext):
                    os.remove(base + ext)
        elif stripe.find_local_shards(base):
            self.store.mount_ec_volume(vid, base)
        self.heartbeat_once()
        return {}


# -- HTTP data path ----------------------------------------------------------


class _ThreadingHTTPServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    volume_server: "VolumeServer"


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Keep-alive clients send one small request per round trip; with Nagle
    # on, each response stalls ~40 ms behind the peer's delayed ACK.
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # quiet
        pass

    @property
    def vs(self) -> VolumeServer:
        return self.server.volume_server

    def _parse_fid(self) -> Optional[FileId]:
        path = urllib.parse.urlparse(self.path).path.lstrip("/")
        try:
            return FileId.parse(path)
        except ValueError:
            return None

    def _reply(
        self,
        code: int,
        body: bytes,
        content_type: str = "application/octet-stream",
        head: bool = False,
        headers: Optional[dict] = None,
    ) -> None:
        self.send_response(code)
        # the trace id rides back on EVERY reply of a traced request, so
        # a client can correlate its latency with the server-side span
        # tree (/debug/traces, glog grep) without guessing
        tid = trace_mod.current_trace_id()
        if tid:
            self.send_header(trace_mod.HTTP_HEADER, tid)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if not head:  # HEAD: headers only, or keep-alive streams desync
            self.wfile.write(body)

    def _reply_json(
        self,
        code: int,
        obj: dict,
        head: bool = False,
        headers: Optional[dict] = None,
    ) -> None:
        self._reply(
            code, json.dumps(obj).encode(), "application/json", head=head,
            headers=headers,
        )

    def _serve_get(self, head: bool) -> None:
        path = urllib.parse.urlparse(self.path).path
        if path == "/debug/traces":
            self._reply(
                200,
                json.dumps(trace_mod.debug_payload(self.path)).encode(),
                "application/json",
                head=head,
            )
            return
        if path not in ("/metrics", "/status", "/ui", "/ui/index.html"):
            # needle reads are the traced serving path; debug/status
            # surfaces must not churn the ring
            t0 = time.monotonic()
            with trace_mod.start(
                "http.read",
                klass="healthy",
                trace_id=self.headers.get(trace_mod.HTTP_HEADER),
            ):
                self._serve_get_inner(head)
            stats.VolumeServerRequestHistogram.labels("get").observe(
                time.monotonic() - t0
            )
            return
        self._serve_get_inner(head)

    def _serve_get_inner(self, head: bool) -> None:
        if urllib.parse.urlparse(self.path).path == "/metrics":
            self._reply(
                200,
                stats.REGISTRY.expose().encode(),
                "text/plain; version=0.0.4",
                head=head,
            )
            return
        if urllib.parse.urlparse(self.path).path == "/status":
            self._reply_json(
                200,
                {
                    "volumes": self.vs.store.volume_infos(),
                    "ec_volumes": [i.to_dict() for i in self.vs.store.ec_volume_infos()],
                },
                head=head,
            )
            return
        if urllib.parse.urlparse(self.path).path in ("/ui", "/ui/index.html"):
            # operator status page (volume_server_handlers_ui.go analog).
            # Every interpolated string is escaped: collection/rack/dc names
            # arrive from unauthenticated callers and render in a browser.
            from html import escape as _esc

            vols = self.vs.store.volume_infos()
            ecs = [i.to_dict() for i in self.vs.store.ec_volume_infos()]
            rows = "".join(
                f"<tr><td>{int(v['id'])}</td><td>{_esc(str(v.get('collection','')))}</td>"
                f"<td>{int(v.get('size',0))}</td><td>{int(v.get('file_count',0))}</td>"
                f"<td>{float(v.get('garbage_ratio',0)):.2f}</td>"
                f"<td>{bool(v.get('read_only',False))}</td>"
                f"<td>{_esc(str(v.get('replica_placement','')))}</td></tr>"
                for v in sorted(vols, key=lambda v: int(v["id"]))
            )
            ec_rows = "".join(
                f"<tr><td>{int(e['volume_id'])}</td>"
                f"<td>{_esc(str(e.get('collection','')))}</td>"
                f"<td>{bin(e.get('shard_bits',0)).count('1')}</td></tr>"
                for e in sorted(ecs, key=lambda e: int(e["volume_id"]))
            )
            html = (
                "<!DOCTYPE html><html><head><title>weedtpu volume server</title>"
                "<style>body{font-family:monospace}table{border-collapse:collapse}"
                "td,th{border:1px solid #999;padding:2px 8px}</style></head><body>"
                f"<h1>Volume Server {_esc(self.vs.url)}</h1>"
                f"<p>grpc :{int(self.vs.grpc_port)} &middot; "
                f"rack {_esc(str(self.vs.rack))} &middot; "
                f"dc {_esc(str(self.vs.data_center))} &middot; "
                f"{len(vols)}/{self.vs.max_volume_count} volume slots</p>"
                "<h2>Volumes</h2><table><tr><th>id</th><th>collection</th>"
                "<th>size</th><th>files</th><th>garbage</th><th>read-only</th>"
                f"<th>rp</th></tr>{rows}</table>"
                "<h2>EC volumes</h2><table><tr><th>id</th><th>collection</th>"
                f"<th>shards held</th></tr>{ec_rows}</table>"
                '<p><a href="/status">/status</a> &middot; '
                '<a href="/metrics">/metrics</a></p></body></html>'
            )
            self._reply(200, html.encode(), "text/html; charset=utf-8", head=head)
            return
        stats.VolumeServerRequestCounter.labels("get").inc()
        fid = self._parse_fid()
        if fid is None:
            self._reply_json(400, {"error": "bad file id"}, head=head)
            return
        if not self.vs.guard.check_read(
            str(fid), self.headers.get("Authorization", ""), self.client_address[0]
        ):
            self._reply_json(401, {"error": "unauthorized read"}, head=head)
            return
        try:
            self.vs._open_ec_volume(fid.volume_id)  # wire the remote reader
            try:
                n = self.vs.store.read_needle(
                    fid.volume_id, fid.key, cookie=fid.cookie
                )
            except CrcError:
                # verify-on-read caught a corrupt copy BEFORE it reached
                # the client: identify + quarantine the damaged shard
                # (here or on a peer holder) and serve the clean
                # reconstruction; raises when nothing can be healed
                n = self.vs._heal_needle_read(
                    fid.volume_id, fid.key, cookie=fid.cookie
                )
        except (KeyError, NeedleNotFound):
            self._reply_json(404, {"error": "not found"}, head=head)
            return
        except NeedleDeleted:
            self._reply_json(404, {"error": "deleted"}, head=head)
            return
        except PermissionError:
            self._reply_json(403, {"error": "cookie mismatch"}, head=head)
            return
        except EcDegradedReadError as e:
            # a degraded read that could not be served NOW is overload/
            # partial-failure, not a server bug: 503 + Retry-After (typed
            # per failure class — suspicion-window length for no-viable-
            # holders, prompt for a deadline cut) so clients back off
            # instead of hammering a stripe mid-repair
            self._reply_json(
                503,
                {
                    "error": str(e),
                    "class": type(e).__name__,
                    "attempted": [str(a) for a in e.attempted],
                    "suspected": [str(s) for s in e.suspected],
                },
                head=head,
                headers={"Retry-After": str(max(1, round(e.retry_after)))},
            )
            return
        except IOError as e:
            self._reply_json(500, {"error": str(e)}, head=head)
            return
        ctype = n.mime.decode() if n.mime else "application/octet-stream"
        # the serving class the read resolved to (healthy / ec_intact /
        # cached / degraded) rides back per-request so load harnesses can
        # classify latencies without scraping traces
        klass = trace_mod.current_class()
        self._reply(
            200, n.data, ctype, head=head,
            headers={trace_mod.READ_CLASS_HEADER: klass} if klass else None,
        )

    def do_GET(self) -> None:
        self._serve_get(head=False)

    def do_HEAD(self) -> None:
        self._serve_get(head=True)

    def _replicate(
        self,
        fid: FileId,
        method: str,
        data: Optional[bytes],
        ctype: str,
        name: bytes = b"",
    ) -> Optional[str]:
        """Fan a write/delete out to the volume's sibling replicas
        (store_replicate.go analog). Returns an error string, or None.
        The X-Weed-Replicate header stops forwarding loops; the filename
        of a form upload rides X-Weed-Filename (b64) so replica needles
        stay byte-identical to the primary's (check.disk compares per-id
        sizes, and the name is part of the needle body)."""
        try:
            resp = self.vs._master_query(
                "Lookup", {"volume_or_file_ids": [str(fid.volume_id)]}
            )
            entries = resp.get("volume_id_locations", [])
            locations = entries[0].get("locations", []) if entries else []
        except Exception as e:  # noqa: BLE001
            return f"replica lookup failed: {e}"
        # replica hop needs its own token: volume servers share the signing
        # key, so mint one here rather than forwarding the client's
        auth = {}
        if self.vs.guard.signing_key:
            from seaweedfs_tpu.security.jwt import mint_file_token

            auth = {
                "Authorization": "Bearer "
                + mint_file_token(self.vs.guard.signing_key, str(fid))
            }

        def _push(url: str) -> Optional[str]:
            try:
                req = urllib.request.Request(
                    f"{tls.scheme()}://{url}/{fid}",
                    data=data,
                    method=method,
                    headers={
                        "X-Weed-Replicate": "1",
                        **auth,
                        **({"Content-Type": ctype} if ctype else {}),
                        **(
                            {"X-Weed-Filename": base64.b64encode(name).decode()}
                            if name
                            else {}
                        ),
                    },
                )
                with tls.urlopen(req, timeout=self.vs.replicate_timeout) as r:
                    r.read()
                return None
            except urllib.error.HTTPError as e:
                if method == "DELETE" and e.code == 404:
                    return None  # already absent on the replica
                return f"{url}: HTTP {e.code}"
            except Exception as e:  # noqa: BLE001
                return f"{url}: {e}"

        # Parallel fan-out (store_replicate.go's DistributedOperation analog):
        # one dead replica costs one timeout, not a serial sum of them.
        targets = [d["url"] for d in locations if d["url"] != self.vs.url]
        if not targets:
            return None
        with futures.ThreadPoolExecutor(max_workers=min(8, len(targets))) as pool:
            errs = [e for e in pool.map(_push, targets) if e]
        return "; ".join(errs) or None

    def do_POST(self) -> None:
        t0 = time.monotonic()
        with trace_mod.start(
            "http.write",
            klass="put",
            trace_id=self.headers.get(trace_mod.HTTP_HEADER),
        ):
            self._do_post_inner()
        stats.VolumeServerRequestHistogram.labels("post").observe(
            time.monotonic() - t0
        )

    def _do_post_inner(self) -> None:
        stats.VolumeServerRequestCounter.labels("post").inc()
        fid = self._parse_fid()
        if fid is None:
            self._reply_json(400, {"error": "bad file id"})
            return
        if not self.vs.guard.check_write(
            str(fid), self.headers.get("Authorization", ""), self.client_address[0]
        ):
            self._reply_json(401, {"error": "unauthorized write"})
            return
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        ctype = self.headers.get("Content-Type", "")
        name = b""
        if ctype.startswith("multipart/form-data"):
            # the reference's canonical workflow is `curl -F file=@x URL`
            # ([ref: weed/server/volume_server_handlers_write.go +
            # needle parsing of form uploads — mount empty]); storing the
            # raw form would hand the framing back as file bytes on read
            try:
                part = _first_multipart_file(data, ctype)
            except Exception:  # noqa: BLE001 — malformed framing is a 400
                part = None
            if part is None:
                self._reply_json(400, {"error": "no file part in form data"})
                return
            data, name, part_mime = part
            ctype = part_mime
        elif self.headers.get("X-Weed-Filename"):
            # replica hop: the primary forwards the parsed form filename
            # so sibling needles stay byte-identical
            try:
                name = base64.b64decode(self.headers["X-Weed-Filename"])
            except Exception:  # noqa: BLE001 — bad header: store unnamed
                name = b""
        n = Needle(cookie=fid.cookie, id=fid.key, data=data)
        if name:
            n.name = name
        if ctype and ctype != "application/octet-stream":
            n.mime = ctype.encode("utf-8", "surrogateescape")
        try:
            _, size = self.vs.store.write_needle(fid.volume_id, n)
        except KeyError:
            self._reply_json(404, {"error": f"volume {fid.volume_id} not found"})
            return
        except VolumeReadOnly as e:
            self._reply_json(422, {"error": str(e)})
            return
        except ValueError as e:
            # client-controlled inputs (255-byte name/mime caps, framing)
            # must answer 400, not abort the connection
            self._reply_json(400, {"error": str(e)})
            return
        if "X-Weed-Replicate" not in self.headers:
            err = self._replicate(fid, "POST", data, ctype, name=name)
            if err:
                # strict replication (the reference fails the write when the
                # fan-out fails): surface the partial state to the client
                self._reply_json(500, {"error": f"replication failed: {err}", "size": size})
                return
        self._reply_json(201, {"size": size})

    do_PUT = do_POST

    def do_DELETE(self) -> None:
        stats.VolumeServerRequestCounter.labels("delete").inc()
        fid = self._parse_fid()
        if fid is None:
            self._reply_json(400, {"error": "bad file id"})
            return
        if not self.vs.guard.check_write(
            str(fid), self.headers.get("Authorization", ""), self.client_address[0]
        ):
            self._reply_json(401, {"error": "unauthorized delete"})
            return
        try:
            found = self.vs.store.delete_needle(fid.volume_id, fid.key)
        except KeyError:
            self._reply_json(404, {"error": "volume not found"})
            return
        except VolumeReadOnly as e:
            self._reply_json(422, {"error": str(e)})
            return
        if "X-Weed-Replicate" not in self.headers:
            err = self._replicate(fid, "DELETE", None, "")
            if err:
                self._reply_json(500, {"error": f"replicated delete failed: {err}"})
                return
        self._reply_json(200 if found else 404, {"found": bool(found)})
