"""Filesystem-op recorder + crash-prefix replayer — the dynamic half of
the durability family (weedsafe).

The static checkers in `analysis.durability` see LEXICAL fsync/rename
ordering; the actual crash contracts (the `.ecp` ingest journal, the
`.ecc` convert journal, the scrub cursor, the kernel_sweep JSONL) are
cross-function protocols whose safety lives in the ORDER of write /
fsync / rename ops at runtime. This module records that order and then
asks the only question that matters: for EVERY prefix of the real op
trace, if the process had died right there, does the real resume
entrypoint land in a documented state?

Recording (modeled on `analysis.lockrec`): `install(root)` interposes
shims over `builtins.open` (write-capable modes under `root` return a
recording proxy), `os.write`, `os.fsync`, `os.replace`, `os.rename`,
`os.unlink`/`os.remove`, and `os.truncate`. Each op carries the path
(root-relative), absolute byte offsets, payload bytes, and its creation
site (file:line of the caller, lockrec-style identity). Opt-in for the
tier-1 session via WEEDTPU_FS_OBSERVE (tests/conftest.py); replay tests
install it directly around a scoped workload.

Crash model (what a prefix materializes to): ops are applied in order
against the install-time snapshot. Data writes are PENDING until an
fsync on that file promotes them to durable; metadata ops (create,
rename/replace, unlink, truncate-at-open) follow ordered-journaling
semantics — applied in recorded order, never reordered past each other.
At the crash point each file's pending tail is resolved per variant:

  clean — every pending write hit the disk before power loss
  torn  — all but the last pending write applied; the last applied only
          through its first half (a torn sector/page tail)
  lost  — no pending write since the last fsync survived

A protocol is crash-safe iff for every prefix x variant the resume
entrypoint either resumes to a byte-identical result or refuses and
falls back to the warm path — never serves or commits corrupt bytes.
The prefix count is bounded by WEEDTPU_FSREPLAY_MAX_PREFIXES (evenly
sampled, endpoints always included) so the tier-1 gate stays inside its
time budget.
"""

from __future__ import annotations

import _thread
import builtins
import dataclasses
import io
import json
import os
import traceback
from typing import Optional

_HERE = __file__

_WRITE_MODE_CHARS = ("w", "a", "x", "+")


@dataclasses.dataclass(frozen=True)
class FsOp:
    """One recorded filesystem operation. `path`/`dst` are root-relative.

    kinds: create (open w/x or a on a missing file), write (data at
    offset), flush (no durability effect; kept for trace fidelity),
    fsync, replace (path -> dst), unlink, truncate (to size `offset`).
    """

    kind: str
    path: str
    offset: int = 0
    data: bytes = b""
    dst: str = ""
    site: str = ""

    def sig(self) -> tuple:
        """Identity without the creation site — what determinism means."""
        return (self.kind, self.path, self.offset, self.data, self.dst)


@dataclasses.dataclass
class FsTrace:
    root: str
    initial: dict[str, bytes]  # rel path -> snapshot bytes at install
    ops: list[FsOp]

    def dump(self, path: str) -> None:
        payload = {
            "root": self.root,
            "initial": {p: data.hex() for p, data in sorted(self.initial.items())},
            "ops": [
                {
                    "kind": op.kind, "path": op.path, "offset": op.offset,
                    "data": op.data.hex(), "dst": op.dst, "site": op.site,
                }
                for op in self.ops
            ],
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)


def _creation_site() -> str:
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if fn == _HERE or fn.endswith("fsrec.py"):
            continue
        return f"{fn}:{frame.lineno}"
    return "<unknown>"


class _RecordingFile:
    """Write-capable file proxy: forwards everything to the real handle,
    reporting writes (with absolute offsets), flushes, truncates, and
    close to the recorder. Text-mode positions are tracked by encoded
    byte count (journal writers never seek in text mode; binary handles
    use the real tell())."""

    def __init__(self, inner, rel: str, rec: "FsRecorder", text: bool):
        self._inner = inner
        self._rel = rel
        self._rec = rec
        self._text = text
        self._pos = 0 if not text else self._text_start()
        rec._register_fd(inner.fileno(), rel)

    def _text_start(self) -> int:
        try:
            return os.fstat(self._inner.fileno()).st_size if "a" in self._inner.mode else 0
        except (OSError, ValueError):
            return 0

    def write(self, data):
        n = self._inner.write(data)
        raw = data.encode("utf-8") if self._text else bytes(data)
        if self._text:
            off = self._pos
            self._pos += len(raw)
        else:
            off = self._inner.tell() - len(raw)
        self._rec._record(FsOp("write", self._rel, off, raw, site=_creation_site()))
        return n

    def flush(self):
        self._inner.flush()
        self._rec._record(FsOp("flush", self._rel, site=_creation_site()))

    def truncate(self, size=None):
        r = self._inner.truncate(size)
        eff = self._inner.tell() if size is None else size
        self._rec._record(FsOp("truncate", self._rel, eff, site=_creation_site()))
        return r

    def seek(self, *a, **k):
        r = self._inner.seek(*a, **k)
        if not self._text:
            pass  # binary offsets read from tell() at write time
        return r

    def close(self):
        try:
            fd = self._inner.fileno()
        except (OSError, ValueError):
            fd = None
        self._inner.close()
        if fd is not None:
            self._rec._unregister_fd(fd)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self):
        return iter(self._inner)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FsRecorder:
    """Records every durability-relevant fs op under `root`. One recorder
    may be installed at a time (module-level patch, like lockrec)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._raw = _thread.allocate_lock()
        self._ops: list[FsOp] = []
        self._fd_paths: dict[int, str] = {}
        self.initial = self._snapshot()

    def _snapshot(self) -> dict[str, bytes]:
        snap: dict[str, bytes] = {}
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in filenames:
                p = os.path.join(dirpath, name)
                try:
                    with io.open(p, "rb") as f:
                        snap[os.path.relpath(p, self.root)] = f.read()
                except OSError:
                    continue
        return snap

    def _rel(self, path) -> Optional[str]:
        try:
            apath = os.path.abspath(os.fspath(path))
        except TypeError:  # fd-relative or int path: not ours
            return None
        if apath == self.root or apath.startswith(self.root + os.sep):
            return os.path.relpath(apath, self.root)
        return None

    def _record(self, op: FsOp) -> None:
        with self._raw:
            self._ops.append(op)

    def _register_fd(self, fd: int, rel: str) -> None:
        with self._raw:
            self._fd_paths[fd] = rel

    def _unregister_fd(self, fd: int) -> None:
        with self._raw:
            self._fd_paths.pop(fd, None)

    def fd_rel(self, fd: int) -> Optional[str]:
        with self._raw:
            return self._fd_paths.get(fd)

    def trace(self) -> FsTrace:
        with self._raw:
            return FsTrace(self.root, dict(self.initial), list(self._ops))

    def reset(self) -> None:
        with self._raw:
            self._ops.clear()
        self.initial = self._snapshot()


_installed: Optional[tuple] = None


def install(root: str) -> FsRecorder:
    """Interpose the recording shims for paths under `root`. Idempotent —
    a second install with the SAME root returns the active recorder; a
    different root is a programming error (raise, don't silently record
    the wrong tree)."""
    global _installed
    if _installed is not None:
        rec = _installed[0]
        if rec.root != os.path.abspath(root):
            raise RuntimeError(
                f"fsrec already installed for {rec.root!r}, asked for {root!r}"
            )
        return rec
    rec = FsRecorder(root)
    orig_open = builtins.open
    orig = {
        "write": os.write, "fsync": os.fsync, "replace": os.replace,
        "rename": os.rename, "unlink": os.unlink, "remove": os.remove,
        "truncate": os.truncate,
    }

    def rec_open(file, mode="r", *args, **kwargs):
        rel = rec._rel(file) if isinstance(file, (str, bytes, os.PathLike)) else None
        writable = any(c in str(mode) for c in _WRITE_MODE_CHARS)
        if rel is None or not writable:
            return orig_open(file, mode, *args, **kwargs)
        existed = os.path.exists(file)
        inner = orig_open(file, mode, *args, **kwargs)
        m = str(mode)
        if not existed or "w" in m or "x" in m:
            rec._record(FsOp("create", rel, site=_creation_site()))
        return _RecordingFile(inner, rel, rec, text="b" not in m)

    def rec_os_write(fd, data, *a, **k):
        rel = rec.fd_rel(fd)
        off = os.lseek(fd, 0, os.SEEK_CUR) if rel is not None else 0
        n = orig["write"](fd, data, *a, **k)
        if rel is not None:
            rec._record(FsOp("write", rel, off, bytes(data[:n]), site=_creation_site()))
        return n

    def rec_fsync(fd):
        orig["fsync"](fd)
        rel = rec.fd_rel(fd)
        if rel is not None:
            rec._record(FsOp("fsync", rel, site=_creation_site()))

    def _rename_like(name):
        def patched(src, dst, *a, **k):
            orig[name](src, dst, *a, **k)
            rel_src, rel_dst = rec._rel(src), rec._rel(dst)
            if rel_src is not None and rel_dst is not None:
                rec._record(FsOp(
                    "replace", rel_src, dst=rel_dst, site=_creation_site()
                ))
        return patched

    def _unlink_like(name):
        def patched(path, *a, **k):
            orig[name](path, *a, **k)
            rel = rec._rel(path)
            if rel is not None:
                rec._record(FsOp("unlink", rel, site=_creation_site()))
        return patched

    def rec_truncate(path, length):
        orig["truncate"](path, length)
        rel = rec._rel(path) if isinstance(path, (str, bytes, os.PathLike)) else None
        if rel is not None:
            rec._record(FsOp("truncate", rel, length, site=_creation_site()))

    builtins.open = rec_open
    os.write = rec_os_write
    os.fsync = rec_fsync
    os.replace = _rename_like("replace")
    os.rename = _rename_like("rename")
    os.unlink = _unlink_like("unlink")
    os.remove = _unlink_like("remove")
    os.truncate = rec_truncate
    _installed = (rec, orig_open, orig)
    return rec


def uninstall() -> None:
    global _installed
    if _installed is None:
        return
    _rec, orig_open, orig = _installed
    builtins.open = orig_open
    os.write = orig["write"]
    os.fsync = orig["fsync"]
    os.replace = orig["replace"]
    os.rename = orig["rename"]
    os.unlink = orig["unlink"]
    os.remove = orig["remove"]
    os.truncate = orig["truncate"]
    _installed = None


def active_recorder() -> Optional[FsRecorder]:
    return _installed[0] if _installed is not None else None


# ---------------------------------------------------------------------------
# Crash-prefix replay
# ---------------------------------------------------------------------------

VARIANTS = ("clean", "torn", "lost")


class _SimFile:
    __slots__ = ("durable", "pending")

    def __init__(self, durable: bytes = b""):
        self.durable = bytearray(durable)
        self.pending: list[FsOp] = []


def _apply_data_op(buf: bytearray, op: FsOp, data: Optional[bytes] = None) -> None:
    if op.kind == "write":
        payload = op.data if data is None else data
        end = op.offset + len(payload)
        if len(buf) < end:
            buf.extend(b"\0" * (end - len(buf)))
        buf[op.offset:end] = payload
    elif op.kind == "truncate":
        if op.offset <= len(buf):
            del buf[op.offset:]
        else:
            buf.extend(b"\0" * (op.offset - len(buf)))


def _settle(f: _SimFile, variant: str) -> bytes:
    """Resolve a file's pending tail at the crash point per variant."""
    buf = bytearray(f.durable)
    pending = f.pending
    if variant == "lost" or not pending:
        return bytes(buf)
    if variant == "clean":
        for op in pending:
            _apply_data_op(buf, op)
        return bytes(buf)
    # torn: all but the last applied; a trailing write lands half its bytes
    for op in pending[:-1]:
        _apply_data_op(buf, op)
    last = pending[-1]
    if last.kind == "write" and len(last.data) > 1:
        _apply_data_op(buf, last, data=last.data[: len(last.data) // 2])
    elif last.kind != "write":
        _apply_data_op(buf, last)
    return bytes(buf)


def simulate_prefix(
    trace: FsTrace, n_ops: int, variant: str = "clean"
) -> dict[str, bytes]:
    """Post-crash file contents (rel path -> bytes) after applying the
    first `n_ops` recorded ops to the install-time snapshot, with the
    pending tails resolved per `variant`."""
    if variant not in VARIANTS:
        raise ValueError(f"unknown variant {variant!r}")
    files: dict[str, _SimFile] = {
        rel: _SimFile(data) for rel, data in trace.initial.items()
    }
    for op in trace.ops[:n_ops]:
        if op.kind == "create":
            files[op.path] = _SimFile()
        elif op.kind in ("write", "truncate"):
            files.setdefault(op.path, _SimFile()).pending.append(op)
        elif op.kind == "fsync":
            f = files.setdefault(op.path, _SimFile())
            for p in f.pending:
                _apply_data_op(f.durable, p)
            f.pending = []
        elif op.kind == "replace":
            if op.path in files:
                files[op.dst] = files.pop(op.path)
        elif op.kind == "unlink":
            files.pop(op.path, None)
        elif op.kind == "flush":
            pass  # page cache only — no durability effect
        else:  # pragma: no cover — future op kinds must be handled here
            raise ValueError(f"unknown op kind {op.kind!r}")
    return {rel: _settle(f, variant) for rel, f in files.items()}


def materialize_prefix(
    trace: FsTrace, n_ops: int, dest: str, variant: str = "clean"
) -> dict[str, bytes]:
    """Write the post-crash state for a prefix into `dest` (created empty
    — caller owns clearing between prefixes) and return it."""
    state = simulate_prefix(trace, n_ops, variant)
    for rel, data in state.items():
        p = os.path.join(dest, rel)
        os.makedirs(os.path.dirname(p) or dest, exist_ok=True)
        with io.open(p, "wb") as f:
            f.write(data)
    return state


def prefix_schedule(n_ops: int, max_prefixes: int) -> list[int]:
    """Which prefixes to replay: every one when the budget allows, else an
    even sample that always keeps both endpoints (0 = nothing happened,
    n_ops = the crash was after the last op)."""
    total = n_ops + 1
    if max_prefixes <= 0 or total <= max_prefixes:
        return list(range(total))
    if max_prefixes == 1:
        return [n_ops]
    step = (total - 1) / (max_prefixes - 1)
    picks = {round(i * step) for i in range(max_prefixes)}
    picks.update((0, n_ops))
    return sorted(picks)
