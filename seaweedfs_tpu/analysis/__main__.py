"""weedlint CLI — `python -m seaweedfs_tpu.analysis`.

Exit code 0 when the tree is clean, 1 when any finding survives
suppression. Runs in tier-1 CI (tests/test_weedlint.py) next to
`kernel_sweep.py --smoke`; budgeted well under 30 s.

  --strict        also flag unused suppression pragmas (the CI mode)
  --changed-only  per-file checkers only on files changed vs git HEAD
                  (project checkers still see the whole tree — their
                  invariants are global); the fast pre-commit mode
  --list-rules    print the rule catalog and exit
  --write-env-table [README.md]
                  regenerate the WEEDTPU_* env-var table between the
                  weedlint markers in the README from the registry
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

from seaweedfs_tpu.analysis import PKG_ROOT, REPO_ROOT, RULES, run

ENV_TABLE_BEGIN = "<!-- weedlint:env-table:begin -->"
ENV_TABLE_END = "<!-- weedlint:env-table:end -->"


def changed_files() -> set[str]:
    """Absolute paths of .py files changed vs HEAD (staged, unstaged, and
    untracked)."""
    out: set[str] = set()
    for args in (
        ["git", "-C", REPO_ROOT, "diff", "--name-only", "HEAD"],
        ["git", "-C", REPO_ROOT, "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                args, capture_output=True, text=True, timeout=20
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(os.path.abspath(os.path.join(REPO_ROOT, line)))
    return out


def rewrite_env_table(readme_path: str) -> bool:
    from seaweedfs_tpu.utils.config import env_table_markdown

    with open(readme_path, "r", encoding="utf-8") as f:
        text = f.read()
    begin = text.find(ENV_TABLE_BEGIN)
    end = text.find(ENV_TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        print(
            f"{readme_path}: missing {ENV_TABLE_BEGIN} / {ENV_TABLE_END} "
            "markers",
            file=sys.stderr,
        )
        return False
    new = (
        text[: begin + len(ENV_TABLE_BEGIN)]
        + "\n"
        + env_table_markdown()
        + text[end:]
    )
    if new != text:
        with open(readme_path, "w", encoding="utf-8") as f:
            f.write(new)
    return True


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m seaweedfs_tpu.analysis", description=__doc__
    )
    parser.add_argument("paths", nargs="*", help="files/dirs to scan (default: the package)")
    parser.add_argument("--strict", action="store_true")
    parser.add_argument("--changed-only", action="store_true")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument(
        "--write-env-table", nargs="?", const=os.path.join(REPO_ROOT, "README.md"),
        metavar="README",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            print(f"{rule:24s} {RULES[rule]}")
        return 0
    if args.write_env_table:
        return 0 if rewrite_env_table(args.write_env_table) else 1

    paths = None
    if args.paths:
        paths = []
        for p in args.paths:
            if os.path.isdir(p):
                from seaweedfs_tpu.analysis import iter_source_files

                paths.extend(iter_source_files(p))
            else:
                paths.append(p)

    t0 = time.monotonic()
    findings = run(
        paths=paths,
        root=PKG_ROOT,
        strict=args.strict,
        changed_only_files=changed_files() if args.changed_only else None,
    )
    for f in findings:
        print(f.render())
    dt = time.monotonic() - t0
    print(
        f"weedlint: {len(findings)} finding(s) in {dt:.1f}s "
        f"({'strict' if args.strict else 'default'} mode)",
        file=sys.stderr,
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
