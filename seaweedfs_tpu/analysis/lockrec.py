"""Dynamic lock-order recorder — the runtime half of the lock-discipline
family.

The static checker sees LEXICAL `with` nesting; real acquisition orders
also flow through call chains, executor callbacks, and the rebuild /
degraded-read concurrency that PRs 3-4 grew. This module instruments
`threading.Lock`/`RLock` (opt-in: WEEDTPU_LOCK_OBSERVE=1, wired in
tests/conftest.py) so every lock carries its creation site, each thread
tracks the stack of sites it currently holds, and acquiring B while
holding A records the edge A -> B. At session end the observed graph
must be acyclic — a cycle is a lock-order race that WILL deadlock under
the right interleaving, found without waiting for chaos_soak to hang.

The recorder's own bookkeeping uses a raw `_thread.allocate_lock` (the
primitive the wrappers delegate to), so instrumentation can never
recurse into itself.
"""

from __future__ import annotations

import _thread
import json
import threading
import traceback
from typing import Optional

from seaweedfs_tpu.analysis import graph

_HERE = __file__


class LockOrderRecorder:
    def __init__(self) -> None:
        self._raw = _thread.allocate_lock()
        self._edges: dict[tuple[str, str], int] = {}  # (a, b) -> count
        self._tls = threading.local()

    # -- per-thread held stack -----------------------------------------------

    def _held(self) -> list[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def on_acquire(self, site: str) -> None:
        held = self._held()
        if site not in held:  # reentrant re-acquire orders nothing new
            new_edges = [(h, site) for h in held if h != site]
            if new_edges:
                with self._raw:
                    for e in new_edges:
                        self._edges[e] = self._edges.get(e, 0) + 1
        held.append(site)

    def on_release(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    # -- results --------------------------------------------------------------

    def edges(self) -> dict[tuple[str, str], int]:
        with self._raw:
            return dict(self._edges)

    def cycles(self, only_containing: Optional[str] = None) -> list[list[str]]:
        """Cycles in the observed graph. `only_containing` restricts the
        graph to edges whose BOTH endpoints mention the substring — the
        tier-1 gate asserts on seaweedfs_tpu's locks, not on whatever
        ordering jax/stdlib internals exhibit."""
        pairs = self.edges().keys()
        if only_containing is not None:
            pairs = [
                (a, b) for a, b in pairs
                if only_containing in a and only_containing in b
            ]
        return graph.cyclic_components(graph.edges_from_pairs(pairs))

    def report(self, only_containing: Optional[str] = None) -> str:
        cycles = self.cycles(only_containing)
        lines = [
            f"lock-order recorder: {len(self.edges())} distinct edges, "
            f"{len(cycles)} cycle(s)"
        ]
        for cyc in cycles:
            lines.append("  CYCLE: " + " -> ".join(cyc + [cyc[0]]))
        return "\n".join(lines)

    def dump(self, path: str) -> None:
        payload = {
            "edges": [
                {"from": a, "to": b, "count": n}
                for (a, b), n in sorted(self.edges().items())
            ],
            "cycles": self.cycles(),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)

    def reset(self) -> None:
        with self._raw:
            self._edges.clear()


def _creation_site() -> str:
    """file:line of the Lock()/RLock() call — the lock's identity in the
    graph (every instance from one site shares ordering discipline, the
    same canonicalization the static checker uses for classes)."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if fn == _HERE or fn.endswith(("threading.py", "lockrec.py")):
            continue
        return f"{fn}:{frame.lineno}"
    return "<unknown>"


class _ObservedLock:
    """Wrapper around a raw lock/RLock that reports acquire/release to the
    recorder. Implements the full lock protocol (including the
    _release_save/_acquire_restore/_is_owned trio Condition variables use
    on RLocks, forwarded so waits stay correct — a Condition wait's
    release/reacquire is deliberately NOT recorded as fresh ordering)."""

    def __init__(self, inner, site: str, rec: LockOrderRecorder):
        self._inner = inner
        self._site = site
        self._rec = rec

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._rec.on_acquire(self._site)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._rec.on_release(self._site)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"<observed {self._inner!r} from {self._site}>"

    # Condition-variable protocol, forwarded ONLY when the inner lock has
    # it (RLock): Condition binds these at construction under try/except
    # AttributeError, and a plain Lock must keep raising so Condition
    # falls back to its acquire/release defaults. A Condition wait's
    # release/reacquire through these is deliberately NOT recorded as
    # fresh ordering — the thread still owns its ordering position, it
    # just parked the lock.
    def __getattr__(self, name: str):
        if name in ("_release_save", "_acquire_restore", "_is_owned", "_at_fork_reinit"):
            return getattr(self._inner, name)
        raise AttributeError(name)


_installed: Optional[tuple] = None
GLOBAL_RECORDER = LockOrderRecorder()


def install(recorder: Optional[LockOrderRecorder] = None) -> LockOrderRecorder:
    """Monkeypatch threading.Lock/RLock with observed factories. Idempotent;
    returns the active recorder. Must run before the package's modules are
    imported for module-level locks to be observed (conftest order)."""
    global _installed
    rec = recorder or GLOBAL_RECORDER
    if _installed is not None:
        return _installed[2]
    orig_lock, orig_rlock = threading.Lock, threading.RLock

    def make_lock():
        return _ObservedLock(orig_lock(), _creation_site(), rec)

    def make_rlock():
        return _ObservedLock(orig_rlock(), _creation_site(), rec)

    threading.Lock = make_lock  # type: ignore[misc]
    threading.RLock = make_rlock  # type: ignore[misc]
    _installed = (orig_lock, orig_rlock, rec)
    return rec


def uninstall() -> None:
    global _installed
    if _installed is None:
        return
    threading.Lock, threading.RLock = _installed[0], _installed[1]
    _installed = None


def active_recorder() -> Optional[LockOrderRecorder]:
    return _installed[2] if _installed is not None else None
