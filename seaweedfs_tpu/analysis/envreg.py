"""env-var registry checkers.

env-raw-read: every configuration read must go through the typed
registry in utils/config.py (config.env) — raw
`os.environ[...]` / `os.environ.get(...)` / `os.getenv(...)` reads
scattered across modules are how defaults drift apart (the pipeline
depth was clamped in one place and not another before the registry).
Writes (`os.environ[k] = v`) and whole-environment passthrough
(`dict(os.environ)` for subprocess envs) are NOT flagged — they are
process plumbing, not configuration reads. utils/config.py itself is
exempt: it IS the registry.

env-unregistered: `config.env("NAME")` with a static name missing from
ENV_REGISTRY — a typo'd knob must fail in CI, not read as a silent
default forever (the runtime raises too; this catches it before any
test exercises the path).
"""

from __future__ import annotations

import ast

from seaweedfs_tpu.analysis import FileContext, Finding, per_file_checker

_EXEMPT_SUFFIX = ("utils/config.py",)


def _is_os_environ(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    )


@per_file_checker
def check_env_raw_read(ctx: FileContext) -> list[Finding]:
    if ctx.rel.replace("\\", "/").endswith(_EXEMPT_SUFFIX):
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        # os.getenv(...)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "getenv"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "os"
        ):
            findings.append(Finding(
                "env-raw-read", ctx.rel, node.lineno,
                "os.getenv() — read through the utils/config.py registry "
                "(config.env) instead",
            ))
        # os.environ.get(...) / os.environ.setdefault(...)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "setdefault")
            and _is_os_environ(node.func.value)
        ):
            findings.append(Finding(
                "env-raw-read", ctx.rel, node.lineno,
                f"os.environ.{node.func.attr}() — read through the "
                "utils/config.py registry (config.env) instead",
            ))
        # os.environ[...] in Load position (subscript writes/deletes are
        # plumbing: benches and tests set the environment on purpose)
        elif (
            isinstance(node, ast.Subscript)
            and _is_os_environ(node.value)
            and isinstance(node.ctx, ast.Load)
        ):
            findings.append(Finding(
                "env-raw-read", ctx.rel, node.lineno,
                "os.environ[...] read — go through the utils/config.py "
                "registry (config.env) instead",
            ))
    return findings


@per_file_checker
def check_env_unregistered(ctx: FileContext) -> list[Finding]:
    # the registry itself is import-light (no jax, no package deps), so
    # the checker can consult the live catalog
    from seaweedfs_tpu.utils.config import ENV_REGISTRY

    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        f = node.func
        is_env_call = (
            isinstance(f, ast.Attribute)
            and f.attr == "env"
            and isinstance(f.value, ast.Name)
            and f.value.id == "config"
        ) or (isinstance(f, ast.Name) and f.id == "env")
        if not is_env_call:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if name.startswith("WEEDTPU_") and name not in ENV_REGISTRY:
                findings.append(Finding(
                    "env-unregistered", ctx.rel, node.lineno,
                    f"config.env({name!r}) — not in ENV_REGISTRY; register "
                    "it in utils/config.py (name, type, default, doc)",
                ))
    return findings
