"""resource-safety checkers.

open-no-ctx: a bare `open()` whose handle is not scoped by a `with`
(or handed to an ExitStack via `enter_context`) leaks the descriptor on
any exception between open and close. Long-lived handles that are
genuinely owned by an object (EcVolume's serving shard handles) are the
intentional exception — suppressed inline with a reason, which is
exactly what the suppression policy is for.

tmpfile-no-unlink: `NamedTemporaryFile(delete=False)` hands YOU the
unlink obligation; a function that creates one and never unlinks,
removes, or os.replace()s it litters the spool directory on every
failure — the drain+unlink discipline the streaming encode/rebuild
paths follow.
"""

from __future__ import annotations

import ast

from seaweedfs_tpu.analysis import FileContext, Finding, per_file_checker


def _is_with_context(ctx: FileContext, call: ast.Call) -> bool:
    parent = ctx.parent(call)
    return isinstance(parent, ast.withitem) and parent.context_expr is call


def _is_enter_context_arg(ctx: FileContext, call: ast.Call) -> bool:
    parent = ctx.parent(call)
    return (
        isinstance(parent, ast.Call)
        and isinstance(parent.func, ast.Attribute)
        and parent.func.attr in ("enter_context", "push", "callback")
        and call in parent.args
    )


@per_file_checker
def check_open_no_ctx(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
        ):
            continue
        if _is_with_context(ctx, node) or _is_enter_context_arg(ctx, node):
            continue
        findings.append(Finding(
            "open-no-ctx", ctx.rel, node.lineno,
            "open() outside a with/ExitStack — the handle leaks on any "
            "exception before close()",
        ))
    return findings


def _has_delete_false(call: ast.Call) -> bool:
    return any(
        kw.arg == "delete"
        and isinstance(kw.value, ast.Constant)
        and kw.value.value is False
        for kw in call.keywords
    )


_CONSUMERS = {"unlink", "remove", "replace", "rename"}


@per_file_checker
def check_tmpfile_no_unlink(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for fdef in ast.walk(ctx.tree):
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tmp_sites = []
        consumed = False
        for node in ast.walk(fdef):
            if isinstance(node, ast.Call):
                f = node.func
                callee = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if callee == "NamedTemporaryFile" and _has_delete_false(node):
                    tmp_sites.append(node.lineno)
                if callee in _CONSUMERS:
                    consumed = True
        if not consumed:
            for line in tmp_sites:
                findings.append(Finding(
                    "tmpfile-no-unlink", ctx.rel, line,
                    f"NamedTemporaryFile(delete=False) in `{fdef.name}` "
                    "with no unlink/remove/replace in the same function — "
                    "the temp file outlives every failure path",
                ))
    return findings
