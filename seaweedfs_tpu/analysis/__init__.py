"""weedlint — project-specific static analysis for seaweedfs_tpu.

The codebase's correctness rests on invariants no general-purpose linter
knows about: lock acquisition order across the EC/cluster hot paths,
the donation contract of jitted dispatches (a donated buffer is DEAD
after the call), the WEEDTPU_* env registry in utils/config.py, the
context-managed-open discipline of streaming paths, and the three-way
agreement between contracts.proto, the committed descriptor artifact,
and the dict-shaped RPC handlers. These rot silently as PRs land and
resurface as heisenbugs in chaos_soak.py rather than tier-1 failures —
so they are machine-checked here, in tier-1, on every run.

Usage:
    python -m seaweedfs_tpu.analysis [--strict] [--changed-only] [paths]

Checker families (rule ids in brackets):
  lock-discipline   [lock-order-cycle, unlocked-global-write]
  donation-safety   [jit-host-sync, donated-buffer-read]
  env-registry      [env-raw-read, env-unregistered]
  resource-safety   [open-no-ctx, tmpfile-no-unlink]
  wire-drift        [wire-drift]
  obs-drift         [obs-metric-undeclared, obs-metric-unused,
                     obs-span-undeclared, obs-span-unused]
  durability        [fsync-missing-before-rename, record-before-fsync,
                     tmp-visible-name, torn-tail-unhandled]

Suppression: a finding is intentional iff the offending line (or the
line above it) carries a comment of the form "weedlint: ignore" plus
the bracketed rule id and a free-text reason. The reason is
REQUIRED — an ignore without one is itself a finding
(bad-suppression), and an ignore that suppresses nothing is flagged in
--strict runs (unused-suppression) so stale pragmas cannot accumulate.

The dynamic half of the lock-discipline family lives in
`analysis.lockrec`: an opt-in instrumented-lock mode (WEEDTPU_LOCK_OBSERVE=1
via tests/conftest.py) records ACTUAL acquisition orders during the
tier-1 run and fails the session if the observed graph has a cycle.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Iterable, Optional

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)

#: every rule a checker may emit (suppression comments are validated
#: against this set so a typo'd rule name cannot silently ignore-all)
RULES = {
    "lock-order-cycle": "two code paths acquire the same locks in opposite orders",
    "unlocked-global-write": "module-level mutable state written from an executor/thread callback outside any lock",
    "jit-host-sync": "host synchronization (np.*, open, print, .block_until_ready) inside a jitted function",
    "donated-buffer-read": "a buffer read after being passed at a donate_argnums position",
    "env-raw-read": "raw os.environ/os.getenv read outside the utils/config.py registry",
    "env-unregistered": "config.env() called with a name missing from ENV_REGISTRY",
    "open-no-ctx": "open() outside a with/ExitStack context",
    "tmpfile-no-unlink": "NamedTemporaryFile(delete=False) with no unlink/replace in the same function",
    "wire-drift": "contracts.proto, contracts.desc and handler field usage disagree",
    "obs-metric-undeclared": "a weedtpu_* metric name used in code is not declared in stats/__init__.py",
    "obs-metric-unused": "a metric declared in stats/__init__.py is never referenced (dead telemetry)",
    "obs-span-undeclared": "a trace span name used at a call site is missing from obs/trace.py SPAN_NAMES",
    "obs-span-unused": "a SPAN_NAMES catalog entry has no recording call site",
    "fsync-missing-before-rename": "a path opened for writing is renamed into place with no fsync in between",
    "record-before-fsync": "a journal record that vouches for data bytes is appended before the data fsync",
    "tmp-visible-name": "staged output created under a serving-discoverable name instead of .inp/.cv.*/dot-tmp",
    "torn-tail-unhandled": "a JSON-lines journal reader lacking the torn-tail truncate/ignore guard",
    "bad-suppression": "weedlint: ignore[...] without a reason, or naming an unknown rule",
    "unused-suppression": "weedlint: ignore[...] that suppresses no finding",
    "parse-error": "source file the analysis (and CI) cannot parse",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_SUPPRESS_RE = re.compile(r"#\s*weedlint:\s*ignore\[([^\]]*)\]\s*(.*)")


@dataclasses.dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]
    reason: str
    used: bool = False


class FileContext:
    """One parsed source file: tree, parent links, and suppressions."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.rel = os.path.relpath(path, REPO_ROOT)
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.parents: dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
        self.suppressions: list[Suppression] = []
        for lineno, text in enumerate(src.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                self.suppressions.append(
                    Suppression(lineno, rules, m.group(2).strip())
                )

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def suppression_findings(self) -> list[Finding]:
        out = []
        for s in self.suppressions:
            unknown = [r for r in s.rules if r != "*" and r not in RULES]
            if unknown:
                out.append(Finding(
                    "bad-suppression", self.rel, s.line,
                    f"ignore names unknown rule(s) {unknown}",
                ))
            if not s.reason:
                out.append(Finding(
                    "bad-suppression", self.rel, s.line,
                    "suppression has no reason — say why the finding is intentional",
                ))
        return out

    def apply_suppressions(self, findings: list[Finding]) -> list[Finding]:
        """Drop findings covered by an ignore on the same line or the line
        above; mark the suppression used."""
        kept = []
        for f in findings:
            hit = None
            for s in self.suppressions:
                if s.line in (f.line, f.line - 1) and (
                    "*" in s.rules or f.rule in s.rules
                ):
                    hit = s
                    break
            if hit is not None:
                hit.used = True
            else:
                kept.append(f)
        return kept

    def unused_suppression_findings(self) -> list[Finding]:
        return [
            Finding(
                "unused-suppression", self.rel, s.line,
                f"ignore[{','.join(s.rules)}] suppresses no finding — remove it",
            )
            for s in self.suppressions
            # unknown-rule pragmas already got bad-suppression; piling an
            # unused report on the same line is noise
            if not s.used and all(r == "*" or r in RULES for r in s.rules)
        ]


# checker registries — modules below self-register at import time
PerFileChecker = Callable[[FileContext], list[Finding]]
ProjectChecker = Callable[[list[FileContext], str], list[Finding]]
PER_FILE_CHECKERS: list[PerFileChecker] = []
PROJECT_CHECKERS: list[ProjectChecker] = []


def per_file_checker(fn: PerFileChecker) -> PerFileChecker:
    PER_FILE_CHECKERS.append(fn)
    return fn


def project_checker(fn: ProjectChecker) -> ProjectChecker:
    PROJECT_CHECKERS.append(fn)
    return fn


def iter_source_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        ]
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


# Parse cache shared across runs (and across checker families, which all
# consume the same FileContext): keyed by absolute path, validated by
# (mtime_ns, size). Parsing + parent-linking dominates a full-tree run, and
# the CLI gate + tests parse the same ~200 files repeatedly — the cache
# keeps the strict clean-tree gate inside its 30 s tier-1 budget as the
# tree grows. FileContext carries one piece of per-run mutable state
# (Suppression.used), reset on every cache hit.
_PARSE_CACHE: dict[str, tuple[tuple[int, int], "FileContext"]] = {}


def load_files(paths: Iterable[str]) -> tuple[list[FileContext], list[Finding]]:
    ctxs, errors = [], []
    for path in paths:
        apath = os.path.abspath(path)
        try:
            st = os.stat(apath)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            sig = None
        if sig is not None:
            hit = _PARSE_CACHE.get(apath)
            if hit is not None and hit[0] == sig:
                ctx = hit[1]
                for s in ctx.suppressions:
                    s.used = False
                ctxs.append(ctx)
                continue
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            ctx = FileContext(path, src)
        except SyntaxError as e:  # a file the CI can't even parse IS a finding
            errors.append(Finding(
                "parse-error", os.path.relpath(path, REPO_ROOT),
                e.lineno or 1, f"unparseable source: {e.msg}",
            ))
            continue
        ctxs.append(ctx)
        if sig is not None:
            _PARSE_CACHE[apath] = (sig, ctx)
    return ctxs, errors


def run(
    paths: Optional[list[str]] = None,
    root: str = PKG_ROOT,
    strict: bool = False,
    changed_only_files: Optional[set[str]] = None,
) -> list[Finding]:
    """Run every checker. `paths` overrides the scanned file set (tests
    point this at fixture trees); `changed_only_files` narrows PER-FILE
    checkers to a subset while project checkers (lock graph, wire drift)
    still see the whole tree — their invariants are global."""
    if paths is None:
        paths = list(iter_source_files(root))
    ctxs, findings = load_files(paths)
    for ctx in ctxs:
        scan_this = (
            changed_only_files is None
            or os.path.abspath(ctx.path) in changed_only_files
        )
        file_findings: list[Finding] = []
        if scan_this:
            for chk in PER_FILE_CHECKERS:
                file_findings.extend(chk(ctx))
        file_findings = ctx.apply_suppressions(file_findings)
        if scan_this:
            file_findings.extend(ctx.suppression_findings())
        findings.extend(file_findings)
    for chk in PROJECT_CHECKERS:
        project = chk(ctxs, root)
        # project findings honor per-file suppressions too
        by_rel: dict[str, list[Finding]] = {}
        for f in project:
            by_rel.setdefault(f.path, []).append(f)
        for ctx in ctxs:
            if ctx.rel in by_rel:
                by_rel[ctx.rel] = ctx.apply_suppressions(by_rel[ctx.rel])
        for rel, fs in by_rel.items():
            findings.extend(fs)
    if strict:
        for ctx in ctxs:
            if (
                changed_only_files is None
                or os.path.abspath(ctx.path) in changed_only_files
            ):
                findings.extend(ctx.unused_suppression_findings())
    # dedupe: a site inside nested defs can be visited once per enclosing
    # scope (e.g. tmpfile-no-unlink); one report per (rule, site, message)
    findings = sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))
    return findings


# register the checker families (import order = report grouping only)
from seaweedfs_tpu.analysis import donation  # noqa: E402,F401
from seaweedfs_tpu.analysis import durability  # noqa: E402,F401
from seaweedfs_tpu.analysis import envreg  # noqa: E402,F401
from seaweedfs_tpu.analysis import lock_order  # noqa: E402,F401
from seaweedfs_tpu.analysis import obs_drift  # noqa: E402,F401
from seaweedfs_tpu.analysis import resources  # noqa: E402,F401
from seaweedfs_tpu.analysis import wire_drift  # noqa: E402,F401
