"""obs-drift checker family (project-wide).

The observability layer has two closed catalogs that dashboards, the
weedload scraper, `ec.status`, and the tail-attribution artifact all key
on by STRING — so they rot silently:

  1. metric names: every `weedtpu_*` metric must be declared ONCE in
     `stats/__init__.py` (REGISTRY.counter/gauge/histogram). A scrape
     list or shell summary referencing an undeclared name reads zeros
     forever; a declared metric nobody increments or scrapes is dead
     weight that LOOKS like telemetry.
  2. span names: every `span("...")`/`start("...")`/`ensure("...")`
     call site must name a stage registered in `obs/trace.py`'s
     SPAN_NAMES, and every registered stage must have a call site —
     the attribution artifact's stage keys are these strings verbatim.

Rules:
  obs-metric-undeclared  a metric-shaped string literal (suffix _total/
                         _seconds/_count/_sum/_bucket/_inflight) not in
                         the stats registry. Plain `weedtpu_*` strings
                         WITHOUT a metric suffix are ignored — native C
                         symbol names and ContextVar labels share the
                         prefix.
  obs-metric-unused      a registry declaration whose binding name and
                         metric string appear nowhere else in the tree.
  obs-span-undeclared    a trace call site naming a stage missing from
                         SPAN_NAMES.
  obs-span-unused        a SPAN_NAMES entry no call site uses.

Like wire-drift, the declaration sources resolve RELATIVE TO THE
SCANNED ROOT (`<root>/stats/__init__.py`, `<root>/obs/trace.py`), so the
planted-violation fixture tree exercises the checker end to end without
touching the real catalogs.
"""

from __future__ import annotations

import ast
import os
import re

from seaweedfs_tpu.analysis import (
    REPO_ROOT,
    FileContext,
    Finding,
    project_checker,
)

_METRIC_LITERAL = re.compile(r"^weedtpu_[a-z0-9_]+$")
_METRIC_SUFFIX = re.compile(
    r"^weedtpu_[a-z0-9_]+_(total|seconds|count|sum|bucket|inflight)$"
)
#: exposition-format suffixes a histogram's scraped series carry on top
#: of its declared name
_SERIES_SUFFIXES = ("_count", "_sum", "_bucket")
#: trace call spellings the package uses: module-qualified (any alias
#: containing "trace") or the bare contextmanager name
_SPAN_FNS = ("span", "start", "ensure", "continue_trace", "traced")


def _parse_metric_decls(path: str):
    """{metric_name: (binding, line)} from a stats registry module:
    `Binding = REGISTRY.counter("weedtpu_...", ...)` shapes."""
    out: dict[str, tuple[str, int]] = {}
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt, val = node.targets[0], node.value
        if not (
            isinstance(tgt, ast.Name)
            and isinstance(val, ast.Call)
            and isinstance(val.func, ast.Attribute)
            and val.func.attr in ("counter", "gauge", "histogram")
            and val.args
            and isinstance(val.args[0], ast.Constant)
            and isinstance(val.args[0].value, str)
        ):
            continue
        out[val.args[0].value] = (tgt.id, node.lineno)
    return out


def _parse_span_catalog(path: str):
    """{span_name: line} from SPAN_NAMES = {...} in obs/trace.py."""
    out: dict[str, int] = {}
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for tgt in targets:
            if (
                isinstance(tgt, ast.Name)
                and tgt.id == "SPAN_NAMES"
                and isinstance(value, ast.Dict)
            ):
                for key in value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        out[key.value] = key.lineno
    return out


def _is_span_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _SPAN_FNS
    if isinstance(f, ast.Attribute) and f.attr in _SPAN_FNS:
        base = f.value
        return isinstance(base, ast.Name) and "trace" in base.id.lower()
    return False


@project_checker
def check_obs_drift(ctxs: list[FileContext], root: str) -> list[Finding]:
    stats_path = os.path.join(root, "stats", "__init__.py")
    catalog_path = os.path.join(root, "obs", "trace.py")
    metrics = _parse_metric_decls(stats_path)
    spans = _parse_span_catalog(catalog_path)
    if not metrics and not spans:
        return []  # tree without an obs layer (other fixture pkgs)
    stats_rel = os.path.relpath(stats_path, REPO_ROOT)
    catalog_rel = os.path.relpath(catalog_path, REPO_ROOT)

    findings: list[Finding] = []
    used_metrics: set[str] = set()
    used_spans: set[str] = set()
    for ctx in ctxs:
        is_decl_file = ctx.rel in (stats_rel, catalog_rel)
        for node in ast.walk(ctx.tree):
            # referenced binding names (stats.ScrubRepairs / imported name)
            if isinstance(node, ast.Attribute):
                names = {node.attr}
            elif isinstance(node, ast.Name):
                names = {node.id}
            else:
                names = ()
            for name in names:
                for metric, (binding, _) in metrics.items():
                    if name == binding and not is_decl_file:
                        used_metrics.add(metric)
            # metric-shaped string literals (scrape lists, ec.status)
            if (
                not is_decl_file
                and isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _METRIC_LITERAL.match(node.value)
            ):
                lit = node.value
                base = lit
                for suffix in _SERIES_SUFFIXES:
                    if lit.endswith(suffix) and lit[: -len(suffix)] in metrics:
                        base = lit[: -len(suffix)]
                        break
                if base in metrics:
                    used_metrics.add(base)
                elif _METRIC_SUFFIX.match(lit):
                    findings.append(Finding(
                        "obs-metric-undeclared", ctx.rel, node.lineno,
                        f"metric {lit!r} is not declared in "
                        "stats/__init__.py — scrapes of it read zeros "
                        "forever; declare it (or fix the name)",
                    ))
            # span call sites
            if (
                not is_decl_file
                and isinstance(node, ast.Call)
                and _is_span_call(node)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                name = node.args[0].value
                if name in spans:
                    used_spans.add(name)
                else:
                    findings.append(Finding(
                        "obs-span-undeclared", ctx.rel, node.lineno,
                        f"span name {name!r} is not in the SPAN_NAMES "
                        "catalog (obs/trace.py) — the attribution "
                        "artifact and ec.trace key on registered stage "
                        "names; register it (or fix the typo)",
                    ))
    for metric, (binding, line) in sorted(metrics.items()):
        if metric not in used_metrics:
            findings.append(Finding(
                "obs-metric-unused", stats_rel, line,
                f"metric {metric!r} ({binding}) is declared but neither "
                "its binding nor its name is referenced anywhere — dead "
                "telemetry; wire it up or delete it",
            ))
    for name, line in sorted(spans.items()):
        if name not in used_spans:
            findings.append(Finding(
                "obs-span-unused", catalog_rel, line,
                f"span name {name!r} is registered in SPAN_NAMES but no "
                "call site records it — stale catalog entry",
            ))
    return findings
