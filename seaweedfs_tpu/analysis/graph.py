"""Tiny digraph utilities shared by the static lock-order checker and
the dynamic lock-order recorder: strongly-connected components (iterative
Tarjan — checker input is arbitrary user code, so no recursion limits)
and cycle extraction."""

from __future__ import annotations

from typing import Hashable, Iterable


def strongly_connected_components(
    edges: dict[Hashable, set],
) -> list[list[Hashable]]:
    """Tarjan SCCs over `node -> successor set` (nodes appearing only as
    successors are included)."""
    nodes = set(edges)
    for succs in edges.values():
        nodes |= set(succs)
    index: dict = {}
    lowlink: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list[list] = []
    counter = [0]

    for root in sorted(nodes, key=repr):
        if root in index:
            continue
        # iterative Tarjan: work items are (node, iterator over successors)
        work = [(root, iter(sorted(edges.get(root, ()), key=repr)))]
        index[root] = lowlink[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = lowlink[succ] = counter[0]
                    counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append(
                        (succ, iter(sorted(edges.get(succ, ()), key=repr)))
                    )
                    advanced = True
                    break
                if succ in on_stack:
                    lowlink[node] = min(lowlink[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs


def cyclic_components(edges: dict[Hashable, set]) -> list[list[Hashable]]:
    """SCCs that actually contain a cycle: size > 1, or a self-loop."""
    out = []
    for scc in strongly_connected_components(edges):
        if len(scc) > 1 or (len(scc) == 1 and scc[0] in edges.get(scc[0], ())):
            out.append(sorted(scc, key=repr))
    return out


def edges_from_pairs(pairs: Iterable[tuple]) -> dict:
    edges: dict = {}
    for a, b in pairs:
        edges.setdefault(a, set()).add(b)
    return edges
