"""lock-discipline checkers.

lock-order-cycle (project-wide): every `with <lock>:` nesting in the
package contributes an acquisition-order edge (outer -> inner) to one
global digraph; a cycle means two code paths can interleave into a
deadlock that only chaos_soak would ever catch. Lock identity is
canonical across files: `self._x_lock` inside class C is `C._x_lock`
(every instance shares the ordering discipline), a module-level lock is
`<module>:<name>`.

unlocked-global-write (per-file): module-level mutable containers
mutated from a function that is handed to an executor/thread
(`submit(f)`, `Thread(target=f)`, `add_done_callback(f)`) without a
`with <lock>:` around the mutation — the classic torn-update heisenbug.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from seaweedfs_tpu.analysis import (
    FileContext,
    Finding,
    graph,
    per_file_checker,
    project_checker,
)

#: what counts as a lock object in a `with` item. The codebase's locks all
#: carry "lock" in their name (_lock, _suspect_lock, _shard_locs_lock ...);
#: condition variables guard with their own lock so they count too.
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex|cond|condition)s?($|_)|lock$", re.I)

_MUTATORS = {
    "append", "appendleft", "add", "update", "setdefault", "extend",
    "insert", "pop", "popleft", "popitem", "remove", "discard", "clear",
}


def _lock_name_of(expr: ast.AST) -> Optional[str]:
    """The bare name a with-item acquires, when it looks like a lock."""
    if isinstance(expr, ast.Name) and _LOCK_NAME_RE.search(expr.id):
        return expr.id
    if isinstance(expr, ast.Attribute) and _LOCK_NAME_RE.search(expr.attr):
        return expr.attr
    return None


def _canonical(ctx: FileContext, expr: ast.AST, class_name: Optional[str]) -> Optional[str]:
    name = _lock_name_of(expr)
    if name is None:
        return None
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id in ("self", "cls")
        and class_name
    ):
        return f"{class_name}.{name}"
    if isinstance(expr, ast.Name):
        return f"{ctx.rel}:{name}"
    # foreign attribute chain (other.lock): scope by source text
    return f"{ctx.rel}:{ast.unparse(expr)}"


class _LockNestingVisitor(ast.NodeVisitor):
    """Collects (outer, inner, site) acquisition edges from lexical
    `with` nesting. The held-stack resets inside nested function defs —
    their bodies run later, not under the enclosing with."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.class_stack: list[str] = []
        self.held: list[str] = []
        self.edges: list[tuple[str, str, int]] = []
        self.sites: dict[str, int] = {}  # lock -> first acquisition line

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_function(self, node) -> None:
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function
    visit_Lambda = _visit_function

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        cls = self.class_stack[-1] if self.class_stack else None
        for item in node.items:
            lock = _canonical(self.ctx, item.context_expr, cls)
            if lock is not None:
                self.sites.setdefault(lock, item.context_expr.lineno)
                for outer in self.held:
                    if outer != lock:
                        self.edges.append((outer, lock, item.context_expr.lineno))
                self.held.append(lock)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With


@project_checker
def check_lock_order(ctxs: list[FileContext], root: str) -> list[Finding]:
    edge_sites: dict[tuple[str, str], tuple[str, int]] = {}
    for ctx in ctxs:
        v = _LockNestingVisitor(ctx)
        v.visit(ctx.tree)
        for outer, inner, line in v.edges:
            edge_sites.setdefault((outer, inner), (ctx.rel, line))
    edges = graph.edges_from_pairs(edge_sites)
    findings = []
    for cycle in graph.cyclic_components(edges):
        members = set(cycle)
        for (outer, inner), (rel, line) in sorted(edge_sites.items()):
            if outer in members and inner in members:
                findings.append(Finding(
                    "lock-order-cycle", rel, line,
                    f"acquires {inner} while holding {outer}, inside the "
                    f"ordering cycle {{{', '.join(cycle)}}} — pick one "
                    "global order for these locks",
                ))
    return findings


def _callback_names(tree: ast.AST) -> set[str]:
    """Function names handed to executors/threads in this file. Both bare
    functions (`submit(f)`) and bound methods (`submit(self._f)`,
    `Thread(target=self._loop)`) count — the package's real entry points
    are almost all bound methods, and a checker that only saw bare names
    would have zero recall on the code it guards."""
    names: set[str] = set()

    def _name_of(arg: ast.AST) -> Optional[str]:
        if isinstance(arg, ast.Name):
            return arg.id
        if isinstance(arg, ast.Attribute):
            return arg.attr
        return None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if attr in ("submit", "add_done_callback", "map"):
            for arg in node.args[:1]:
                n = _name_of(arg)
                if n:
                    names.add(n)
        if attr in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg == "target":
                    n = _name_of(kw.value)
                    if n:
                        names.add(n)
    return names


def _module_mutables(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    mutable_calls = {"dict", "list", "set", "deque", "defaultdict", "OrderedDict", "Counter"}
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
            value = stmt.value
        else:
            continue
        is_mutable = isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp))
        if isinstance(value, ast.Call):
            f = value.func
            callee = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None
            )
            is_mutable = is_mutable or callee in mutable_calls
        if not is_mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


class _CallbackBodyVisitor(ast.NodeVisitor):
    """Inside one callback function: flag mutations of module-level
    mutables that are not under any `with <lock>:`."""

    def __init__(self, ctx: FileContext, mutables: set[str]):
        self.ctx = ctx
        self.mutables = mutables
        self.lock_depth = 0
        self.findings: list[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        locked = any(_lock_name_of(i.context_expr) for i in node.items)
        if locked:
            self.lock_depth += 1
        self.generic_visit(node)
        if locked:
            self.lock_depth -= 1

    visit_AsyncWith = visit_With

    def _flag(self, node: ast.AST, name: str, how: str) -> None:
        if self.lock_depth == 0:
            self.findings.append(Finding(
                "unlocked-global-write", self.ctx.rel, node.lineno,
                f"{how} of module-level `{name}` from an executor/thread "
                "callback without a held lock",
            ))

    def _target_global(self, target: ast.AST) -> Optional[str]:
        if isinstance(target, ast.Subscript) and isinstance(target.value, ast.Name):
            if target.value.id in self.mutables:
                return target.value.id
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            name = self._target_global(t)
            if name:
                self._flag(node, name, "subscript write")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        name = self._target_global(node.target)
        if name:
            self._flag(node, name, "augmented write")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            name = self._target_global(t)
            if name:
                self._flag(node, name, "subscript delete")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _MUTATORS
            and isinstance(f.value, ast.Name)
            and f.value.id in self.mutables
        ):
            self._flag(node, f.value.id, f".{f.attr}()")
        self.generic_visit(node)


@per_file_checker
def check_unlocked_global_writes(ctx: FileContext) -> list[Finding]:
    if not isinstance(ctx.tree, ast.Module):
        return []
    mutables = _module_mutables(ctx.tree)
    if not mutables:
        return []
    callbacks = _callback_names(ctx.tree)
    if not callbacks:
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name in callbacks:
            v = _CallbackBodyVisitor(ctx, mutables)
            for stmt in node.body:
                v.visit(stmt)
            findings.extend(v.findings)
    return findings
