"""dispatch/donation safety checkers.

jit-host-sync (per-file): a function compiled by `jax.jit` runs as one
async device dispatch; host work inside it — numpy materialization,
file I/O, `.block_until_ready()`, print — either breaks tracing or
silently serializes the pipeline the streaming paths spent three PRs
overlapping. Flag it at the call site.

donated-buffer-read (per-file): `jax.jit(..., donate_argnums=...)`
transfers ownership of the donated argument's buffer to XLA — the
caller's array is DEAD after the dispatch (the `_StagingRing` reuse
contract from the streaming pipeline). Reading a name again after
passing it at a donated position is use-after-free that happens to work
on CPU and corrupts on device. The checker tracks names bound to
donated jits file-locally and flags any later read of a donated
argument in the same function unless it is re-bound first.

Both rules see THROUGH `shard_map` wrappers (the mesh backend's shape:
`jax.jit(shard_map(f, ...), donate_argnums=...)` bindings and
`@functools.partial(jax.jit, donate_argnums=...)` stacked over
`@functools.partial(shard_map, ...)` defs): the mapped body is traced
exactly like a jitted one, so host work inside it is flagged, and a
name passed at a donated position of the wrapped callable follows the
same dead-until-rebound rule.
"""

from __future__ import annotations

import ast
from typing import Optional

from seaweedfs_tpu.analysis import FileContext, Finding, per_file_checker

_HOST_SYNC_NP = {"asarray", "array", "frombuffer", "copyto", "save", "load"}
_HOST_SYNC_METHODS = {"block_until_ready", "tobytes", "item", "tolist"}


def _is_jit_call(node: ast.AST) -> bool:
    """`jax.jit(...)` / `jit(...)` / `functools.partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    if isinstance(f, ast.Attribute) and f.attr == "partial" or (
        isinstance(f, ast.Name) and f.id == "partial"
    ):
        return bool(node.args) and _is_jit_ref(node.args[0])
    return False


def _is_jit_ref(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "jit") or (
        isinstance(node, ast.Name) and node.id == "jit"
    )


def _is_shard_map_ref(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "shard_map") or (
        isinstance(node, ast.Name) and node.id == "shard_map"
    )


def _is_shard_map_call(node: ast.AST) -> bool:
    """`shard_map(f, ...)` / `functools.partial(shard_map, mesh=...)` —
    the mapped body is traced like a jitted one, so both checkers must
    see through the wrapper."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if _is_shard_map_ref(f):
        return True
    if isinstance(f, ast.Attribute) and f.attr == "partial" or (
        isinstance(f, ast.Name) and f.id == "partial"
    ):
        return bool(node.args) and _is_shard_map_ref(node.args[0])
    return False


def _jitted_function_defs(tree: ast.AST) -> list[ast.FunctionDef]:
    """Defs that run traced: decorated with jit/partial(jit) or
    shard_map/partial(shard_map), or passed by name to a `jax.jit(f, ...)`
    or `shard_map(f, ...)` call anywhere in the file."""
    jitted_names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_jit_call(node) or _is_shard_map_call(node):
            args = node.args
            # first positional arg is the wrapped callable — but in the
            # partial(jit/shard_map, ...) spelling it is the wrapper
            # itself, not a user function
            if (
                args
                and isinstance(args[0], ast.Name)
                and not _is_jit_ref(args[0])
                and not _is_shard_map_ref(args[0])
            ):
                jitted_names.add(args[0].id)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in jitted_names or any(
            _is_jit_call(d)
            or _is_jit_ref(d)
            or _is_shard_map_call(d)
            or _is_shard_map_ref(d)
            for d in node.decorator_list
        ):
            out.append(node)
    return out


@per_file_checker
def check_jit_host_sync(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for fdef in _jitted_function_defs(ctx.tree):
        for node in ast.walk(fdef):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in ("open", "print"):
                findings.append(Finding(
                    "jit-host-sync", ctx.rel, node.lineno,
                    f"`{f.id}(...)` inside jitted `{fdef.name}` — host I/O "
                    "does not belong in a traced dispatch",
                ))
            elif isinstance(f, ast.Attribute):
                if (
                    isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy")
                    and f.attr in _HOST_SYNC_NP
                ):
                    findings.append(Finding(
                        "jit-host-sync", ctx.rel, node.lineno,
                        f"`np.{f.attr}(...)` inside jitted `{fdef.name}` — "
                        "materializes on host mid-dispatch (use jnp)",
                    ))
                elif f.attr in _HOST_SYNC_METHODS:
                    findings.append(Finding(
                        "jit-host-sync", ctx.rel, node.lineno,
                        f"`.{f.attr}()` inside jitted `{fdef.name}` — "
                        "forces a device sync inside the traced region",
                    ))
    return findings


def _donated_positions(call: ast.Call) -> Optional[list[int]]:
    """The static donate_argnums of a jit(...) call, or None."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.append(elt.value)
            return out
    return None


def _donating_names(tree: ast.AST) -> dict[str, list[int]]:
    """name -> donated positions, for `g = jax.jit(f, donate_argnums=...)`
    bindings anywhere in the file (module or function scope; `f` may be a
    `shard_map(...)` wrapper — the binding is what donates), and for defs
    decorated with a donating jit (`@functools.partial(jax.jit,
    donate_argnums=...)`, typically stacked over a shard_map partial)."""
    out: dict[str, list[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if not (isinstance(node.value, ast.Call) and _is_jit_call(node.value)):
                continue
            pos = _donated_positions(node.value)
            if not pos:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                if isinstance(d, ast.Call) and _is_jit_call(d):
                    pos = _donated_positions(d)
                    if pos:
                        out[node.name] = pos
    return out


@per_file_checker
def check_donated_buffer_read(ctx: FileContext) -> list[Finding]:
    donating = _donating_names(ctx.tree)
    if not donating:
        return []
    findings: list[Finding] = []
    for fdef in ast.walk(ctx.tree):
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # (donated name, donation line) pairs within this function
        donated: list[tuple[str, int]] = []
        rebinds: dict[str, list[int]] = {}
        reads: list[tuple[str, int]] = []
        for node in ast.walk(fdef):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                pos = donating.get(node.func.id)
                if pos:
                    for p in pos:
                        if p < len(node.args) and isinstance(node.args[p], ast.Name):
                            donated.append((node.args[p].id, node.lineno))
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    rebinds.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node.ctx, ast.Load):
                    reads.append((node.id, node.lineno))
        for name, dline in donated:
            for rname, rline in reads:
                if rname != name or rline <= dline:
                    continue
                # a re-bind between donation and read revives the name
                if any(dline < b <= rline for b in rebinds.get(name, ())):
                    continue
                findings.append(Finding(
                    "donated-buffer-read", ctx.rel, rline,
                    f"`{name}` read after being donated on line {dline} — "
                    "the buffer belongs to XLA now (stage a fresh array, "
                    "or drop the donation)",
                ))
    return findings
