"""wire-drift checker (project-wide).

The RPC plane has three representations that must agree:

  1. `pb/contracts.proto` — the pinned schema (source of truth),
  2. `pb/contracts.desc` — the committed FileDescriptorSet artifact that
     serves protoc-less deploys (regenerated on demand when protoc is
     present, so it can silently go stale in a PR that edits the .proto),
  3. the dict-shaped handlers — `req["field"]` reads and `return {...}`
     literals whose keys ARE proto field names on the binary wire
     (an unknown key raises at conversion time, but only on the
     WEEDTPU_WIRE=proto path that tier-1 exercises least).

This checker cross-references all three: .proto vs .desc message/field
sets, wire.py's WRAPPER_FIELD registry vs the schema, and every
svc.add-registered handler's request-key reads and
response-literal keys vs the method's request/response messages.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from seaweedfs_tpu.analysis import REPO_ROOT, FileContext, Finding, project_checker

_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+|required\s+)?"
    r"(?:map\s*<[^>]+>|[\w.]+)\s+(\w+)\s*=\s*\d+"
)
_RPC_RE = re.compile(
    r"^\s*rpc\s+(\w+)\s*\(\s*(?:stream\s+)?([\w.]+)\s*\)\s*"
    r"returns\s*\(\s*(stream\s+)?([\w.]+)\s*\)"
)
_KEYWORDS = ("message ", "service ", "enum ", "rpc ", "option ", "syntax",
             "package", "import ", "reserved ")


def parse_proto(path: str):
    """-> (messages: {qualname: set(field names)}, lines: {qualname: line},
    methods: {method: [(request_msg, response_msg, resp_is_stream), ...]}).
    Message qualnames are dotted for nesting (Outer.Inner); method message
    refs resolve to the bare name as written in the rpc line."""
    messages: dict[str, set[str]] = {}
    msg_lines: dict[str, int] = {}
    methods: dict[str, list[tuple[str, str, bool]]] = {}
    stack: list[tuple[str, Optional[str]]] = []  # (kind, name)

    with open(path, "r", encoding="utf-8") as f:
        for lineno, raw in enumerate(f, start=1):
            line = raw.split("//", 1)[0].rstrip()
            if not line.strip():
                continue
            stripped = line.strip()
            m = re.match(r"^(message|service|enum|oneof)\s+(\w+)?", stripped)
            if m and "{" in stripped:
                kind, name = m.group(1), m.group(2)
                if kind == "message":
                    qual = ".".join(
                        [n for k, n in stack if k == "message" and n] + [name]
                    )
                    messages[qual] = set()
                    msg_lines[qual] = lineno
                    stack.append(("message", qual))
                    # one-line bodies carry their fields on the same line:
                    #   message LookupRequest { repeated string ids = 1; }
                    body = stripped.split("{", 1)[1]
                    for decl in body.split(";"):
                        fm = _FIELD_RE.match(decl.strip())
                        if fm:
                            messages[qual].add(fm.group(1))
                else:
                    stack.append((kind, name))
                if stripped.count("}") >= stripped.count("{"):
                    stack.pop()  # one-line body closes immediately
                continue
            rm = _RPC_RE.match(stripped)
            if rm:
                # same-named methods across services merge: the handler
                # check unions their fields (a per-service split would
                # need the Service() wiring, and union only under-flags)
                methods.setdefault(rm.group(1), []).append((
                    rm.group(2).split(".")[-1],
                    rm.group(4).split(".")[-1],
                    bool(rm.group(3)),
                ))
                continue
            # fields attribute to the nearest enclosing MESSAGE — a field
            # inside `oneof { ... }` belongs to the message, not the oneof
            owner = next(
                (n for k, n in reversed(stack) if k == "message"), None
            )
            if owner is not None and stack[-1][0] in ("message", "oneof"):
                fm = _FIELD_RE.match(line)
                if fm and not stripped.startswith(_KEYWORDS):
                    messages[owner].add(fm.group(1))
            if stripped.startswith("}") or stripped == "};":
                if stack:
                    stack.pop()
    return messages, msg_lines, methods


def _bare(messages: dict[str, set[str]]) -> dict[str, set[str]]:
    """Leaf-name view (handlers and rpc lines use bare names; collisions
    between a top-level and a nested message would be a schema smell this
    project does not have)."""
    out: dict[str, set[str]] = {}
    for qual, fields in messages.items():
        out[qual.split(".")[-1]] = fields
    return out


def _desc_messages(desc_path: str) -> Optional[dict[str, set[str]]]:
    """Message -> field-name sets from the committed descriptor artifact
    (map-entry synthetic messages skipped). None when the protobuf
    runtime is unavailable."""
    try:
        from google.protobuf import descriptor_pb2
    except ImportError:  # pragma: no cover — runtime ships in this image
        return None
    with open(desc_path, "rb") as f:
        fds = descriptor_pb2.FileDescriptorSet.FromString(f.read())
    out: dict[str, set[str]] = {}

    def walk(msg, prefix: str) -> None:
        if msg.options.map_entry:
            return
        qual = f"{prefix}.{msg.name}" if prefix else msg.name
        out[qual] = {f.name for f in msg.field}
        for nested in msg.nested_type:
            walk(nested, qual)

    for fdp in fds.file:
        for msg in fdp.message_type:
            walk(msg, "")
    return out


def _handler_map(ctx: FileContext) -> dict[str, str]:
    """handler function name -> RPC method name, from svc.add / bare-add
    registration calls whose first arg is the method string literal."""
    out: dict[str, str] = {}
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and len(node.args) >= 2):
            continue
        f = node.func
        callee = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if callee != "add":
            continue
        method, handler = node.args[0], node.args[1]
        if not (isinstance(method, ast.Constant) and isinstance(method.value, str)):
            continue
        if isinstance(handler, ast.Attribute):
            out[handler.attr] = method.value
        elif isinstance(handler, ast.Name):
            out[handler.id] = method.value
    return out


def _req_keys(fdef: ast.FunctionDef, req_name: str) -> list[tuple[str, int]]:
    keys = []
    for node in ast.walk(fdef):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == req_name
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
        ):
            keys.append((node.slice.value, node.lineno))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == req_name
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            keys.append((node.args[0].value, node.lineno))
    return keys


def _resp_literal_keys(fdef: ast.FunctionDef) -> list[tuple[str, int]]:
    """Constant keys of dict literals returned DIRECTLY by the handler
    (built-up response dicts are out of static reach; the wire codec
    still catches them at runtime on the proto path)."""
    keys = []
    for node in ast.walk(fdef):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.append((k.value, node.lineno))
    return keys


@project_checker
def check_wire_drift(ctxs: list[FileContext], root: str) -> list[Finding]:
    proto_path = os.path.join(root, "pb", "contracts.proto")
    if not os.path.exists(proto_path):
        return []
    proto_rel = os.path.relpath(proto_path, REPO_ROOT)
    messages, msg_lines, methods = parse_proto(proto_path)
    bare = _bare(messages)
    findings: list[Finding] = []

    # 1. committed descriptor artifact vs the .proto text
    desc_path = os.path.join(root, "pb", "contracts.desc")
    if os.path.exists(desc_path):
        desc = _desc_messages(desc_path)
        if desc is not None:
            for qual, fields in sorted(messages.items()):
                if qual not in desc:
                    findings.append(Finding(
                        "wire-drift", proto_rel, msg_lines.get(qual, 1),
                        f"message {qual} is in contracts.proto but not the "
                        "committed contracts.desc — regenerate the artifact "
                        "(pb.wire.regenerate_descriptor_artifact)",
                    ))
                elif fields != desc[qual]:
                    only_proto = sorted(fields - desc[qual])
                    only_desc = sorted(desc[qual] - fields)
                    findings.append(Finding(
                        "wire-drift", proto_rel, msg_lines.get(qual, 1),
                        f"message {qual} fields drifted from contracts.desc "
                        f"(proto-only: {only_proto}, desc-only: {only_desc}) "
                        "— regenerate the artifact",
                    ))
            for qual in sorted(set(desc) - set(messages)):
                findings.append(Finding(
                    "wire-drift", proto_rel, 1,
                    f"message {qual} is in contracts.desc but not "
                    "contracts.proto — regenerate the artifact",
                ))

    # 2. wire.py WRAPPER_FIELD registry vs the schema
    for ctx in ctxs:
        if not ctx.rel.replace("\\", "/").endswith("pb/wire.py"):
            continue
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "WRAPPER_FIELD"
                    for t in node.targets
                )
                and isinstance(node.value, ast.Dict)
            ):
                continue
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant) and isinstance(v, ast.Constant)):
                    continue
                msg = str(k.value).split(".")[-1]
                if msg not in bare:
                    findings.append(Finding(
                        "wire-drift", ctx.rel, k.lineno,
                        f"WRAPPER_FIELD names unknown message {k.value!r}",
                    ))
                elif str(v.value) not in bare[msg]:
                    findings.append(Finding(
                        "wire-drift", ctx.rel, k.lineno,
                        f"WRAPPER_FIELD[{k.value!r}] = {v.value!r} is not a "
                        f"field of {msg} (has {sorted(bare[msg])})",
                    ))

    # 3. handler request reads / response literals vs the schema
    for ctx in ctxs:
        handlers = _handler_map(ctx)
        if not handlers:
            continue
        for fdef in ast.walk(ctx.tree):
            if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            method = handlers.get(fdef.name)
            if method is None or method not in methods:
                continue
            entries = methods[method]
            req_msgs = sorted({e[0] for e in entries})
            resp_msgs = sorted({e[1] for e in entries if not e[2]})
            args = [a.arg for a in fdef.args.args]
            req_name = args[1] if args and args[0] == "self" and len(args) > 1 else (
                args[0] if args else None
            )
            req_fields: Optional[set[str]] = None
            for msg in req_msgs:
                if msg in bare:
                    req_fields = (req_fields or set()) | bare[msg]
            if req_name and req_fields is not None:
                for key, line in _req_keys(fdef, req_name):
                    if key not in req_fields:
                        findings.append(Finding(
                            "wire-drift", ctx.rel, line,
                            f"handler {fdef.name} ({method}) reads "
                            f"req[{key!r}] but {'/'.join(req_msgs)} has no "
                            f"such field (has {sorted(req_fields)})",
                        ))
            resp_fields: Optional[set[str]] = None
            for msg in resp_msgs:
                if msg in bare:
                    resp_fields = (resp_fields or set()) | bare[msg]
            if resp_fields is not None:
                for key, line in _resp_literal_keys(fdef):
                    if key not in resp_fields:
                        findings.append(Finding(
                            "wire-drift", ctx.rel, line,
                            f"handler {fdef.name} ({method}) returns key "
                            f"{key!r} but {'/'.join(resp_msgs)} has no such "
                            f"field (has {sorted(resp_fields)})",
                        ))
    return findings
