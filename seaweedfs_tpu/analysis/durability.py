"""durability checkers — the crash-consistency half of weedlint (weedsafe).

The tree carries four hand-rolled crash contracts (the `.ecp` ingest
journal, the `.ecc` convert journal with `.eci`-first cutover, the fsync'd
scrub cursor, and the crash-resumable kernel_sweep JSONL) plus a dozen
smaller tmp+rename publication sites. Each promises the same discipline:
flush+fsync staged bytes BEFORE the rename that publishes them, fsync data
BEFORE the journal record that vouches for it, stage under a
non-serving-discoverable name, and treat a torn JSON-lines tail as
end-of-journal rather than an error. These checkers machine-check the
lexically-visible part of that discipline; the dynamic half (recording
real op traces and replaying every crash prefix) lives in
`analysis.fsrec`.

fsync-missing-before-rename: a function opens a path for writing and
later os.replace()/os.rename()s that same path expression with no
fsync-looking call in between — the rename can publish a file whose
bytes are still in the page cache, so a crash yields an empty or torn
file under the FINAL name (the one state the tmp+rename idiom exists to
prevent). Scope-local and expression-matched on purpose: cross-function
handoffs (parts opened in __init__, sealed elsewhere) are the replayer's
job, not a lexical rule's.

record-before-fsync: a journal append whose payload is a watermark/rows
record (a dict literal with kind/type in {"rows", "watermark"}) with no
fsync-looking call earlier in the same function. A watermark record
VOUCHES for data bytes; journaling it before the data fsync means a crash
can leave a journal that testifies to bytes the disk never got. Intent
records ({"kind": "ow"}, deltas) are exempt — those are deliberately
journaled BEFORE the mutation they describe.

tmp-visible-name: a write/truncate-mode open() whose path ends in a
serving-discoverable suffix (.dat/.idx/.eci/.ecx/.ecj/.ecNN). Staged
output must be created under .inp/.cv.*/dot-tmp names and renamed into
place, or a reader (or crash) can observe a half-written final file.

torn-tail-unhandled: a loop over journal lines that json.loads() the
line with no ValueError/JSONDecodeError guard — a torn tail (the one
crash artifact every JSON-lines journal here is allowed to have) would
raise instead of terminating the read.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from seaweedfs_tpu.analysis import FileContext, Finding, per_file_checker

# modes that create/truncate a file (append/update modes never produce the
# "empty file under the final name" hazard these rules target)
_CREATE_MODE_RE = re.compile(r"^[wx]b?\+?$")
_WRITE_MODE_RE = re.compile(r"^[wxa]b?\+?$|^r\+b?$")

#: suffixes a serving/scan path discovers on disk — creating one of these
#: names directly (instead of staging + rename) races every reader
_SERVING_SUFFIX_RE = re.compile(r"\.(dat|idx|eci|ecx|ecj|ec\d\d)$")

#: journal-append seams: a call through one of these names carrying a
#: vouching record is the "record" side of record-before-fsync
_APPEND_NAMES = {"append", "_append", "_append_record", "append_ecj", "persist"}

#: record kinds that vouch for previously-written data bytes (vs intent
#: records, which are journaled BEFORE their mutation by design)
_VOUCHING_KINDS = {"rows", "watermark"}


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_fsync_call(call: ast.Call) -> bool:
    """os.fsync(...) or any helper whose name mentions fsync (covers
    `_fsync_all`, `fsync_dir`, methods like `self._fsync_parts`)."""
    name = _callee_name(call)
    return name is not None and "fsync" in name


def _is_os_call(call: ast.Call, attr: str) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == attr
        and isinstance(f.value, ast.Name)
        and f.value.id == "os"
    )


def _mode_of(call: ast.Call) -> Optional[str]:
    """The constant mode string of an open() call, None if dynamic."""
    mode_node: Optional[ast.expr] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return "r"
    if isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str):
        return mode_node.value
    return None


def _scopes(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _direct_walk(scope: ast.AST):
    """Walk a function body WITHOUT descending into nested function
    definitions (their opens/renames are their own scope's business)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@per_file_checker
def check_fsync_missing_before_rename(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for scope in _scopes(ctx.tree):
        opened: dict[str, int] = {}  # path-expr dump -> open line
        fsync_lines: list[int] = []
        renames: list[tuple[ast.Call, str]] = []
        for node in _direct_walk(scope):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "open"
                and node.args
            ):
                mode = _mode_of(node)
                if mode is not None and _WRITE_MODE_RE.match(mode):
                    opened[ast.dump(node.args[0])] = node.lineno
            elif _is_fsync_call(node):
                fsync_lines.append(node.lineno)
            elif (_is_os_call(node, "replace") or _is_os_call(node, "rename")) and node.args:
                renames.append((node, ast.dump(node.args[0])))
        for call, src_dump in renames:
            open_line = opened.get(src_dump)
            if open_line is None or open_line > call.lineno:
                continue
            if any(open_line <= ln <= call.lineno for ln in fsync_lines):
                continue
            findings.append(Finding(
                "fsync-missing-before-rename", ctx.rel, call.lineno,
                f"`{scope.name}` renames a path it opened for writing at "
                f"line {open_line} with no fsync in between — a crash "
                "after the rename can publish an empty/torn file under "
                "the final name",
            ))
    return findings


def _vouching_dict_arg(call: ast.Call) -> bool:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if not isinstance(arg, ast.Dict):
            continue
        for k, v in zip(arg.keys, arg.values):
            if (
                isinstance(k, ast.Constant)
                and k.value in ("kind", "type")
                and isinstance(v, ast.Constant)
                and v.value in _VOUCHING_KINDS
            ):
                return True
    return False


@per_file_checker
def check_record_before_fsync(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for scope in _scopes(ctx.tree):
        fsync_lines: list[int] = []
        appends: list[ast.Call] = []
        for node in _direct_walk(scope):
            if not isinstance(node, ast.Call):
                continue
            if _is_fsync_call(node):
                fsync_lines.append(node.lineno)
            elif _callee_name(node) in _APPEND_NAMES and _vouching_dict_arg(node):
                appends.append(node)
        for call in appends:
            if any(ln <= call.lineno for ln in fsync_lines):
                continue
            findings.append(Finding(
                "record-before-fsync", ctx.rel, call.lineno,
                f"`{scope.name}` journals a vouching record with no data "
                "fsync before it — a crash can leave a journal testifying "
                "to bytes the disk never got",
            ))
    return findings


def _const_suffix(expr: ast.expr) -> Optional[str]:
    """The trailing constant string fragment of a path expression, if the
    expression's tail is lexically visible: a string constant, `x + ".dat"`,
    an f-string ending in a literal, `% `/`.format` on a literal with a
    constant tail, or os.path.join(..., const)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _const_suffix(expr.right)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mod):
        return _const_suffix(expr.left)
    if isinstance(expr, ast.JoinedStr) and expr.values:
        return _const_suffix(expr.values[-1])
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr in ("join", "format")
        and expr.args
    ):
        if expr.func.attr == "format":
            return _const_suffix(expr.func.value)
        return _const_suffix(expr.args[-1])
    return None


@per_file_checker
def check_tmp_visible_name(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
            and node.args
        ):
            continue
        mode = _mode_of(node)
        if mode is None or not _CREATE_MODE_RE.match(mode):
            continue
        suffix = _const_suffix(node.args[0])
        if suffix is None:
            continue
        # a '%'/format placeholder in the tail means the literal tail is
        # not the on-disk tail
        tail = suffix.rsplit("}", 1)[-1]
        m = _SERVING_SUFFIX_RE.search(tail)
        if m is None:
            continue
        findings.append(Finding(
            "tmp-visible-name", ctx.rel, node.lineno,
            f"creates `{m.group(0)}` (a serving-discoverable name) "
            "directly — stage under .inp/.cv.*/dot-tmp and rename into "
            "place so readers and crashes never observe a partial file",
        ))
    return findings


_TORN_EXC_NAMES = {"ValueError", "JSONDecodeError", "Exception", "BaseException"}


def _handler_catches_decode(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:  # bare except
        return True
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        name = t.attr if isinstance(t, ast.Attribute) else (
            t.id if isinstance(t, ast.Name) else None
        )
        if name in _TORN_EXC_NAMES:
            return True
    return False


def _guarded(ctx: FileContext, node: ast.AST, stop: ast.AST) -> bool:
    """Is `node` inside a try whose handlers catch decode errors, looking
    no further out than `stop` (the enclosing function/module)?"""
    cur = ctx.parent(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.Try) and any(
            _handler_catches_decode(h) for h in cur.handlers
        ):
            return True
        cur = ctx.parent(cur)
    return False


def _names_in(expr: ast.expr) -> set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _loop_target_names(target: ast.expr) -> set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


@per_file_checker
def check_torn_tail_unhandled(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for scope in [ctx.tree] + list(_scopes(ctx.tree)):
        for node in _direct_walk(scope):
            if not isinstance(node, ast.For):
                continue
            targets = _loop_target_names(node.target)
            for sub in ast.walk(node):
                if not (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "loads"
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "json"
                    and sub.args
                ):
                    continue
                if not (_names_in(sub.args[0]) & targets):
                    continue
                if _guarded(ctx, sub, scope):
                    continue
                findings.append(Finding(
                    "torn-tail-unhandled", ctx.rel, sub.lineno,
                    "json.loads on a journal line with no "
                    "ValueError/JSONDecodeError guard — a torn tail (the "
                    "one crash artifact JSON-lines journals are allowed "
                    "to have) would raise instead of ending the read",
                ))
    return findings
