"""ctypes binding to libweedtpu.so (native/weedtpu.cc) — the C++ runtime
kernels (CRC32C, AVX2 GF(2^8) baseline). Builds the library on first use if
the toolchain is present; everything degrades to pure-Python fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libweedtpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _stale() -> bool:
    """True when the .so is missing or older than its source."""
    try:
        so_mtime = os.path.getmtime(_LIB_PATH)
    except OSError:
        return True
    try:
        src_mtime = os.path.getmtime(os.path.join(_NATIVE_DIR, "weedtpu.cc"))
    except OSError:
        return False
    return src_mtime > so_mtime


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, (re)building it when missing or out of date;
    None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if _stale() and not _build() and not os.path.exists(_LIB_PATH):
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.weedtpu_crc32c.restype = ctypes.c_uint32
            lib.weedtpu_crc32c.argtypes = [
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_uint64,
            ]
            lib.weedtpu_has_avx2.restype = ctypes.c_int
            lib.weedtpu_gf_matrix_apply.restype = None
            _lib = lib
        except (OSError, AttributeError):
            # OSError: unloadable .so; AttributeError: a stale binary
            # missing expected symbols. Either way fall back to Python.
            _load_failed = True
        return _lib


# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------

_CRC_TABLE: Optional[list[int]] = None


def _py_table() -> list[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        tbl = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            tbl.append(crc)
        _CRC_TABLE = tbl
    return _CRC_TABLE


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C (Castagnoli) — the needle checksum algorithm
    [VERIFY: weed/storage/needle/needle_read_write.go uses Castagnoli]."""
    lib = load()
    if lib is not None:
        return lib.weedtpu_crc32c(crc, bytes(data), len(data))
    tbl = _py_table()
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ tbl[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def gf_matrix_apply_native(matrix, inputs, length: int, threads: int = 1):
    """Native (AVX2 when available) GF matrix apply over byte slices.

    matrix: (R, C) uint8 numpy array; inputs: list of C bytes-like of `length`.
    threads: 1 = single core; 0 = all cores; N = exactly N workers (the
    multithreaded split mirrors the reference codec's WithAutoGoroutines).
    Returns list of R arrays, or None if the library is unavailable.
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    rows, cols = matrix.shape
    in_bufs = [np.ascontiguousarray(np.frombuffer(i, dtype=np.uint8)) for i in inputs]
    out_bufs = [np.zeros(length, dtype=np.uint8) for _ in range(rows)]
    InArr = ctypes.c_char_p * cols
    OutArr = ctypes.c_void_p * rows
    ins = InArr(*[i.ctypes.data_as(ctypes.c_char_p) for i in in_bufs])
    outs = OutArr(*[o.ctypes.data_as(ctypes.c_void_p) for o in out_bufs])
    mat_ptr = matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    if threads == 1 or not hasattr(lib, "weedtpu_gf_matrix_apply_mt"):
        lib.weedtpu_gf_matrix_apply(
            mat_ptr,
            ctypes.c_uint32(rows),
            ctypes.c_uint32(cols),
            ins,
            outs,
            ctypes.c_uint64(length),
        )
    else:
        lib.weedtpu_gf_matrix_apply_mt(
            mat_ptr,
            ctypes.c_uint32(rows),
            ctypes.c_uint32(cols),
            ins,
            outs,
            ctypes.c_uint64(length),
            ctypes.c_uint32(threads),
        )
    return out_bufs


def gf_matrix_apply_batch_native(matrix, shards, threads: int = 0):
    """Batched native apply: shards (B, C, N) uint8 -> (B, R, N), one
    library call (one worker pool over batch elements, zero repacking —
    the per-element slice pointers index straight into `shards`).
    Returns None when the library (or the batch symbol) is unavailable."""
    import numpy as np

    lib = load()
    if lib is None or not hasattr(lib, "weedtpu_gf_matrix_apply_batch"):
        return None
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    rows, cols = matrix.shape
    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    batch, c, n = shards.shape
    if c != cols:
        raise ValueError(f"matrix wants {cols} inputs, stack has {c}")
    out = np.zeros((batch, rows, n), dtype=np.uint8)
    InArr = ctypes.c_char_p * (batch * cols)
    OutArr = ctypes.c_void_p * (batch * rows)
    base_in = shards.ctypes.data
    base_out = out.ctypes.data
    ins = InArr(*[
        ctypes.c_char_p(base_in + (b * cols + ci) * n)
        for b in range(batch)
        for ci in range(cols)
    ])
    outs = OutArr(*[
        base_out + (b * rows + r) * n for b in range(batch) for r in range(rows)
    ])
    lib.weedtpu_gf_matrix_apply_batch(
        matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint32(rows),
        ctypes.c_uint32(cols),
        ins,
        outs,
        ctypes.c_uint64(n),
        ctypes.c_uint32(batch),
        ctypes.c_uint32(threads),
    )
    return out


def has_avx2() -> bool:
    lib = load()
    return bool(lib and lib.weedtpu_has_avx2())


def has_mt() -> bool:
    """True when the loaded library exports the multithreaded apply —
    a stale pre-MT binary would otherwise silently run single-threaded."""
    lib = load()
    return bool(lib and hasattr(lib, "weedtpu_gf_matrix_apply_mt"))
