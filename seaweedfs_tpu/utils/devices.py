"""Device identification helpers shared across backend-selection sites."""

from __future__ import annotations


def is_tpu_device(d) -> bool:
    """True for real TPUs and for the axon tunnel (platform=="axon",
    device_kind "TPU v5 lite")."""
    return d.platform in ("tpu", "axon") or "tpu" in d.device_kind.lower()
