"""Device identification helpers shared across backend-selection sites."""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert the user's JAX_PLATFORMS env over site-level overrides.

    The axon sitecustomize calls jax.config.update("jax_platforms",
    "axon,cpu") at interpreter start, which OUTRANKS the env var — so a
    server launched with JAX_PLATFORMS=cpu would still initialize the
    (single-client) TPU tunnel backend and can hang when another process
    holds it. Call this right after `import jax`, before any backend
    touch, wherever the framework imports jax in a server process."""
    # weedlint: ignore[env-raw-read] foreign (jax) env var, not a WEEDTPU knob
    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    import jax

    try:
        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:  # noqa: BLE001 — config attr shape varies by version
        jax.config.update("jax_platforms", want)


def is_tpu_device(d) -> bool:
    """True for real TPUs and for the axon tunnel (platform=="axon",
    device_kind "TPU v5 lite")."""
    return d.platform in ("tpu", "axon") or "tpu" in d.device_kind.lower()
