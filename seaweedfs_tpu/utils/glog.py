"""Leveled logging — weed/glog analog [VERIFY: mount empty; SURVEY.md
§2.1 "Logging" row]: `V(n)`-style verbosity gating on top of stdlib
logging, so call sites read like the reference (`glog.V(3).infof(...)`).
Verbosity comes from set_verbosity() or the WEEDTPU_V env var.

Every emitted line carries structured key=value context: the ambient
weedtrace id is appended automatically (` trace=<id>`) whenever a trace
is active in the calling thread, so `grep trace=<id>` over the
cluster's stderr reconstructs one request's cross-process log lines —
the glog half of end-to-end tracing. `kv(...)` formats extra context
pairs in the same grep-stable shape."""

from __future__ import annotations

import logging
import sys

from seaweedfs_tpu.utils import config

_logger = logging.getLogger("seaweedfs_tpu")
if not _logger.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(
        logging.Formatter("%(levelname).1s%(asctime)s %(name)s] %(message)s", "%m%d %H:%M:%S")
    )
    _logger.addHandler(h)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False

_verbosity = config.env("WEEDTPU_V")


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def kv(**pairs) -> str:
    """key=value context in the grep-stable shape log lines use —
    append to a message: glog.info("repair done %s", glog.kv(vid=3))."""
    return " ".join(f"{k}={v}" for k, v in pairs.items())


def _ctx_suffix() -> str:
    """` trace=<id>` when a weedtrace is active in this thread. Lazy
    import: glog is a leaf module and obs.trace must stay importable
    from anywhere without cycles."""
    try:
        from seaweedfs_tpu.obs import trace as _trace

        tid = _trace.current_trace_id()
    except Exception:  # noqa: BLE001 — logging must never raise
        return ""
    return f" trace={tid}" if tid else ""


def _with_ctx(msg):
    """Suffix the trace context onto string messages (non-str messages —
    exceptions handed straight to the logger — pass through untouched
    so their %-free formatting stays valid)."""
    if not isinstance(msg, str):
        return msg
    suffix = _ctx_suffix()
    return msg + suffix if suffix else msg


class _Verbose:
    def __init__(self, enabled: bool):
        self.enabled = enabled

    def info(self, msg: str, *args) -> None:
        if self.enabled:
            _logger.info(_with_ctx(msg), *args)

    infof = info


def V(level: int) -> _Verbose:  # noqa: N802 — glog's exact API shape
    return _Verbose(level <= _verbosity)


def info(msg: str, *args) -> None:
    _logger.info(_with_ctx(msg), *args)


def warning(msg: str, *args) -> None:
    _logger.warning(_with_ctx(msg), *args)


def error(msg: str, *args) -> None:
    _logger.error(_with_ctx(msg), *args)


infof = info
warningf = warning
errorf = error
