"""Leveled logging — weed/glog analog [VERIFY: mount empty; SURVEY.md
§2.1 "Logging" row]: `V(n)`-style verbosity gating on top of stdlib
logging, so call sites read like the reference (`glog.V(3).infof(...)`).
Verbosity comes from set_verbosity() or the WEEDTPU_V env var."""

from __future__ import annotations

import logging
import sys

from seaweedfs_tpu.utils import config

_logger = logging.getLogger("seaweedfs_tpu")
if not _logger.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(
        logging.Formatter("%(levelname).1s%(asctime)s %(name)s] %(message)s", "%m%d %H:%M:%S")
    )
    _logger.addHandler(h)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False

_verbosity = config.env("WEEDTPU_V")


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


class _Verbose:
    def __init__(self, enabled: bool):
        self.enabled = enabled

    def info(self, msg: str, *args) -> None:
        if self.enabled:
            _logger.info(msg, *args)

    infof = info


def V(level: int) -> _Verbose:  # noqa: N802 — glog's exact API shape
    return _Verbose(level <= _verbosity)


def info(msg: str, *args) -> None:
    _logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    _logger.warning(msg, *args)


def error(msg: str, *args) -> None:
    _logger.error(msg, *args)


infof = info
warningf = warning
errorf = error
