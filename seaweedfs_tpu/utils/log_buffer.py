"""LogBuffer — mirror of weed/util/log_buffer/ [VERIFY: mount empty;
SURVEY.md §2.1 "Messaging" + "Util" rows]: an in-memory append buffer of
timestamped records that flushes to a durable segment (via callback)
when full or on an interval, while still serving reads that span both
flushed segments (caller-provided) and the live tail.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional


@dataclass
class LogRecord:
    ts_ns: int
    key: bytes
    value: bytes

    def to_dict(self) -> dict:
        import base64

        return {
            "ts_ns": self.ts_ns,
            "key": base64.b64encode(self.key).decode(),
            "value": base64.b64encode(self.value).decode(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LogRecord":
        import base64

        return cls(
            ts_ns=int(d["ts_ns"]),
            key=base64.b64decode(d.get("key", "")),
            value=base64.b64decode(d.get("value", "")),
        )


class LogBuffer:
    """`flush_fn(first_ts_ns, last_ts_ns, records)` persists a batch; it
    runs on the caller's thread (add) or the flush timer thread."""

    def __init__(
        self,
        flush_fn: Callable[[int, int, list[LogRecord]], None],
        max_bytes: int = 4 * 1024 * 1024,
        flush_interval_s: float = 2.0,
    ):
        self._flush_fn = flush_fn
        self._max = max_bytes
        self._records: list[LogRecord] = []
        self._last_ts = 0  # survives drains: monotonicity across flushes
        self._bytes = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._interval = flush_interval_s
        self._timer = threading.Thread(target=self._flush_loop, daemon=True)
        self._timer.start()

    def add(self, key: bytes, value: bytes, ts_ns: Optional[int] = None) -> int:
        rec = LogRecord(ts_ns or time.time_ns(), key, value)
        to_flush = None
        with self._lock:
            # monotonic across the whole buffer LIFETIME, not just the
            # current batch — a record stamped <= the last flushed ts
            # would be invisible to subscribers seeking past the flush
            if rec.ts_ns <= self._last_ts:
                rec.ts_ns = self._last_ts + 1
            self._last_ts = rec.ts_ns
            self._records.append(rec)
            self._bytes += len(key) + len(value) + 16
            if self._bytes >= self._max:
                to_flush = self._drain_locked()
            self._cv.notify_all()
        if to_flush:
            self._persist(to_flush)
        return rec.ts_ns

    def _persist(self, recs: list[LogRecord]) -> bool:
        try:
            self._flush_fn(recs[0].ts_ns, recs[-1].ts_ns, recs)
            return True
        except Exception:  # noqa: BLE001 — requeue, retry on next flush
            with self._lock:
                self._records = recs + self._records
                self._bytes += sum(len(r.key) + len(r.value) + 16 for r in recs)
            return False

    def _drain_locked(self) -> list[LogRecord]:
        recs, self._records = self._records, []
        self._bytes = 0
        return recs

    def flush(self) -> bool:
        """Persist the live tail. On flush_fn failure the batch is
        REQUEUED at the front (records stay readable and are retried on
        the next flush) and False is returned — a transient sink outage
        must never drop acked records or kill the flush timer."""
        with self._lock:
            recs = self._drain_locked()
        if not recs:
            return True
        return self._persist(recs)

    def _flush_loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def close(self) -> None:
        self._stop.set()
        if not self.flush():
            self.flush()  # one retry on shutdown

    def read_since(self, ts_ns: int) -> list[LogRecord]:
        """Live-tail records newer than ts_ns (flushed data is the
        caller's job to merge in)."""
        with self._lock:
            return [r for r in self._records if r.ts_ns > ts_ns]

    def wait_for_data(self, ts_ns: int, timeout: float) -> bool:
        with self._lock:
            if any(r.ts_ns > ts_ns for r in self._records):
                return True
            self._cv.wait(timeout)
            return any(r.ts_ns > ts_ns for r in self._records)
