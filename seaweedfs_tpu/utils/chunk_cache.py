"""ChunkCache — tiered read cache for immutable chunks, the
weed/util/chunk_cache analog [VERIFY: mount empty; SURVEY.md §2.1 "Util"
row]: the filer/mount read path hits the same hot chunks over and over
(directory pages, small files, manifest heads); a fid is written once and
never mutated, so caching by fid is safe and deletes just evict.

Two tiers, like the reference's memory + on-disk volume caches:

  memory   byte-budgeted LRU (OrderedDict), items above `max_item_bytes`
           bypass it — one huge blob must not wipe the working set
  disk     optional directory of fid-named files with a byte budget,
           evicted oldest-mtime-first; survives restarts (the reference's
           persisted disk cache role)

Reads promote disk hits back into memory. All operations are lock-guarded
and O(1)-ish; eviction is amortized.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional


class ChunkCache:
    def __init__(
        self,
        memory_bytes: int = 64 << 20,
        max_item_bytes: int = 4 << 20,
        disk_dir: str = "",
        disk_bytes: int = 0,
    ):
        self.memory_budget = memory_bytes
        self.max_item_bytes = max_item_bytes
        self.disk_dir = disk_dir
        self.disk_budget = disk_bytes
        self._mem: OrderedDict[str, bytes] = OrderedDict()
        self._mem_bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        if disk_dir and disk_bytes > 0:
            os.makedirs(disk_dir, exist_ok=True)

    # -- keys -----------------------------------------------------------------

    @staticmethod
    def _disk_name(fid: str) -> str:
        return hashlib.sha1(fid.encode()).hexdigest() + ".chunk"

    def _disk_path(self, fid: str) -> str:
        return os.path.join(self.disk_dir, self._disk_name(fid))

    # -- api ------------------------------------------------------------------

    def get(self, fid: str) -> Optional[bytes]:
        with self._lock:
            data = self._mem.get(fid)
            if data is not None:
                self._mem.move_to_end(fid)
                self.hits += 1
                return data
        if self.disk_dir and self.disk_budget > 0:
            try:
                with open(self._disk_path(fid), "rb") as f:
                    data = f.read()
                self._put_mem(fid, data)  # promote
                with self._lock:
                    self.hits += 1
                return data
            except OSError:
                pass
        with self._lock:
            self.misses += 1
        return None

    def put(self, fid: str, data: bytes) -> None:
        if len(data) > self.max_item_bytes:
            return
        self._put_mem(fid, data)
        if self.disk_dir and self.disk_budget > 0:
            try:
                tmp = self._disk_path(fid) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, self._disk_path(fid))
                self._evict_disk()
            except OSError:
                pass  # a full/broken disk tier must never fail a read

    def _put_mem(self, fid: str, data: bytes) -> None:
        with self._lock:
            old = self._mem.pop(fid, None)
            if old is not None:
                self._mem_bytes -= len(old)
            self._mem[fid] = data
            self._mem_bytes += len(data)
            while self._mem_bytes > self.memory_budget and self._mem:
                _, evicted = self._mem.popitem(last=False)
                self._mem_bytes -= len(evicted)

    def delete(self, fid: str) -> None:
        with self._lock:
            old = self._mem.pop(fid, None)
            if old is not None:
                self._mem_bytes -= len(old)
        if self.disk_dir and self.disk_budget > 0:
            try:
                os.remove(self._disk_path(fid))
            except OSError:
                pass

    def _evict_disk(self) -> None:
        try:
            entries = [
                (e.stat().st_mtime, e.path, e.stat().st_size)
                for e in os.scandir(self.disk_dir)
                if e.name.endswith(".chunk")
            ]
        except OSError:
            return
        total = sum(s for _, _, s in entries)
        if total <= self.disk_budget:
            return
        for _, path, size in sorted(entries):  # oldest first
            try:
                os.remove(path)
                total -= size
            except OSError:
                pass
            if total <= self.disk_budget:
                break

    @property
    def memory_bytes_used(self) -> int:
        with self._lock:
            return self._mem_bytes

    def clear(self) -> None:
        with self._lock:
            self._mem.clear()
            self._mem_bytes = 0
