"""ChunkCache — tiered read cache for immutable chunks, the
weed/util/chunk_cache analog [VERIFY: mount empty; SURVEY.md §2.1 "Util"
row]: the filer/mount read path hits the same hot chunks over and over
(directory pages, small files, manifest heads); a fid is written once and
never mutated, so caching by fid is safe and deletes just evict.

Two tiers, like the reference's memory + on-disk volume caches:

  memory   byte-budgeted LRU (OrderedDict), items above `max_item_bytes`
           bypass it — one huge blob must not wipe the working set
  disk     optional directory of fid-named files with a byte budget,
           evicted oldest-mtime-first; survives restarts (the reference's
           persisted disk cache role)

Reads promote disk hits back into memory. All operations are lock-guarded
and O(1)-ish; eviction is amortized.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional


class ChunkCache:
    def __init__(
        self,
        memory_bytes: int = 64 << 20,
        max_item_bytes: int = 4 << 20,
        disk_dir: str = "",
        disk_bytes: int = 0,
    ):
        self.memory_budget = memory_bytes
        self.max_item_bytes = max_item_bytes
        self.disk_dir = disk_dir
        self.disk_budget = disk_bytes
        self._mem: OrderedDict[str, bytes] = OrderedDict()
        self._mem_bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self._disk_bytes = 0  # running total: eviction scans only when over budget
        if disk_dir and disk_bytes > 0:
            os.makedirs(disk_dir, exist_ok=True)
            try:
                self._disk_bytes = sum(
                    e.stat().st_size
                    for e in os.scandir(disk_dir)
                    if e.name.endswith(".chunk")
                )
            except OSError:
                pass

    # -- keys -----------------------------------------------------------------

    @staticmethod
    def _disk_name(fid: str) -> str:
        return hashlib.sha1(fid.encode()).hexdigest() + ".chunk"

    def _disk_path(self, fid: str) -> str:
        return os.path.join(self.disk_dir, self._disk_name(fid))

    # -- api ------------------------------------------------------------------

    def get(self, fid: str) -> Optional[bytes]:
        with self._lock:
            data = self._mem.get(fid)
            if data is not None:
                self._mem.move_to_end(fid)
                self.hits += 1
                return data
        if self.disk_dir and self.disk_budget > 0:
            try:
                with open(self._disk_path(fid), "rb") as f:
                    data = f.read()
                # same guard as put(): a persisted oversized blob (e.g.
                # after a restart with a smaller max_item_bytes) must not
                # wipe the memory working set on promotion
                if len(data) <= self.max_item_bytes:
                    self._put_mem(fid, data)
                with self._lock:
                    self.hits += 1
                return data
            except OSError:
                pass
        with self._lock:
            self.misses += 1
        return None

    def put(self, fid: str, data: bytes) -> None:
        if len(data) > self.max_item_bytes:
            return
        self._put_mem(fid, data)
        if self.disk_dir and self.disk_budget > 0:
            try:
                path = self._disk_path(fid)
                try:
                    prev = os.path.getsize(path)
                except OSError:
                    prev = 0
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                with self._lock:
                    self._disk_bytes += len(data) - prev
                    over = self._disk_bytes > self.disk_budget
                if over:
                    self._evict_disk()
            except OSError:
                pass  # a full/broken disk tier must never fail a read

    def _put_mem(self, fid: str, data: bytes) -> None:
        with self._lock:
            old = self._mem.pop(fid, None)
            if old is not None:
                self._mem_bytes -= len(old)
            self._mem[fid] = data
            self._mem_bytes += len(data)
            while self._mem_bytes > self.memory_budget and self._mem:
                _, evicted = self._mem.popitem(last=False)
                self._mem_bytes -= len(evicted)

    def delete(self, fid: str) -> None:
        with self._lock:
            old = self._mem.pop(fid, None)
            if old is not None:
                self._mem_bytes -= len(old)
        if self.disk_dir and self.disk_budget > 0:
            path = self._disk_path(fid)
            try:
                size = os.path.getsize(path)
                os.remove(path)
                with self._lock:
                    self._disk_bytes = max(0, self._disk_bytes - size)
            except OSError:
                pass

    def _evict_disk(self) -> None:
        """Called only when the running total crossed the budget — the
        directory scan is paid once per overflow, not per put."""
        try:
            entries = [
                (e.stat().st_mtime, e.path, e.stat().st_size)
                for e in os.scandir(self.disk_dir)
                if e.name.endswith(".chunk")
            ]
        except OSError:
            return
        total = sum(s for _, _, s in entries)
        removed = 0
        for _, path, size in sorted(entries):  # oldest first
            if total - removed <= self.disk_budget:
                break
            try:
                os.remove(path)
                removed += size
            except OSError:
                pass
        # adjust by the delta rather than overwriting: puts/deletes racing
        # this scan already updated the counter for files we didn't see
        with self._lock:
            self._disk_bytes = max(0, self._disk_bytes - removed)

    @property
    def memory_bytes_used(self) -> int:
        with self._lock:
            return self._mem_bytes

    def clear(self) -> None:
        """Full invalidation of BOTH tiers (a memory-only clear would keep
        serving old bytes from disk on the next get)."""
        with self._lock:
            self._mem.clear()
            self._mem_bytes = 0
        if self.disk_dir and self.disk_budget > 0:
            try:
                for e in os.scandir(self.disk_dir):
                    if e.name.endswith(".chunk"):
                        os.remove(e.path)
            except OSError:
                pass
            with self._lock:
                self._disk_bytes = 0
