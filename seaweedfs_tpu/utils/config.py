"""TOML configuration — weed/util/config.go + command/scaffold.go analog
[VERIFY: mount empty; SURVEY.md §5 "Config/flag system"]: named TOML
files (security.toml, master.toml, filer.toml, shell.toml) searched in
`.`, `~/.seaweedfs_tpu/`, `/etc/seaweedfs_tpu/`; `scaffold` prints
commented templates. Parsing uses stdlib tomllib.

Also the typed WEEDTPU_* environment-variable registry: every env knob
the package reads is declared here ONCE (name, type, default, doc) and
read through `env()`. weedlint's env-registry checker flags any raw
`os.environ`/`os.getenv` read elsewhere in the package, and the README
env-var table is generated from this registry — so the docs, the
defaults, and the code cannot drift apart."""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

try:  # stdlib on 3.11+; this image runs 3.10
    import tomllib
except ImportError:  # pragma: no cover — version-dependent
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ImportError:
        tomllib = None  # type: ignore[assignment] — parse at use time

SEARCH_PATHS = [".", "~/.seaweedfs_tpu", "/etc/seaweedfs_tpu"]


def load_configuration(name: str, required: bool = False) -> dict[str, Any]:
    """Load `<name>.toml` from the search path; {} when absent."""
    fname = name if name.endswith(".toml") else name + ".toml"
    for d in SEARCH_PATHS:
        path = os.path.join(os.path.expanduser(d), fname)
        if os.path.exists(path):
            if tomllib is None:
                # a present config that can't be parsed must FAIL, not be
                # silently ignored — dropping security.toml would disable
                # auth without a trace. Absent configs (the common case)
                # never reach here, so 3.10 servers without TOML configs
                # run fine.
                raise RuntimeError(
                    f"{path} exists but no TOML parser is available "
                    "(python < 3.11 without the tomli package)"
                )
            with open(path, "rb") as f:
                return tomllib.load(f)
    if required:
        raise FileNotFoundError(
            f"{fname} not found in {[os.path.expanduser(d) for d in SEARCH_PATHS]}"
        )
    return {}


def get_nested(conf: dict, dotted: str, default: Any = None) -> Any:
    """conf lookup by 'a.b.c' path."""
    cur: Any = conf
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


SCAFFOLDS = {
    "security": '''\
# security.toml — put in ., ~/.seaweedfs_tpu/, or /etc/seaweedfs_tpu/
# JWT signing on the volume-server write path. Empty key = auth disabled.

[jwt.signing]
key = ""
expires_after_seconds = 10

# optional separate key gating reads
[jwt.signing.read]
key = ""
expires_after_seconds = 10

[guard]
# IPs allowed to bypass JWT checks
white_list = []

# TLS/mTLS for the gRPC control plane. Setting `ca` turns TLS on for every
# server and client in the process. Generate a throwaway CA + leaf pair with
#   python -c "from seaweedfs_tpu.security.tls import generate_self_signed; \\
#              print(generate_self_signed('./certs'))"
[grpc]
ca = ""
cert = ""
key = ""
require_client_auth = true    # mTLS: peers must present a CA-signed cert
# override_authority = "weedtpu-cluster"   # when certs name the cluster, not each host

# HTTPS on the HTTP data path (volume/filer/s3/webdav/iam servers); uses the
# [grpc] cert material
[https]
enabled = false
''',
    "master": '''\
# master.toml
[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1

[master.sequencer]
type = "memory"   # memory | snowflake
''',
    "shell": '''\
# shell.toml
[cluster]
default = "localhost"

[cluster.localhost]
master = "127.0.0.1:9333"
''',
    "filer": '''\
# filer.toml — filer metadata store selection
[memory]
enabled = false

[sqlite]
enabled = true
dbFile = "./filer.db"

# from-scratch embedded log-structured store (the leveldb2-analog):
# append-only CRC-framed log + in-memory index, auto-compaction
[log]
enabled = false
dir = "./filerlog"
''',
}


def scaffold(name: str) -> Optional[str]:
    return SCAFFOLDS.get(name)


# -- WEEDTPU_* environment-variable registry ----------------------------------


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One declared environment knob. `type` drives parsing (bool accepts
    1/true/yes/on, case-insensitive); `parse` overrides it for knobs with
    extra constraints (clamps, enums) so every call site agrees on the
    same coercion instead of re-implementing it."""

    name: str
    type: type
    default: Any
    doc: str
    parse: Optional[Callable[[str], Any]] = None

    def value(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None or raw == "":
            return self.default
        if self.parse is not None:
            return self.parse(raw)
        if self.type is bool:
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return self.type(raw)


ENV_REGISTRY: dict[str, EnvVar] = {}


def register_env(
    name: str,
    type_: type,
    default: Any,
    doc: str,
    parse: Optional[Callable[[str], Any]] = None,
) -> EnvVar:
    if not name.startswith("WEEDTPU_"):
        raise ValueError(f"env knob {name!r} must be WEEDTPU_-prefixed")
    prev = ENV_REGISTRY.get(name)
    if prev is not None:
        # `parse` compares by identity: the registry is declared ONCE
        # below, so any re-registration bringing its own parser (even a
        # semantically identical closure) is a second source of truth and
        # must fail loudly rather than silently keep the first parser
        if (prev.type, prev.default) != (type_, default) or prev.parse is not parse:
            raise ValueError(
                f"{name} re-registered with conflicting spec: "
                f"{(prev.type, prev.default, prev.parse)} vs "
                f"{(type_, default, parse)}"
            )
        return prev
    var = EnvVar(name, type_, default, doc, parse)
    ENV_REGISTRY[name] = var
    return var


def env(name: str) -> Any:
    """Parsed value of a REGISTERED env knob (default when unset/empty).
    Unknown names raise — a typo'd knob must fail loudly, not silently
    read as its default forever."""
    var = ENV_REGISTRY.get(name)
    if var is None:
        raise KeyError(f"{name} is not in the WEEDTPU env registry")
    return var.value()


def _clamped_int(minimum: int) -> Callable[[str], int]:
    return lambda raw: max(minimum, int(raw))


def _enum(*allowed: str) -> Callable[[str], str]:
    def parse(raw: str) -> str:
        v = raw.strip().lower()
        if v not in allowed:
            raise ValueError(f"expected one of {allowed}, got {raw!r}")
        return v

    return parse


# The full knob catalog. Declarations live here (not at call sites) so one
# import renders the complete table; call sites look their knob up by name.
register_env(
    "WEEDTPU_PIPELINE_DEPTH", int, 2,
    "Inflight depth of the streaming encode/rebuild pipelines (1 = one "
    "batch overlapped, 2 = double buffering, 3 = triple; clamped to >= 1). "
    "Deeper hides longer device latency at (depth+1) staging buffers of "
    "memory.",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_REBUILD_PREFETCH_BATCHES", int, 2,
    "How many batches ahead of the reading cursor the rebuild pipeline "
    "keeps network-prefetched on remote slab sources (clamped to >= 1).",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_BACKEND", str, "",
    "Operator override of the evidence-based auto backend selection: one "
    "of numpy | native | xorsched | jax | pallas | mesh (empty/auto = "
    "measured decision). Explicit new_encoder(backend=...) callers are "
    "never overridden.",
)
register_env(
    "WEEDTPU_MESH_SHAPE", str, "",
    "dp x sp axis shape of the mesh backend's device mesh, as `DPxSP` "
    "(e.g. `4x2`). Empty/`auto` resolves from the best achievable shape "
    "in committed MULTICHIP_r*.json evidence, falling back to "
    "(devices/2) x 2 (or devices x 1 below 4 devices).",
)
register_env(
    "WEEDTPU_MESH_REBUILD", str, "ring",
    "Distributed-rebuild formulation of the mesh backend: `ring` rotates "
    "one resident survivor block per chip with ppermute (peak per-chip "
    "memory = one block; measured faster), `alltoall` regroups "
    "shard-major survivors to byte-major with one all_to_all. Both are "
    "byte-identical to the single-device decode.",
    parse=_enum("ring", "alltoall"),
)
register_env(
    "WEEDTPU_EVIDENCE_MAX_AGE_DAYS", float, 120.0,
    "Committed on-chip measurement evidence older than this no longer "
    "flips the auto backend away from its conservative XLA default.",
)
register_env(
    "WEEDTPU_DECODE_MATRIX_CACHE", int, 512,
    "LRU cap on cached decode matrices (bounds the GF-elimination keys a "
    "long-lived server with churning shard-loss patterns accumulates).",
)
register_env(
    "WEEDTPU_V", int, 0,
    "glog verbosity level: glog.V(n) call sites with n <= this emit.",
)
register_env(
    "WEEDTPU_WIRE", str, "json",
    "Process-wide RPC wire selection: `proto` flips every unary JSON "
    "method in the pinned schema to binary protobuf; anything else means "
    "JSON. All processes of a cluster must agree.",
    parse=lambda raw: "proto" if raw.strip().lower() == "proto" else "json",
)
register_env(
    "WEEDTPU_BENCH_RPC_DELAY_MS", float, 0.0,
    "Bench-only per-RPC server-side sleep (ms) modeling network RTT on "
    "loopback hosts, so fetch/decode overlap is measurable. 0 = off.",
)
register_env(
    "WEEDTPU_LOCK_OBSERVE", bool, False,
    "Opt-in dynamic lock-order recorder: instruments threading.Lock/RLock "
    "at test-session start, records actual acquisition-order edges, and "
    "fails the run if the observed graph has a cycle (see "
    "seaweedfs_tpu/analysis/lockrec.py).",
)
register_env(
    "WEEDTPU_LOCK_OBSERVE_OUT", str, "",
    "Optional path: the instrumented-lock run dumps the observed "
    "acquisition-order graph here as JSON (edges + acquisition sites).",
)
register_env(
    "WEEDTPU_FS_OBSERVE", str, "",
    "Opt-in filesystem-op recorder (weedsafe dynamic half): the directory "
    "to observe — write/fsync/rename/unlink ops on paths under it are "
    "recorded with creation sites for crash-prefix replay (see "
    "seaweedfs_tpu/analysis/fsrec.py). Empty (default) = off.",
)
register_env(
    "WEEDTPU_FS_OBSERVE_OUT", str, "",
    "Optional path: an observed session dumps its recorded filesystem op "
    "trace here as JSON (op kinds, offsets, payload hex, creation sites).",
)
register_env(
    "WEEDTPU_FSREPLAY_MAX_PREFIXES", int, 48,
    "Crash-prefix replay budget per recorded workload: at most this many "
    "prefixes of the op trace are materialized and driven through the "
    "real resume entrypoints (evenly sampled, endpoints always kept) so "
    "the tier-1 replay gate stays inside its time budget. <=0 = every "
    "prefix.",
)
register_env(
    "WEEDTPU_HEDGE_READS", bool, True,
    "Hedged degraded-read shard fetches: once a survivor fetch has run "
    "past the per-peer EWMA-derived hedge delay, launch ONE backup fetch "
    "against a different holder; first success wins, the loser is "
    "cancelled/drained, and results are asserted byte-identical.",
)
register_env(
    "WEEDTPU_HEDGE_DELAY_MS", float, 0.0,
    "Fixed hedge delay in ms for degraded-read shard fetches; 0 (default) "
    "derives the delay per peer from the live latency EWMA + deviation "
    "tracked in the suspicion registry (TCP-RTO-style).",
)
register_env(
    "WEEDTPU_COALESCE_READS", bool, True,
    "Single-flight coalescing of concurrent degraded decodes of the SAME "
    "(shard, interval): one leader reconstructs, waiters get byte-"
    "identical copies — a hot lost shard costs one decode, not N.",
)
register_env(
    "WEEDTPU_REBUILD_MAX_INFLIGHT", int, 8,
    "Token gate on concurrent VolumeEcShardSlabRead rebuild streams per "
    "volume server (clamped to >= 1). A rebuild storm queues behind the "
    "gate instead of saturating the RPC worker pool and starving "
    "foreground interval reads.",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_REBUILD_YIELD_MS", float, 0.0,
    "Cooperative yield (ms) a rebuild slab stream sleeps between chunks, "
    "ceding the GIL/IO to foreground reads under contention. 0 = off.",
)
register_env(
    "WEEDTPU_TRACE_REPAIR", str, "auto",
    "Trace-repair projections for distributed rebuilds: `on` attempts "
    "projection fetches wherever holders advertise the slab_projection "
    "capability, `off` forces full survivor slabs (and stops "
    "advertising/serving the projection read), `auto` additionally "
    "declines projections when the plan would not move fewer bytes than "
    "the slabs it replaces. Any trace failure mid-rebuild falls back to "
    "full slabs.",
    parse=_enum("on", "off", "auto"),
)
register_env(
    "WEEDTPU_TRACE_CHUNK", int, 4 * 1024 * 1024,
    "Projection-window sub-range size (bytes) a TraceSlabSource fetches "
    "per request — the trace analog of the slab stripe size (clamped to "
    ">= 64 KiB).",
    parse=_clamped_int(64 * 1024),
)
register_env(
    "WEEDTPU_SLAB_FANOUT", int, 4,
    "Striping fan-out of remote slab/projection sources: concurrent "
    "sub-range fetches per source, spread across replica holders by "
    "least-inflight pick so one window aggregates the holders' bandwidth "
    "instead of pinning the first-sorted holder (clamped to >= 1).",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_INLINE_EC", str, "off",
    "Inline-EC ingest (encode-on-write): `on` streams every volume "
    "append through the staging-ring encode pipeline so a sealing volume "
    "is born EC'd (stripe state accumulates per open volume, parity is "
    "encoded incrementally per completed large row, journaled for "
    "crash-resume); `off` (default) keeps EC a warm-storage conversion.",
    parse=_enum("on", "off"),
)
register_env(
    "WEEDTPU_INLINE_EC_SEAL_BYTES", int, 0,
    "Auto-seal threshold for inline-EC ingest: a volume whose .dat "
    "crosses this many bytes is sealed in place (read-only, inline "
    "stripe finalized to .ec00-.ec13/.ecx/.eci, EC volume mounted). "
    "0 = never auto-seal; sealing then happens only via the "
    "VolumeEcShardsGenerate{inline:true} control RPC (ec.encode -inline).",
    parse=_clamped_int(0),
)
register_env(
    "WEEDTPU_INLINE_EC_LARGE_BLOCK", int, 1024 * 1024 * 1024,
    "Large stripe-block size (bytes) the inline-EC ingest builders "
    "encode with; must match the seal-time geometry or the inline state "
    "is discarded for the warm path (clamped to >= 4096).",
    parse=_clamped_int(4096),
)
register_env(
    "WEEDTPU_INLINE_EC_SMALL_BLOCK", int, 1024 * 1024,
    "Small (tail) stripe-block size (bytes) for inline-EC ingest — the "
    "inline sibling of the warm encoder's small_block_size (clamped to "
    ">= 512).",
    parse=_clamped_int(512),
)
register_env(
    "WEEDTPU_INLINE_EC_DELTA", bool, True,
    "Delta parity updates for overwrites landing inside already-encoded "
    "inline stripe rows: parity' = parity XOR G_col*(old XOR new) on just "
    "the touched byte columns (GF-linearity rank-1 update). Off = an "
    "overwrite invalidates the inline state and the seal falls back to "
    "the warm full re-encode.",
)
register_env(
    "WEEDTPU_SCRUB", str, "off",
    "Background shard-integrity scrubber: `on` starts a per-volume-server "
    "scan thread that CRC32-verifies every mounted EC shard against its "
    ".eci record in bounded chunks (rate-capped, riding the rebuild "
    "admission lane), quarantines failures out of serving, and triggers "
    "automatic trace-repair; `off` (default) leaves verification to the "
    "explicit ec.verify command.",
    parse=_enum("on", "off"),
)
register_env(
    "WEEDTPU_SCRUB_RATE_MB", float, 64.0,
    "Scrub read-rate cap in MB/s per volume server (rolling 1 s window); "
    "0 = unthrottled. Keeps a full-disk integrity pass from competing "
    "with foreground reads for disk bandwidth.",
)
register_env(
    "WEEDTPU_SCRUB_CHUNK", int, 4 * 1024 * 1024,
    "Scrub chunk size in bytes — the unit of admission-gated, rate-"
    "metered CRC folding (clamped to >= 64 KiB).",
    parse=_clamped_int(64 * 1024),
)
register_env(
    "WEEDTPU_SCRUB_INTERVAL", float, 30.0,
    "Seconds the scrubber sleeps between full passes over the mounted EC "
    "volumes. The persisted cursor makes an interrupted pass resume "
    "mid-shard across restarts.",
)
register_env(
    "WEEDTPU_SCRUB_CURSOR", str, "",
    "Path of the fsync'd scrub cursor file (scan progress + pending "
    "quarantine entries, resumed across restarts). Empty = "
    "`.scrub_cursor.json` in the server's first storage directory.",
)
register_env(
    "WEEDTPU_SCRUB_REPAIR_BACKOFF", float, 5.0,
    "Base backoff in seconds between repair attempts for one quarantined "
    "shard (doubles per failure, capped at 12x the base) — a stripe "
    "missing too many survivors retries calmly instead of hammering the "
    "master/holders.",
)
register_env(
    "WEEDTPU_SCRUB_MAX_REPAIRS", int, 1,
    "Concurrent automatic shard repairs per volume server (clamped to "
    ">= 1). Each repair is a trace-mode rebuild (or a clean-replica "
    "re-pull) — capping them keeps a corruption burst from becoming a "
    "rebuild storm.",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_CONVERT_BATCH", int, 64 * 1024 * 1024,
    "Device-batch budget (bytes) of the geometry-conversion pipeline — "
    "how much virtual-dat data one staging-ring dispatch covers (clamped "
    "to >= 1 MiB). The conversion analog of the encode pipeline's "
    "max_batch_bytes.",
    parse=_clamped_int(1024 * 1024),
)
register_env(
    "WEEDTPU_CONVERT_JOURNAL_MB", float, 64.0,
    "How many MB of converted output the geometry converter writes "
    "between fsync'd .ecc journal watermarks. Smaller = finer "
    "crash-resume granularity (less re-encoded on restart), larger = "
    "fewer fsyncs. Clamped to > 0.",
    parse=lambda raw: max(0.001, float(raw)),
)
register_env(
    "WEEDTPU_CONVERT_VERIFY", bool, True,
    "Re-read every converted shard FROM DISK and verify it against the "
    "staged .eci CRCs before cut-over retires the old geometry (the "
    "scrub-grade gate: bytes on disk, not bytes in flight, are what the "
    "new geometry will serve). Off skips the extra read pass.",
)
register_env(
    "WEEDTPU_TRACE", str, "on",
    "weedtrace request tracing: `on` (default — designed to be safe to "
    "leave on: allocation-light spans, no I/O, bounded ring) records "
    "context-local span trees on every hot path, propagates trace ids "
    "across RPC metadata / the X-Weedtpu-Trace HTTP header, and serves "
    "them at /debug/traces + `ec.trace`; `off` collapses every trace "
    "call site to a no-op.",
    parse=_enum("on", "off"),
)
register_env(
    "WEEDTPU_TRACE_SAMPLE", float, 1.0,
    "Probability a completed NON-tail trace enters the sampled ring "
    "(error traces and the N slowest per (kind, class) are always "
    "retained regardless). Clamped to [0, 1]; lower it on very hot "
    "fronts to bound serialization-free ring churn.",
    parse=lambda raw: min(1.0, max(0.0, float(raw))),
)
register_env(
    "WEEDTPU_TRACE_RING", int, 256,
    "Capacity of the per-process sampled-trace FIFO (tail-retained "
    "error/slowest traces live in their own bounded structures on top). "
    "Clamped to >= 8.",
    parse=_clamped_int(8),
)
register_env(
    "WEEDTPU_TRACE_SLOWEST", int, 5,
    "How many slowest traces per (kind, class) the ring always retains, "
    "independent of sampling — the tail the p99 is about (clamped to "
    ">= 1).",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_TRACE_SEED", int, 0,
    "Seed for the trace-sampling RNG (deterministic retention for "
    "tests/replays); 0 = OS entropy.",
)
register_env(
    "WEEDTPU_XORSCHED_TILE_KB", int, 4,
    "Width-axis tile of the xorsched executors, in KB per shard: each "
    "tile keeps the whole bit-plane slot frame (inputs + grouped temps + "
    "outputs) cache-resident while the XOR program replays. 4 KB "
    "measures best on the committed BENCH host (L1-sized frame); "
    "clamped to >= 1.",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_XORSCHED_CACHE", int, 64,
    "Entry cap of the compiled XOR-schedule LRU (keyed by matrix bytes "
    "+ tile geometry, like the decode-matrix memo). Compilation is "
    "milliseconds and programs are KBs, so a small cap covers every "
    "live (geometry, erasure-pattern) pair; clamped to >= 1.",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_XORSCHED_THREADS", int, 1,
    "Worker threads of the width-parallel native xorsched executor: the "
    "fused block-diagonal decode flattens to independent (block, "
    "width-tile) tasks, spread across this many threads. 0 means "
    "hardware concurrency (resolved natively); 1 keeps the PR 17 "
    "single-stream path; clamped to >= 0.",
    parse=_clamped_int(0),
)
register_env(
    "WEEDTPU_REBUILD_FUSE", str, "on",
    "Heterogeneous rebuild fusion in rebuild_ec_files_batch: 'on' fuses "
    "ALL signature groups of a batch into one block-diagonal decode "
    "dispatch (dispatch_groups == 1); 'off' restores the PR 16 "
    "per-signature-group dispatches (the bench baseline).",
    parse=_enum("on", "off"),
)
register_env(
    "WEEDTPU_REPAIR", str, "off",
    "Master-side fleet repair scheduler: `on` enumerates every stripe "
    "left under-replicated by a dead/quarantined holder, ranks by "
    "remaining redundancy (2-missing strictly before 1-missing, ties by "
    "stripe bytes then single-domain exposure), and drives batched "
    "remote rebuilds through the rebuild admission lane; `off` (default) "
    "leaves mass repair to the operator's ec.rebuild.",
    parse=_enum("on", "off"),
)
register_env(
    "WEEDTPU_REPAIR_MAX_INFLIGHT", int, 2,
    "Cluster-wide cap on concurrently-running batched rebuild dispatches "
    "from the fleet repair scheduler (clamped to >= 1) — the scheduler's "
    "own pacing budget on top of each holder's "
    "WEEDTPU_REBUILD_MAX_INFLIGHT admission gate.",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_REPAIR_BATCH", int, 8,
    "How many same-priority-class stripes one repair dispatch may carry "
    "to a single rebuild target (clamped to >= 1). The target fuses "
    "equal missing-signature volumes into shared decode dispatches, so "
    "bigger batches amortize device/staging setup across volumes.",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_REPAIR_SCAN_S", float, 30.0,
    "Seconds between full under-replication scans of the master's EC "
    "registry. Death signals (reaped nodes, shrinking heartbeats, "
    "confirmed peer-unreachable reports) trigger an immediate scan on "
    "top of this cadence.",
)
register_env(
    "WEEDTPU_REPAIR_SETTLE_S", float, 2.0,
    "Correlation window the repair scheduler waits after a death signal "
    "before dispatching: a rack's nodes die together but their heartbeat "
    "silences stagger, and ranking before the dust settles would start "
    "1-missing repairs that should have been 2-missing.",
)
register_env(
    "WEEDTPU_REPAIR_DEAD_S", float, 15.0,
    "Heartbeat-silence age after which a holder that peers ALSO report "
    "unreachable is treated as dead for repair purposes (unreported "
    "holders die at 4x this, bounded below by 60 s, so a long GC pause "
    "alone never triggers a mass rebuild).",
)
register_env(
    "WEEDTPU_REPAIR_BACKOFF", float, 2.0,
    "Base seconds of the per-stripe exponential backoff after a repair "
    "dispatch is refused (503/RESOURCE_EXHAUSTED from the admission "
    "lane) or fails in transport; doubles per failure, capped at 12x.",
)
register_env(
    "WEEDTPU_REPAIR_REPORT_FAILURES", int, 3,
    "Consecutive unreachable-peer failures on the degraded-read/rebuild "
    "paths before a volume server names that peer in its heartbeat's "
    "unreachable_peers report (clamped to >= 1; any success resets the "
    "count).",
    parse=_clamped_int(1),
)
register_env(
    "WEEDTPU_PLACEMENT_MAX_PER_DOMAIN", int, 0,
    "Operator override of the failure-domain placement cap (shards of "
    "one stripe a single rack may hold). 0 (default) = the volume's "
    "parity count m, the largest cap that still survives a whole-domain "
    "loss.",
    parse=_clamped_int(0),
)
register_env(
    "WEEDTPU_INLINE_EC_SPREAD", str, "off",
    "Inline-ingest parity spreading: `on` streams each parity shard's "
    "encoded rows to its placement-planned eventual holder WHILE the "
    "volume is still ingesting, so seal cut-over ships only the small "
    "tail and the owner never hosts all k+m shards; any spread failure "
    "falls back to sealing that shard locally. Requires "
    "WEEDTPU_INLINE_EC=on.",
    parse=_enum("on", "off"),
)
register_env(
    "WEEDTPU_LOOKUP_RETRIES", int, 2,
    "Bounded retries (with decorrelated jitter) of the single-flight "
    "master shard-location lookup leader before it fails its waiters — "
    "one transient master hiccup no longer fails a whole burst of "
    "degraded reads (clamped to >= 0).",
    parse=_clamped_int(0),
)

register_env(
    "WEEDTPU_READ_CACHE_MB", float, 64.0,
    "Byte budget (MiB) of the process-wide decoded-interval read cache: a "
    "hot degraded interval is reconstructed once per epoch, not once per "
    "request (the coalesce leader publishes its decode). 0 disables the "
    "cache entirely (no lookups, no counters). Clamped to >= 0.",
    parse=lambda raw: max(0.0, float(raw)),
)

register_env(
    "WEEDTPU_READ_CACHE_TTL_S", float, 30.0,
    "Age (seconds) after which a cached decoded interval expires and the "
    "next read re-decodes — the 'epoch' of decode-once-per-epoch serving. "
    "0 means entries never expire by age (eviction/invalidation only). "
    "Clamped to >= 0.",
    parse=lambda raw: max(0.0, float(raw)),
)


def env_table_markdown() -> str:
    """The README `WEEDTPU_*` table, generated from the registry."""
    lines = [
        "| Variable | Type | Default | Description |",
        "| --- | --- | --- | --- |",
    ]
    for name in sorted(ENV_REGISTRY):
        var = ENV_REGISTRY[name]
        default = "(empty)" if var.default == "" else f"`{var.default}`"
        doc = " ".join(var.doc.split()).replace("|", "\\|")
        lines.append(
            f"| `{name}` | {var.type.__name__} | {default} | {doc} |"
        )
    return "\n".join(lines) + "\n"
