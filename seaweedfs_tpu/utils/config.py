"""TOML configuration — weed/util/config.go + command/scaffold.go analog
[VERIFY: mount empty; SURVEY.md §5 "Config/flag system"]: named TOML
files (security.toml, master.toml, filer.toml, shell.toml) searched in
`.`, `~/.seaweedfs_tpu/`, `/etc/seaweedfs_tpu/`; `scaffold` prints
commented templates. Parsing uses stdlib tomllib."""

from __future__ import annotations

import os
from typing import Any, Optional

try:  # stdlib on 3.11+; this image runs 3.10
    import tomllib
except ImportError:  # pragma: no cover — version-dependent
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ImportError:
        tomllib = None  # type: ignore[assignment] — parse at use time

SEARCH_PATHS = [".", "~/.seaweedfs_tpu", "/etc/seaweedfs_tpu"]


def load_configuration(name: str, required: bool = False) -> dict[str, Any]:
    """Load `<name>.toml` from the search path; {} when absent."""
    fname = name if name.endswith(".toml") else name + ".toml"
    for d in SEARCH_PATHS:
        path = os.path.join(os.path.expanduser(d), fname)
        if os.path.exists(path):
            if tomllib is None:
                # a present config that can't be parsed must FAIL, not be
                # silently ignored — dropping security.toml would disable
                # auth without a trace. Absent configs (the common case)
                # never reach here, so 3.10 servers without TOML configs
                # run fine.
                raise RuntimeError(
                    f"{path} exists but no TOML parser is available "
                    "(python < 3.11 without the tomli package)"
                )
            with open(path, "rb") as f:
                return tomllib.load(f)
    if required:
        raise FileNotFoundError(
            f"{fname} not found in {[os.path.expanduser(d) for d in SEARCH_PATHS]}"
        )
    return {}


def get_nested(conf: dict, dotted: str, default: Any = None) -> Any:
    """conf lookup by 'a.b.c' path."""
    cur: Any = conf
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


SCAFFOLDS = {
    "security": '''\
# security.toml — put in ., ~/.seaweedfs_tpu/, or /etc/seaweedfs_tpu/
# JWT signing on the volume-server write path. Empty key = auth disabled.

[jwt.signing]
key = ""
expires_after_seconds = 10

# optional separate key gating reads
[jwt.signing.read]
key = ""
expires_after_seconds = 10

[guard]
# IPs allowed to bypass JWT checks
white_list = []

# TLS/mTLS for the gRPC control plane. Setting `ca` turns TLS on for every
# server and client in the process. Generate a throwaway CA + leaf pair with
#   python -c "from seaweedfs_tpu.security.tls import generate_self_signed; \\
#              print(generate_self_signed('./certs'))"
[grpc]
ca = ""
cert = ""
key = ""
require_client_auth = true    # mTLS: peers must present a CA-signed cert
# override_authority = "weedtpu-cluster"   # when certs name the cluster, not each host

# HTTPS on the HTTP data path (volume/filer/s3/webdav/iam servers); uses the
# [grpc] cert material
[https]
enabled = false
''',
    "master": '''\
# master.toml
[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1

[master.sequencer]
type = "memory"   # memory | snowflake
''',
    "shell": '''\
# shell.toml
[cluster]
default = "localhost"

[cluster.localhost]
master = "127.0.0.1:9333"
''',
    "filer": '''\
# filer.toml — filer metadata store selection
[memory]
enabled = false

[sqlite]
enabled = true
dbFile = "./filer.db"

# from-scratch embedded log-structured store (the leveldb2-analog):
# append-only CRC-framed log + in-memory index, auto-compaction
[log]
enabled = false
dir = "./filerlog"
''',
}


def scaffold(name: str) -> Optional[str]:
    return SCAFFOLDS.get(name)
