"""Shared HTTP server plumbing for the filer / s3 / webdav / iam servers
— one threading server class and one base handler so body-framing and
reply rules live in a single place.
"""

from __future__ import annotations

import functools
import http.server
import socketserver
from typing import Optional


class ThreadingHTTPServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class QuietHandler(http.server.BaseHTTPRequestHandler):
    """Base handler: HTTP/1.1, silent access log, safe body read, uniform
    reply writer."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # access log off (reference: glog -v)
        pass

    def read_body(self) -> Optional[bytes]:
        """Request body per Content-Length. Returns None for chunked
        transfer encoding (unsupported — callers must answer 411, not
        silently store an empty body)."""
        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            return None
        length = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(length) if length > 0 else b""

    def reply_length_required(self) -> None:
        self.send_reply(411, b"chunked transfer encoding not supported", "text/plain")

    def send_reply(
        self,
        code: int,
        body: bytes = b"",
        ctype: str = "application/octet-stream",
        headers: Optional[dict] = None,
        head: bool = False,
    ) -> None:
        """Write a full response. 204/304 carry no body (RFC 9110; a body
        there desyncs keep-alive clients). `head` sends headers only —
        pass the intended Content-Length via `headers`."""
        headers = dict(headers or {})
        if code in (204, 304):
            body = b""
        self.send_response(code)
        if body or head:
            self.send_header("Content-Type", ctype)
        if "Content-Length" not in headers:
            self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        if body and not head:
            self.wfile.write(body)


def safe_int(value, default: int) -> int:
    """Parse client-supplied ints without letting ValueError kill the
    handler thread."""
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def loopback_aliases(host: str) -> set[str]:
    """Hostnames clients may legitimately sign for when a server binds
    loopback or a wildcard address — callers append ':port' once the bound
    port is known. Wildcard binds also include the machine's own hostname
    and addresses, so clients reaching the server via its LAN IP or DNS
    name aren't 403'd; deployments behind proxies/LBs still must list
    their advertised names explicitly (extra_hosts / -allowedHosts).
    All names are lower-cased — Host comparison is case-insensitive
    (RFC 9110 §4.2.3)."""
    aliases: set[str] = set()
    if host in ("0.0.0.0", "::", "127.0.0.1", "localhost", "::1"):
        aliases = {"127.0.0.1", "localhost", "[::1]"}
    if host in ("0.0.0.0", "::"):
        aliases |= _self_addresses()
    return {a.lower() for a in aliases}


@functools.lru_cache(maxsize=1)
def _self_addresses() -> frozenset[str]:
    """The machine's own hostname + addresses, resolved once per process —
    getaddrinfo can block for the resolver timeout on hosts with broken
    DNS, and every server constructor calls loopback_aliases."""
    import socket

    found: set[str] = set()
    try:
        name = socket.gethostname()
        found.add(name)
        for info in socket.getaddrinfo(name, None):
            addr = info[4][0]
            found.add(f"[{addr}]" if ":" in addr else addr)
    except OSError:
        pass
    return frozenset(found)
