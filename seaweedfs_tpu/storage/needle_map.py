"""Needle maps: id -> (offset, size) per volume.

Mirror of weed/storage/needle_map (CompactMap / MemDb) [VERIFY: mount empty].
`MemDb` is the sorted in-memory store the EC encoder uses to produce .ecx from
.idx; `CompactMap` is the volume-serving map fed by .idx replay.
"""

from __future__ import annotations

from typing import BinaryIO, Iterator, Optional

from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types


class MemDb:
    """Sorted id->(offset,size) map with .idx ingest and ascending visit."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}

    def set(self, key: int, stored_offset: int, size: int) -> None:
        self._m[key] = (stored_offset, size)

    def delete(self, key: int) -> None:
        self._m.pop(key, None)

    def get(self, key: int) -> Optional[tuple[int, int]]:
        return self._m.get(key)

    def __len__(self) -> int:
        return len(self._m)

    def ascending_visit(self) -> Iterator[tuple[int, int, int]]:
        for key in sorted(self._m):
            off, size = self._m[key]
            yield key, off, size

    def load_from_idx(self, idx_path: str) -> None:
        """Replay an .idx log: last write wins; tombstones/zero-offset delete.
        (readNeedleMap semantics in the reference's ec_encoder.go.)"""
        with open(idx_path, "rb") as f:
            buf = f.read()
        for key, off, size in idx_mod.walk_index_buffer(buf):
            if off != 0 and not types.is_deleted(size):
                self.set(key, off, size)
            else:
                self.delete(key)

    def save_to_idx(self, path: str) -> None:
        idx_mod.write_entries(self.ascending_visit(), path)


class CompactMap(MemDb):
    """Serving-path map. Same semantics; kept as a distinct type to mirror the
    reference's needle_map.CompactMap seam (a future C++ native map can slot
    in behind this interface)."""
