"""Needle maps: id -> (offset, size) per volume.

Mirror of weed/storage/needle_map (CompactMap / MemDb / the leveldb and
sorted-file persistent variants) [VERIFY: mount empty]. `MemDb` is the
sorted in-memory store the EC encoder uses to produce .ecx from .idx;
`CompactMap` is the volume-serving map fed by .idx replay;
`SortedFileNeedleMap` is the persistent map for volumes whose needle
population does not fit (or should not be rebuilt into) RAM on every
mount — the role of needle_map_leveldb.go / needle_map_sorted_file.go.
"""

from __future__ import annotations

import bisect
import json
import os
from typing import BinaryIO, Iterator, Optional

import numpy as np

from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types


class MemDb:
    """Sorted id->(offset,size) map with .idx ingest and ascending visit."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}
        self._sorted: Optional[list[int]] = None  # cache, dropped on key churn

    def set(self, key: int, stored_offset: int, size: int) -> None:
        if key not in self._m:
            self._sorted = None
        self._m[key] = (stored_offset, size)

    def delete(self, key: int) -> None:
        if self._m.pop(key, None) is not None:
            self._sorted = None

    def get(self, key: int) -> Optional[tuple[int, int]]:
        return self._m.get(key)

    def __len__(self) -> int:
        return len(self._m)

    def ascending_visit(self, start: int = 0) -> Iterator[tuple[int, int, int]]:
        """Visit (key, offset, size) ascending by key, from `start` on.
        The sorted key list is cached until the key set changes, so a paged
        scan (VolumeNeedleIds: ~77 pages on a 5M-needle volume) sorts once
        and each page is O(log n + page), not a fresh sort per page."""
        if self._sorted is None:
            self._sorted = sorted(self._m)
        keys = self._sorted
        for key in keys[bisect.bisect_left(keys, start):]:
            entry = self._m.get(key)
            if entry is not None:  # key vanished since the cache was cut
                yield key, *entry

    def load_from_idx(self, idx_path: str) -> None:
        """Replay an .idx log: last write wins; tombstones/zero-offset delete.
        (readNeedleMap semantics in the reference's ec_encoder.go.)"""
        with open(idx_path, "rb") as f:
            buf = f.read()
        for key, off, size in idx_mod.walk_index_buffer(buf):
            if off != 0 and not types.is_deleted(size):
                self.set(key, off, size)
            else:
                self.delete(key)

    def save_to_idx(self, path: str) -> None:
        idx_mod.write_entries(self.ascending_visit(), path)


class CompactMap(MemDb):
    """Serving-path map. Same semantics; kept as a distinct type to mirror the
    reference's needle_map.CompactMap seam (a future C++ native map can slot
    in behind this interface)."""

    def close(self) -> None:  # interface parity with SortedFileNeedleMap
        pass


class SortedFileNeedleMap:
    """Persistent needle map: sorted live entries in a `.sdx` sidecar,
    binary-searched through a memory map, plus a small in-RAM overlay of
    post-build mutations.

    Mount cost is O(tail), not O(needles): the `.sdx.meta` sidecar records
    the `.idx` byte offset the `.sdx` was built from, so a clean reopen
    memory-maps the sorted file and replays only `.idx` entries appended
    after that watermark. A crash between an `.idx` append and the next
    flush loses nothing — the tail replay recovers it. Entries use the
    same big-endian 16-byte record as `.idx`/`.ecx`.

    [ref: weed/storage/needle_map_sorted_file.go,
    needle_map_leveldb.go — mount empty, SURVEY.md §2.1 "Needle maps".]
    """

    OVERLAY_FLUSH_ENTRIES = 128 * 1024  # merge threshold, ~3 MB of dict

    def __init__(self, base_path: str):
        self.idx_path = base_path + ".idx"
        self.sdx_path = base_path + ".sdx"
        self.meta_path = base_path + ".sdx.meta"
        # key -> (offset, size) live, or None meaning deleted-since-build
        self._overlay: dict[int, Optional[tuple[int, int]]] = {}
        self._mm: Optional[np.ndarray] = None
        self._keys: Optional[np.ndarray] = None
        self._count = 0
        self.rebuilt_full = False  # diagnostics: did mount pay a full scan?
        self.replayed_tail = 0
        # While replaying the .idx tail, the overlay only covers entries up
        # to this byte offset; a flush during replay must not stamp the
        # watermark past it, or a crash mid-replay would skip the rest of
        # the tail on the next mount (lost entries / resurrected deletes).
        self._replay_pos: Optional[int] = None
        self._open()

    # -- build / open --------------------------------------------------------

    def _idx_size(self) -> int:
        try:
            return os.path.getsize(self.idx_path)
        except OSError:
            return 0

    def _map_sdx(self) -> None:
        size = os.path.getsize(self.sdx_path)
        n = size // types.NEEDLE_MAP_ENTRY_SIZE
        if n:
            self._mm = np.memmap(self.sdx_path, dtype=idx_mod._BE_ENTRY_DTYPE,
                                 mode="r", shape=(n,))
            self._keys = self._mm["key"]
        else:
            self._mm = None
            self._keys = None

    def _open(self) -> None:
        idx_size = self._idx_size()
        watermark = -1
        if os.path.exists(self.sdx_path) and os.path.exists(self.meta_path):
            try:
                with open(self.meta_path, encoding="utf-8") as f:
                    watermark = int(json.load(f)["idx_size"])
            except (ValueError, KeyError, OSError):
                watermark = -1
        if 0 <= watermark <= idx_size:
            self._map_sdx()
            self._count = 0 if self._mm is None else len(self._mm)
            self._replay_tail(watermark, idx_size)
        else:
            self._rebuild(idx_size)

    def _rebuild(self, idx_size: int) -> None:
        """Full .idx replay -> fresh sorted .sdx (first mount / lost meta)."""
        mem = MemDb()
        if os.path.exists(self.idx_path):
            mem.load_from_idx(self.idx_path)
        tmp = self.sdx_path + ".tmp"
        idx_mod.write_entries(mem.ascending_visit(), tmp)
        os.replace(tmp, self.sdx_path)
        self._write_meta(idx_size)
        self._map_sdx()
        self._count = len(mem)
        self._overlay.clear()
        self.rebuilt_full = True

    def _replay_tail(self, watermark: int, idx_size: int) -> None:
        """Apply .idx entries appended after the .sdx build watermark."""
        if idx_size <= watermark:
            return
        with open(self.idx_path, "rb") as f:
            f.seek(watermark)
            buf = f.read(idx_size - watermark)
        self._replay_pos = watermark
        try:
            for key, off, size in idx_mod.walk_index_buffer(buf):
                if off != 0 and not types.is_deleted(size):
                    self.set(key, off, size)
                else:
                    self.delete(key)
                self.replayed_tail += 1
                self._replay_pos = (
                    watermark + self.replayed_tail * types.NEEDLE_MAP_ENTRY_SIZE
                )
        finally:
            self._replay_pos = None

    def _write_meta(self, idx_size: int) -> None:
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"idx_size": idx_size}, f)
            f.flush()
            os.fsync(f.fileno())  # watermark vouches for sdx coverage
        os.replace(tmp, self.meta_path)

    # -- map interface -------------------------------------------------------

    def _search_sdx(self, key: int) -> Optional[tuple[int, int]]:
        if self._keys is None:
            return None
        pos = int(np.searchsorted(self._keys, np.uint64(key)))
        if pos >= len(self._keys) or int(self._keys[pos]) != key:
            return None
        row = self._mm[pos]
        return int(row["offset"]), int(row["size"])

    def get(self, key: int) -> Optional[tuple[int, int]]:
        if key in self._overlay:
            return self._overlay[key]
        return self._search_sdx(key)

    def set(self, key: int, stored_offset: int, size: int) -> None:
        if self.get(key) is None:
            self._count += 1
        self._overlay[key] = (stored_offset, size)
        if len(self._overlay) >= self.OVERLAY_FLUSH_ENTRIES:
            self.flush()

    def delete(self, key: int) -> None:
        if self.get(key) is not None:
            self._count -= 1
            self._overlay[key] = None

    def __len__(self) -> int:
        return self._count

    def ascending_visit(self, start: int = 0) -> Iterator[tuple[int, int, int]]:
        """Merge the sorted file with the sorted overlay, from `start` on
        (binary search into both sides — no linear skip for paged callers)."""
        overlay_keys = sorted(k for k in self._overlay if k >= start)
        oi = 0
        if self._mm is not None and self._keys is not None:
            first = int(np.searchsorted(self._keys, np.uint64(start)))
            rows = self._mm[first:]
        else:
            rows = ()
        for row in rows:
            key = int(row["key"])
            while oi < len(overlay_keys) and overlay_keys[oi] < key:
                ok = overlay_keys[oi]
                if self._overlay[ok] is not None:
                    yield ok, *self._overlay[ok]
                oi += 1
            if oi < len(overlay_keys) and overlay_keys[oi] == key:
                ov = self._overlay[overlay_keys[oi]]
                if ov is not None:
                    yield key, *ov
                oi += 1
                continue
            yield key, int(row["offset"]), int(row["size"])
        while oi < len(overlay_keys):
            ok = overlay_keys[oi]
            if self._overlay[ok] is not None:
                yield ok, *self._overlay[ok]
            oi += 1

    # -- persistence ---------------------------------------------------------

    def flush(self) -> None:
        """Merge the overlay into a fresh sorted .sdx and advance the
        watermark to the covered .idx position (the full current size,
        or the replay cursor when flushed mid-tail-replay)."""
        covered = self._replay_pos if self._replay_pos is not None else self._idx_size()
        if not self._overlay and os.path.exists(self.sdx_path):
            self._write_meta(covered)
            return
        tmp = self.sdx_path + ".tmp"
        idx_mod.write_entries(self.ascending_visit(), tmp)
        # drop the old memmap handle before replacing the file under it
        self._mm = None
        self._keys = None
        os.replace(tmp, self.sdx_path)
        self._write_meta(covered)
        self._overlay.clear()
        self._map_sdx()

    def close(self) -> None:
        self.flush()
        self._mm = None
        self._keys = None

    def load_from_idx(self, idx_path: str) -> None:
        """Interface parity with MemDb (used after compaction): rebuild
        the sidecar from the given .idx."""
        self.idx_path = idx_path
        self._rebuild(self._idx_size())


def new_needle_map(kind: str, base_path: str):
    """Factory mirroring the reference's -index flag seam
    (memory | sorted_file)."""
    if kind == "memory":
        return CompactMap()
    if kind == "sorted_file":
        return SortedFileNeedleMap(base_path)
    raise ValueError(f"unknown needle map kind {kind!r}")
