"""Store — the per-server set of disk locations, normal volumes, and EC
volumes. Mirror of weed/storage/store.go + disk_location*.go + store_ec.go
[VERIFY: mount empty; SURVEY.md §2.1].
"""

from __future__ import annotations

import glob
import os
import re
import threading
from typing import Optional

from seaweedfs_tpu.ec.ec_volume import EcVolume
from seaweedfs_tpu.ec.shard_bits import EcVolumeInfo, ShardBits
from seaweedfs_tpu.utils import glog
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.super_block import ReplicaPlacement, SuperBlock, TTL
from seaweedfs_tpu.storage.volume import Volume

_BASE_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)$")


def parse_base_name(base: str) -> Optional[tuple[str, int]]:
    m = _BASE_RE.match(base)
    if not m:
        return None
    return m.group("col") or "", int(m.group("vid"))


class DiskLocation:
    def __init__(self, directory: str):
        # normpath: path-equality checks (e.g. resolving which location owns
        # a base path) must not break on a trailing slash in -dir
        self.directory = os.path.normpath(directory)
        os.makedirs(directory, exist_ok=True)
        self.volumes: dict[int, Volume] = {}
        self.ec_volumes: dict[int, EcVolume] = {}

    def load(self, encoder: Optional[Encoder] = None, needle_map_kind: str = "memory") -> None:
        # tiered volumes have no local .dat — discovered via .tierinfo
        discovered = glob.glob(os.path.join(self.directory, "*.dat")) + glob.glob(
            os.path.join(self.directory, "*.tierinfo")
        )
        for path in discovered:
            base = os.path.basename(path).rsplit(".", 1)[0]
            parsed = parse_base_name(base)
            if parsed is None:
                continue
            collection, vid = parsed
            if vid not in self.volumes:
                self.volumes[vid] = Volume(
                    self.directory, vid, collection, needle_map_kind=needle_map_kind
                )
        for ecx in glob.glob(os.path.join(self.directory, "*.ecx")):
            base = os.path.basename(ecx)[: -len(".ecx")]
            parsed = parse_base_name(base)
            if parsed is None:
                continue
            collection, vid = parsed
            base_path = os.path.join(self.directory, base)
            if vid not in self.ec_volumes and stripe.find_local_shards(base_path):
                try:
                    self.ec_volumes[vid] = EcVolume(base_path, encoder=encoder)
                except (ValueError, KeyError) as e:
                    # a shard set contradicting its .eci geometry (typed
                    # EcGeometryError — e.g. a crash mid-conversion-
                    # cutover) or a malformed/unusable .eci record (plain
                    # ValueError/KeyError out of geometry_from_info) must
                    # not kill server boot OR get served: skip it loudly —
                    # the convert resume path / operator finishes the
                    # swap, and the next load picks the healed volume up
                    glog.warning("skipping ec volume %d: %s", vid, e)


class Store:
    def __init__(
        self,
        directories: list[str],
        encoder: Optional[Encoder] = None,
        needle_map_kind: str = "memory",
    ):
        self.encoder = encoder or new_encoder()
        self.locations = [DiskLocation(d) for d in directories]
        # -index flag analog: memory rebuilds the id map in RAM per mount,
        # sorted_file binary-searches a persistent .sdx sidecar
        self.needle_map_kind = needle_map_kind
        self._lock = threading.RLock()
        #: optional post-append hook `callback(vid)`, fired after every
        #: acked needle write/delete (both are .dat appends) — the inline-EC
        #: ingest manager polls its stripe builders through this seam. Must
        #: never raise into the write path (callers install a guarded fn).
        self.on_write: Optional[callable] = None

    def load(self) -> None:
        with self._lock:
            for loc in self.locations:
                loc.load(self.encoder, self.needle_map_kind)

    def close(self) -> None:
        with self._lock:
            for loc in self.locations:
                for v in loc.volumes.values():
                    v.close()
                for ev in loc.ec_volumes.values():
                    ev.close()

    # -- normal volumes ------------------------------------------------------

    def _pick_location(self) -> DiskLocation:
        return min(self.locations, key=lambda l: len(l.volumes) + len(l.ec_volumes))

    def create_volume(
        self,
        vid: int,
        collection: str = "",
        replication: str = "000",
        ttl: str = "",
        version: int = 3,
    ) -> Volume:
        with self._lock:
            if self.get_volume(vid) is not None:
                raise ValueError(f"volume {vid} already exists")
            sb = SuperBlock(
                version=version,
                replica_placement=ReplicaPlacement.parse(replication),
                ttl=TTL.parse(ttl),
            )
            loc = self._pick_location()
            v = Volume(
                loc.directory,
                vid,
                collection,
                super_block=sb,
                needle_map_kind=self.needle_map_kind,
            )
            loc.volumes[vid] = v
            return v

    def get_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            if vid in loc.volumes:
                return loc.volumes[vid]
        return None

    def get_ec_volume(self, vid: int) -> Optional[EcVolume]:
        for loc in self.locations:
            if vid in loc.ec_volumes:
                return loc.ec_volumes[vid]
        return None

    def write_needle(self, vid: int, n: Needle) -> tuple[int, int]:
        v = self.get_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        out = v.write_needle(n)
        if self.on_write is not None:
            self.on_write(vid)
        return out

    def read_needle(self, vid: int, needle_id: int, cookie: Optional[int] = None) -> Needle:
        v = self.get_volume(vid)
        if v is not None:
            return v.read_needle(needle_id, cookie)
        ev = self.get_ec_volume(vid)
        if ev is not None:
            return self.read_ec_needle(vid, needle_id, cookie)
        raise KeyError(f"volume {vid} not found")

    def delete_needle(self, vid: int, needle_id: int) -> bool:
        v = self.get_volume(vid)
        if v is not None:
            found = v.delete_needle(needle_id)
            if self.on_write is not None:
                self.on_write(vid)  # a tombstone is a .dat append too
            return found
        ev = self.get_ec_volume(vid)
        if ev is not None:
            return ev.delete_needle(needle_id)
        raise KeyError(f"volume {vid} not found")

    # -- EC volumes (store_ec.go analog) -------------------------------------

    def read_ec_needle(self, vid: int, needle_id: int, cookie: Optional[int] = None) -> Needle:
        ev = self.get_ec_volume(vid)
        if ev is None:
            raise KeyError(f"ec volume {vid} not found")
        blob = ev.read_needle_blob(needle_id)
        n = Needle.from_bytes(blob, ev.version)
        if n.id != needle_id:
            raise IOError(f"ec needle id mismatch: {n.id:x} != {needle_id:x}")
        if cookie is not None and n.cookie != cookie:
            raise PermissionError(f"needle {needle_id:x}: cookie mismatch")
        return n

    def mount_ec_volume(self, vid: int, base_path: str) -> EcVolume:
        with self._lock:
            loc = next(
                (l for l in self.locations if os.path.dirname(base_path) == l.directory),
                self.locations[0],
            )
            old = loc.ec_volumes.pop(vid, None)
            if old is not None:
                old.close()
            ev = EcVolume(base_path, encoder=self.encoder)
            loc.ec_volumes[vid] = ev
            return ev

    def unmount_ec_volume(self, vid: int) -> None:
        with self._lock:
            for loc in self.locations:
                ev = loc.ec_volumes.pop(vid, None)
                if ev is not None:
                    ev.close()

    # -- status / heartbeat --------------------------------------------------

    def remove_volume(self, vid: int) -> bool:
        """Close and unlink a local volume's files. The store lock covers
        only the map pop — close() can block behind a minutes-long
        compaction's volume lock, and holding Store._lock through that
        would stall create/mount (and with them every Assign-driven grow)
        cluster-wide."""
        popped = []
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    popped.append(v)
        for v in popped:
            v.close()
            # .tierinfo included: leaving it would resurrect the volume as
            # a zombie on the next mount (load() discovers via *.tierinfo)
            for ext in (".dat", ".idx", ".sdx", ".sdx.meta", ".tierinfo"):
                p = v.base_path + ext
                if os.path.exists(p):
                    os.remove(p)
        return bool(popped)

    def unmount_volume(self, vid: int) -> bool:
        """Close a volume and stop serving it, KEEPING its files on disk
        (VolumeUnmount analog) — the inverse of mount_volume."""
        popped = []
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    popped.append(v)
        for v in popped:
            v.close()
        return bool(popped)

    def mount_volume(self, vid: int) -> bool:
        """(Re)open an unmounted volume from its on-disk files
        (VolumeMount analog). Returns False when no files exist.
        Volume() replays the index — potentially minutes — so it runs
        OUTSIDE the store lock (same discipline as remove_volume)."""
        import glob as _glob

        target: Optional[tuple[DiskLocation, str]] = None
        with self._lock:
            for loc in self.locations:
                if vid in loc.volumes:
                    return True  # already mounted
            for loc in self.locations:
                for path in _glob.glob(os.path.join(loc.directory, "*.dat")) + _glob.glob(
                    os.path.join(loc.directory, "*.tierinfo")
                ):
                    base = os.path.basename(path).rsplit(".", 1)[0]
                    parsed = parse_base_name(base)
                    if parsed is not None and parsed[1] == vid:
                        target = (loc, parsed[0])
                        break
                if target:
                    break
        if target is None:
            return False
        loc, collection = target
        v = Volume(loc.directory, vid, collection, needle_map_kind=self.needle_map_kind)
        with self._lock:
            if vid in loc.volumes:  # raced with another mount: keep theirs
                v.close()
            else:
                loc.volumes[vid] = v
        return True

    def expired_volume_ids(self) -> list[int]:
        """TTL volumes whose NEWEST write has aged out (the reference
        prunes ttl volumes the same way: .dat mtime is the last append,
        so mtime + ttl < now means every needle inside is past its TTL).
        Scan only — the volume server deletes under its per-volume
        maintenance mutex so a reap can never race a copy/encode."""
        import time as _time

        expired = []
        with self._lock:
            for loc in self.locations:
                for vid, v in loc.volumes.items():
                    ttl_s = v.super_block.ttl.seconds
                    if not ttl_s:
                        continue
                    mtime = v.last_modified()
                    if mtime and mtime + ttl_s < _time.time():
                        expired.append(vid)
        return expired

    def reap_expired_volumes(self) -> list[int]:
        """Standalone (no volume server) expiry pass, used by tests and
        local tools; servers go through expired_volume_ids() + their
        maintenance mutex instead."""
        expired = [
            vid
            for vid in self.expired_volume_ids()
            if (v := self.get_volume(vid)) is not None and not v.read_only
        ]
        for vid in expired:
            self.remove_volume(vid)
        return expired

    def volume_infos(self) -> list[dict]:
        out = []
        for loc in self.locations:
            for vid, v in loc.volumes.items():
                # lock-free snapshot: the heartbeat must not block behind a
                # long-running compaction's volume lock
                size, count, garbage = v.stats_snapshot()
                last_modified = v.last_modified()  # ec.encode -quietFor input
                out.append(
                    {
                        "id": vid,
                        "collection": v.collection,
                        "size": size,
                        "file_count": count,
                        "read_only": v.read_only,
                        "replica_placement": str(v.super_block.replica_placement),
                        "ttl": str(v.super_block.ttl),
                        "version": v.version,
                        "disk_type": "remote" if v.tiered else "",
                        "garbage_ratio": round(garbage, 4),
                        "last_modified": last_modified,
                    }
                )
        return out

    def ec_volume_infos(self) -> list[EcVolumeInfo]:
        out = []
        for loc in self.locations:
            for vid, ev in loc.ec_volumes.items():
                parsed = parse_base_name(os.path.basename(ev.base))
                out.append(
                    EcVolumeInfo(
                        volume_id=vid,
                        collection=parsed[0] if parsed else "",
                        shard_bits=ShardBits.from_ids(ev.shard_ids),
                        shard_size=int(ev.shard_size or 0),
                        data_shards=int(ev.data_shards),
                        total_shards=int(ev.total_shards),
                    )
                )
        return out
