"""On-disk primitive types — mirror of weed/storage/types [VERIFY: reference
mount empty; layouts follow upstream SeaweedFS, SURVEY.md §2.1].

NeedleId: uint64, big-endian on disk.
Offset:   uint32 on disk, counting units of NEEDLE_PADDING_SIZE (8 bytes) —
          so a 4-byte offset addresses 32 GiB volumes.
Size:     int32, big-endian two's complement; negative = deleted
          (TOMBSTONE_FILE_SIZE = -1).
Index entry (.idx / .ecx): key(8) | offset(4) | size(4) = 16 bytes.
"""

from __future__ import annotations

import struct

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 4
SIZE_SIZE = 4
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
NEEDLE_PADDING_SIZE = 8
NEEDLE_HEADER_SIZE = 4 + NEEDLE_ID_SIZE + SIZE_SIZE  # cookie + id + size
NEEDLE_CHECKSUM_SIZE = 4
TIMESTAMP_SIZE = 8

TOMBSTONE_FILE_SIZE = -1

_ENTRY = struct.Struct(">QIi")  # key, offset (x8 units), size


def is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def offset_to_bytes(actual_offset: int) -> int:
    """Byte offset -> stored uint32 (units of 8). Must be 8-aligned."""
    if actual_offset % NEEDLE_PADDING_SIZE:
        raise ValueError(f"offset {actual_offset} not {NEEDLE_PADDING_SIZE}-aligned")
    return actual_offset // NEEDLE_PADDING_SIZE


def offset_to_actual(stored: int) -> int:
    return stored * NEEDLE_PADDING_SIZE


def pack_index_entry(key: int, stored_offset: int, size: int) -> bytes:
    return _ENTRY.pack(key, stored_offset, size)


def unpack_index_entry(buf: bytes, pos: int = 0) -> tuple[int, int, int]:
    """-> (key, stored_offset, size)."""
    return _ENTRY.unpack_from(buf, pos)


def actual_size(size: int, version: int = 3) -> int:
    """Total on-disk bytes a needle record of body `size` occupies
    (header + body + checksum [+ timestamp for v3] + padding to 8)."""
    base = NEEDLE_HEADER_SIZE + max(size, 0) + NEEDLE_CHECKSUM_SIZE
    if version == 3:
        base += TIMESTAMP_SIZE
    return base + padding_length(size, version)


def padding_length(size: int, version: int = 3) -> int:
    base = NEEDLE_HEADER_SIZE + max(size, 0) + NEEDLE_CHECKSUM_SIZE
    if version == 3:
        base += TIMESTAMP_SIZE
    return (-base) % NEEDLE_PADDING_SIZE
