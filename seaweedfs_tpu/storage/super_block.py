"""Volume superblock + replica placement + TTL — mirror of
weed/storage/super_block [VERIFY: mount empty].

Superblock: 8 bytes at .dat offset 0:
  version(1) | replica_placement(1) | ttl(2) | compact_revision(2 BE) | extra(2)

ReplicaPlacement packs three digits x,y,z (copies on other DCs, other racks,
same rack) into one byte as x*100 + y*10 + z.

TTL packs (count, unit) into 2 bytes; units: minute/hour/day/week/month/year.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

SUPER_BLOCK_SIZE = 8

TTL_UNITS = {
    0: "",
    1: "m",
    2: "h",
    3: "d",
    4: "w",
    5: "M",
    6: "y",
}
TTL_UNIT_CODES = {v: k for k, v in TTL_UNITS.items() if v}
_TTL_MINUTES = {"m": 1, "h": 60, "d": 24 * 60, "w": 7 * 24 * 60, "M": 31 * 24 * 60, "y": 365 * 24 * 60}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: str = ""

    @classmethod
    def parse(cls, s: str) -> "TTL":
        s = (s or "").strip()
        if not s:
            return cls()
        unit = s[-1]
        if unit.isdigit():
            count, unit = int(s), "m"
        elif unit not in TTL_UNIT_CODES:
            raise ValueError(f"bad ttl unit {unit!r}")
        else:
            count = int(s[:-1] or "0")
        if not 0 <= count <= 255:
            # one on-disk byte holds the count: silently wrapping (300m ->
            # 44m) would expire data early, so reject at the boundary
            raise ValueError(
                f"ttl count {count}{unit} exceeds 255 — use a larger unit"
            )
        return cls(count, unit)

    def to_bytes(self) -> bytes:
        if not self.count:
            return b"\x00\x00"
        if not 0 < self.count <= 255:
            raise ValueError(f"ttl count {self.count} not storable in one byte")
        return bytes([self.count, TTL_UNIT_CODES[self.unit]])

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if len(b) < 2 or b[0] == 0:
            return cls()
        return cls(b[0], TTL_UNITS.get(b[1], "m"))

    @property
    def seconds(self) -> int:
        """0 = no expiry."""
        if not self.count:
            return 0
        return self.count * _TTL_MINUTES[self.unit or "m"] * 60

    @property
    def minutes(self) -> int:
        return self.count * _TTL_MINUTES.get(self.unit, 0) if self.count else 0

    def __str__(self) -> str:
        return f"{self.count}{self.unit}" if self.count else ""


@dataclass(frozen=True)
class ReplicaPlacement:
    same_rack: int = 0
    diff_rack: int = 0
    diff_dc: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        s = (s or "000").strip()
        if len(s) != 3 or not s.isdigit():
            raise ValueError(f"replica placement must be 3 digits, got {s!r}")
        return cls(diff_dc=int(s[0]), diff_rack=int(s[1]), same_rack=int(s[2]))

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls(diff_dc=b // 100, diff_rack=(b // 10) % 10, same_rack=b % 10)

    @property
    def copy_count(self) -> int:
        return self.same_rack + self.diff_rack + self.diff_dc + 1

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"


@dataclass
class SuperBlock:
    version: int = 3
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    compact_revision: int = 0

    def to_bytes(self) -> bytes:
        return struct.pack(
            ">BB2sHH",
            self.version,
            self.replica_placement.to_byte(),
            self.ttl.to_bytes(),
            self.compact_revision,
            0,
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("superblock too short")
        version, rp, ttl_b, rev, _ = struct.unpack(">BB2sHH", b[:SUPER_BLOCK_SIZE])
        return cls(
            version=version,
            replica_placement=ReplicaPlacement.from_byte(rp),
            ttl=TTL.from_bytes(ttl_b),
            compact_revision=rev,
        )
