""".idx index-file walker — mirror of weed/storage/idx [VERIFY: mount empty].

A .idx file is an append-only log of 16-byte entries (key, offset, size); the
same record shape, sorted by key, is the .ecx format.
"""

from __future__ import annotations

import io
from typing import BinaryIO, Callable, Iterator

import numpy as np

from seaweedfs_tpu.storage import types


def walk_index_buffer(buf: bytes) -> Iterator[tuple[int, int, int]]:
    """Yield (key, stored_offset, size) for each complete 16-byte entry."""
    n = len(buf) // types.NEEDLE_MAP_ENTRY_SIZE
    for i in range(n):
        yield types.unpack_index_entry(buf, i * types.NEEDLE_MAP_ENTRY_SIZE)


def walk_index_file(f: BinaryIO | str, fn: Callable[[int, int, int], None]) -> None:
    """WalkIndexFile semantics: call fn(key, offset, size) per entry."""
    if isinstance(f, str):
        with open(f, "rb") as fh:
            data = fh.read()
    else:
        data = f.read()
    for key, off, size in walk_index_buffer(data):
        fn(key, off, size)


def index_entries_array(buf: bytes) -> np.ndarray:
    """Vectorized parse: -> structured array with key/offset/size columns."""
    n = len(buf) // types.NEEDLE_MAP_ENTRY_SIZE
    raw = np.frombuffer(buf[: n * types.NEEDLE_MAP_ENTRY_SIZE], dtype=np.uint8).reshape(n, 16)
    key = raw[:, 0:8].astype(np.uint64)
    keys = np.zeros(n, dtype=np.uint64)
    for b in range(8):
        keys = (keys << np.uint64(8)) | key[:, b]
    offs = (
        (raw[:, 8].astype(np.uint32) << 24)
        | (raw[:, 9].astype(np.uint32) << 16)
        | (raw[:, 10].astype(np.uint32) << 8)
        | raw[:, 11].astype(np.uint32)
    )
    sizes = (
        (raw[:, 12].astype(np.uint32) << 24)
        | (raw[:, 13].astype(np.uint32) << 16)
        | (raw[:, 14].astype(np.uint32) << 8)
        | raw[:, 15].astype(np.uint32)
    ).astype(np.int32)
    out = np.zeros(n, dtype=[("key", np.uint64), ("offset", np.uint32), ("size", np.int32)])
    out["key"], out["offset"], out["size"] = keys, offs, sizes
    return out


def write_entries(entries, out: BinaryIO | str) -> None:
    """Write (key, stored_offset, size) triples as 16-byte records."""
    sink = open(out, "wb") if isinstance(out, str) else out
    try:
        for key, off, size in entries:
            sink.write(types.pack_index_entry(key, off, size))
    finally:
        if isinstance(out, str):
            sink.close()
