""".idx index-file walker — mirror of weed/storage/idx [VERIFY: mount empty].

A .idx file is an append-only log of 16-byte entries (key, offset, size); the
same record shape, sorted by key, is the .ecx format.
"""

from __future__ import annotations

import io
from typing import BinaryIO, Callable, Iterator

import numpy as np

from seaweedfs_tpu.storage import types


def walk_index_buffer(buf: bytes) -> Iterator[tuple[int, int, int]]:
    """Yield (key, stored_offset, size) for each complete 16-byte entry."""
    n = len(buf) // types.NEEDLE_MAP_ENTRY_SIZE
    for i in range(n):
        yield types.unpack_index_entry(buf, i * types.NEEDLE_MAP_ENTRY_SIZE)


def walk_index_file(f: BinaryIO | str, fn: Callable[[int, int, int], None]) -> None:
    """WalkIndexFile semantics: call fn(key, offset, size) per entry."""
    if isinstance(f, str):
        with open(f, "rb") as fh:
            data = fh.read()
    else:
        data = f.read()
    for key, off, size in walk_index_buffer(data):
        fn(key, off, size)


_BE_ENTRY_DTYPE = np.dtype([("key", ">u8"), ("offset", ">u4"), ("size", ">i4")])
_NATIVE_ENTRY_DTYPE = np.dtype(
    [("key", np.uint64), ("offset", np.uint32), ("size", np.int32)]
)


def index_entries_array(buf: bytes) -> np.ndarray:
    """Vectorized parse: -> structured array with key/offset/size columns."""
    n = len(buf) // types.NEEDLE_MAP_ENTRY_SIZE
    be = np.frombuffer(buf[: n * types.NEEDLE_MAP_ENTRY_SIZE], dtype=_BE_ENTRY_DTYPE)
    return be.astype(_NATIVE_ENTRY_DTYPE)


def write_entries(entries, out: BinaryIO | str) -> None:
    """Write (key, stored_offset, size) triples as 16-byte records."""
    # weedlint: ignore[open-no-ctx] conditional open (path-or-handle API), closed in the finally below
    sink = open(out, "wb") if isinstance(out, str) else out
    try:
        for key, off, size in entries:
            sink.write(types.pack_index_entry(key, off, size))
    finally:
        if isinstance(out, str):
            sink.close()
