"""Volume engine — append-only needle log + index, the L1 core.

Mirror of weed/storage/volume*.go (volume_read/write/loading/vacuum/checking)
[VERIFY: mount empty; SURVEY.md §2.1]. A volume is <collection>_<vid>.dat
(superblock + needle records at 8-aligned offsets) plus <...>.idx (append-only
16-byte entries). Deletes append a tombstone record and a tombstone index
entry. Vacuum rewrites live needles into a fresh .dat/.idx pair.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle import CURRENT_VERSION, Needle
from seaweedfs_tpu.storage.needle_map import CompactMap
from seaweedfs_tpu.storage.super_block import SuperBlock


class NeedleExpired(KeyError):
    """TTL needle aged out: reads as absent; compaction reclaims it."""


class VolumeReadOnly(IOError):
    pass


class Volume:
    def __init__(
        self,
        dir_: str,
        volume_id: int,
        collection: str = "",
        super_block: Optional[SuperBlock] = None,
        needle_map_kind: str = "memory",
    ):
        self.dir = dir_
        self.id = volume_id
        self.collection = collection
        self.read_only = False
        self._lock = threading.RLock()
        self.needle_map_kind = needle_map_kind
        self.nm = CompactMap()
        base = f"{collection}_{volume_id}" if collection else str(volume_id)
        self.base_path = os.path.join(dir_, base)
        self.dat_path = self.base_path + ".dat"
        self.idx_path = self.base_path + ".idx"

        self.tiered = False
        if os.path.exists(self.base_path + ".tierinfo") and not os.path.exists(
            self.dat_path
        ):
            # cold volume: .dat lives in remote storage (backend row,
            # SURVEY.md §2.1); serve reads through the remote backend
            from seaweedfs_tpu.remote_storage.tier import open_tiered_dat

            self._dat = open_tiered_dat(self.base_path)
            self.tiered = True
            self.read_only = True
            exists = True
        else:
            exists = os.path.exists(self.dat_path)
            # weedlint: ignore[open-no-ctx] mount-lifetime .dat handle, closed in close()
            self._dat = open(self.dat_path, "r+b" if exists else "w+b")
        try:
            if exists:
                self._dat.seek(0, os.SEEK_END)
                dat_size = self._dat.tell()
                if dat_size >= 8:
                    self._dat.seek(0)
                    self.super_block = SuperBlock.from_bytes(self._dat.read(8))
                else:
                    self.super_block = super_block or SuperBlock()
                    self._write_super_block()
                if not os.path.exists(self.idx_path) and dat_size > 8 and not self.tiered:
                    # .dat has records but the index is gone (crash, manual
                    # deletion): rebuild it by scan before serving, else
                    # reads miss and a compact would wipe the volume.
                    # Structure-only scan: per-needle CRC is not the index's
                    # job — a flipped data bit surfaces on that needle's
                    # read, not as a refusal to open the whole volume.
                    from seaweedfs_tpu.storage.scan import rebuild_idx

                    rebuild_idx(self.base_path, verify_crc=False)
                if needle_map_kind != "memory":
                    # persistent map: O(tail) mount — binary-searches the
                    # .sdx sidecar instead of rebuilding the id map in RAM
                    from seaweedfs_tpu.storage.needle_map import new_needle_map

                    self.nm = new_needle_map(needle_map_kind, self.base_path)
                elif os.path.exists(self.idx_path):
                    self.nm.load_from_idx(self.idx_path)
            else:
                self.super_block = super_block or SuperBlock()
                self._write_super_block()
                if needle_map_kind != "memory":
                    from seaweedfs_tpu.storage.needle_map import new_needle_map

                    self.nm = new_needle_map(needle_map_kind, self.base_path)
            # weedlint: ignore[open-no-ctx] mount-lifetime .idx handle, closed in close()
            self._idx = open(self.idx_path, "ab")
            # live-byte accounting for the garbage ratio that drives the
            # master's automatic vacuum (topology_vacuum.go analog): one
            # O(live) pass at mount, then maintained incrementally
            self._live_bytes = sum(
                types.actual_size(size, self.version)
                for _, _, size in self.nm.ascending_visit()
            )
        except BaseException:
            self._dat.close()
            raise

    def _write_super_block(self) -> None:
        self._dat.seek(0)
        self._dat.write(self.super_block.to_bytes())
        self._dat.flush()

    @property
    def version(self) -> int:
        return self.super_block.version

    def configure_replication(self, replication: str) -> None:
        """Rewrite the superblock's replica-placement byte in place
        (volume.configure.replication analog): replication is a topology
        property of the volume, so changing it must survive a remount."""
        from seaweedfs_tpu.storage.super_block import ReplicaPlacement

        with self._lock:
            self.super_block.replica_placement = ReplicaPlacement.parse(replication)
            self._write_super_block()

    def close(self) -> None:
        with self._lock:
            # .idx must be durable before the persistent map advances its
            # watermark past it
            self._idx.flush()
            self.nm.close()
            self._dat.close()
            self._idx.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- write path ----------------------------------------------------------

    def write_needle(self, n: Needle) -> tuple[int, int]:
        """Append a needle; returns (offset, body_size)."""
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.id} is read-only")
            self._dat.seek(0, os.SEEK_END)
            offset = self._dat.tell()
            if offset % types.NEEDLE_PADDING_SIZE:
                pad = types.NEEDLE_PADDING_SIZE - offset % types.NEEDLE_PADDING_SIZE
                self._dat.write(b"\x00" * pad)
                offset += pad
            rec = n.to_bytes(self.version)
            self._dat.write(rec)
            self._dat.flush()
            stored = types.offset_to_bytes(offset)
            old = self.nm.get(n.id)
            if old is not None:  # overwrite: the old record becomes garbage
                self._live_bytes -= types.actual_size(old[1], self.version)
            self.nm.set(n.id, stored, n.size)
            self._live_bytes += types.actual_size(n.size, self.version)
            self._idx.write(types.pack_index_entry(n.id, stored, n.size))
            self._idx.flush()
            return offset, n.size

    def delete_needle(self, needle_id: int) -> bool:
        """Tombstone a needle; returns False if absent."""
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.id} is read-only")
            old = self.nm.get(needle_id)
            if old is None:
                return False
            self._live_bytes -= types.actual_size(old[1], self.version)
            tomb = Needle(id=needle_id, cookie=0)
            self._dat.seek(0, os.SEEK_END)
            self._dat.write(tomb.to_bytes(self.version, tombstone=True))
            self._dat.flush()
            self.nm.delete(needle_id)
            self._idx.write(
                types.pack_index_entry(needle_id, 0, types.TOMBSTONE_FILE_SIZE)
            )
            self._idx.flush()
            return True

    # -- read path -----------------------------------------------------------

    def read_needle(self, needle_id: int, cookie: Optional[int] = None) -> Needle:
        with self._lock:
            loc = self.nm.get(needle_id)
            if loc is None:
                raise KeyError(f"needle {needle_id} not found in volume {self.id}")
            stored, size = loc
            offset = types.offset_to_actual(stored)
            self._dat.seek(offset)
            buf = self._dat.read(types.actual_size(size, self.version))
        n = Needle.from_bytes(buf, self.version)
        if n.id != needle_id:
            raise IOError(f"needle id mismatch at {offset}: {n.id:x} != {needle_id:x}")
        if cookie is not None and n.cookie != cookie:
            raise PermissionError(f"needle {needle_id:x}: cookie mismatch")
        # needle-level TTL: on a TTL volume an aged-out needle reads as
        # absent even before the whole volume is reaped
        ttl_s = self.super_block.ttl.seconds
        if ttl_s and n.append_at_ns:
            import time as _time

            if n.append_at_ns / 1e9 + ttl_s < _time.time():
                raise NeedleExpired(f"needle {needle_id} expired (ttl)")
        return n

    def content_size(self) -> int:
        with self._lock:
            self._dat.seek(0, os.SEEK_END)
            return self._dat.tell()

    def needle_count(self) -> int:
        return len(self.nm)

    def needle_entries_page(self, start: int, limit: int) -> tuple[list[list[int]], bool]:
        """One page of live (id, size) pairs ascending from `start`, under
        the volume lock (writers mutate the map under the same lock — an
        unlocked visit can fault mid-iteration). Returns (page, truncated)."""
        with self._lock:
            out: list[list[int]] = []
            for key, _off, size in self.nm.ascending_visit(start):
                out.append([key, size])
                if len(out) >= limit:
                    break
            return out, len(out) >= limit

    def needle_append_ts(self, needle_ids: list[int]) -> dict[int, int]:
        """append_at_ns for each requested LIVE needle, 0 when the volume
        predates v3 timestamps, absent when the needle isn't in the map.
        One 8-byte read per needle — the ts sits at a fixed position
        (header + body + checksum) — so volume.fsck's cutoff filter never
        pays a full-payload ReadNeedle per orphan."""
        out: dict[int, int] = {}
        with self._lock:
            for nid in needle_ids:
                loc = self.nm.get(nid)
                if loc is None:
                    continue
                if self.version < 3:
                    out[nid] = 0
                    continue
                stored, size = loc
                pos = (
                    types.offset_to_actual(stored)
                    + types.NEEDLE_HEADER_SIZE
                    + max(size, 0)
                    + types.NEEDLE_CHECKSUM_SIZE
                )
                self._dat.seek(pos)
                raw = self._dat.read(8)
                out[nid] = int.from_bytes(raw, "big") if len(raw) == 8 else 0
        return out

    def tombstone_history(self, start: int = 0, limit: int = 0) -> tuple[list[list[int]], bool]:
        """Ids (ascending from `start`) with a tombstone anywhere in the
        .idx history, each paired with whether the FINAL state is deleted
        (1) or the needle was re-written after the delete (0). The delete
        history volume.check.disk needs: final tombstones let it propagate
        deletes instead of resurrecting from a replica that missed the
        delete; rewrite evidence lets it tell 'missed the delete' from
        'wrote after the delete' and keep the newer write. O(idx) walk;
        ops-command cadence only. Returns (page, truncated); limit<=0 means
        unbounded."""
        with self._lock:
            self._idx.flush()
            with open(self.idx_path, "rb") as f:
                buf = f.read()
        ever: set[int] = set()
        final: dict[int, bool] = {}
        for key, off, size in idx_mod.walk_index_buffer(buf):
            dead = off == 0 or types.is_deleted(size)
            if dead:
                ever.add(key)
            final[key] = dead
        rows = [[k, 1 if final[k] else 0] for k in sorted(ever) if k >= start]
        if limit > 0 and len(rows) > limit:
            return rows[:limit], True
        return rows, False

    def is_expired(self) -> bool:
        """True when this is a TTL volume whose NEWEST write (.dat mtime)
        has aged out. Callers deciding to DELETE must re-check under
        self._lock: a write that was acked meanwhile refreshed the mtime."""
        ttl_s = self.super_block.ttl.seconds
        if not ttl_s or self.tiered:
            return False
        import time as _time

        lm = self.last_modified()
        return bool(lm) and lm + ttl_s < _time.time()

    def last_modified(self) -> int:
        """Unix seconds of the last append (.dat mtime; 0 when unreadable)
        — the one definition shared by TTL expiry, heartbeat volume info,
        and ec.encode's -quietFor filter."""
        try:
            return int(os.path.getmtime(self.dat_path))
        except OSError:
            return 0

    def garbage_ratio(self) -> float:
        """Fraction of the .dat body that is dead (deleted/overwritten
        records + tombstones) — the auto-vacuum trigger signal."""
        with self._lock:
            return self._garbage_from(self.content_size())

    def _garbage_from(self, size: int) -> float:
        from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE

        body = size - SUPER_BLOCK_SIZE
        if body <= 0:
            return 0.0
        return max(0.0, (body - self._live_bytes) / body)

    def stats_snapshot(self) -> tuple[int, int, float]:
        """(size, needle_count, garbage_ratio) WITHOUT the volume lock —
        the heartbeat thread must keep reporting while a compaction holds
        the lock for minutes, or the master reaps a healthy node mid-
        compact. Values are GIL-consistent-enough; staleness is fine."""
        try:
            size = os.path.getsize(self.dat_path)
        except OSError:
            if not self.tiered:
                return 0, len(self.nm), 0.0
            # remote .dat: take the locked path — tiered volumes cannot
            # compact, so nothing ever holds the lock for minutes
            size = self.content_size()
        return size, len(self.nm), self._garbage_from(size)

    # -- maintenance ---------------------------------------------------------

    def check_integrity(self) -> int:
        """Scan the .dat tail records parse + crc; returns live needle count
        (volume_checking.go analog — here a full sweep of indexed needles)."""
        ok = 0
        for key, stored, size in self.nm.ascending_visit():
            try:
                self.read_needle(key)  # raises on parse/crc error
            except NeedleExpired:
                continue  # aged-out TTL needle: absent, not corrupt
            ok += 1
        return ok

    def compact(self) -> tuple[int, int]:
        """Vacuum: rewrite live needles into fresh .dat/.idx
        (volume_vacuum.go analog). Returns (bytes_before, bytes_after)."""
        from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE

        with self._lock:
            if self.tiered:
                raise IOError(
                    f"volume {self.id} is tiered to remote storage — "
                    "fetch it back (volume.tier.fetch) before compacting"
                )
            before = self.content_size()
            idx_entries = (
                os.path.getsize(self.idx_path)
                if os.path.exists(self.idx_path)
                else 0
            )
            if (
                len(self.nm) == 0
                and before > SUPER_BLOCK_SIZE
                and idx_entries < types.NEEDLE_MAP_ENTRY_SIZE
            ):
                # An empty map with a non-empty .dat AND no index entries at
                # all means the .idx was lost/never loaded — compacting would
                # destroy every needle. (A legitimately fully-deleted volume
                # also has an empty map, but its .idx holds tombstone
                # entries, so it passes and compaction reclaims the space.)
                raise IOError(
                    f"volume {self.id}: index is empty but .dat holds "
                    f"{before} bytes — refusing to compact (run fix)"
                )
            cpd_dat, cpd_idx = self.dat_path + ".cpd", self.idx_path + ".cpx"
            new_sb = SuperBlock(
                version=self.super_block.version,
                replica_placement=self.super_block.replica_placement,
                ttl=self.super_block.ttl,
                compact_revision=self.super_block.compact_revision + 1,
            )
            with open(cpd_dat, "wb") as dat, open(cpd_idx, "wb") as idxf:
                dat.write(new_sb.to_bytes())
                for key, stored, size in self.nm.ascending_visit():
                    try:
                        n = self.read_needle(key)
                    except NeedleExpired:
                        # aged-out TTL needle: dropping it IS the reclaim
                        self._live_bytes -= types.actual_size(size, self.version)
                        continue
                    offset = dat.tell()
                    rec = n.to_bytes(self.version)
                    dat.write(rec)
                    idxf.write(
                        types.pack_index_entry(key, types.offset_to_bytes(offset), n.size)
                    )
                dat.flush()
                os.fsync(dat.fileno())
                idxf.flush()
                os.fsync(idxf.fileno())
            self._dat.close()
            self._idx.close()
            os.replace(cpd_dat, self.dat_path)
            os.replace(cpd_idx, self.idx_path)
            # weedlint: ignore[open-no-ctx] compaction swap reopens the mount-lifetime handles
            self._dat = open(self.dat_path, "r+b")
            self._idx = open(self.idx_path, "ab")  # weedlint: ignore[open-no-ctx] see above
            self.super_block = new_sb
            if self.needle_map_kind != "memory":
                from seaweedfs_tpu.storage.needle_map import new_needle_map

                # sidecar watermark refers to the pre-compaction .idx; wipe
                # it so the map rebuilds from the fresh index
                for ext in (".sdx", ".sdx.meta"):
                    if os.path.exists(self.base_path + ext):
                        os.unlink(self.base_path + ext)
                self.nm = new_needle_map(self.needle_map_kind, self.base_path)
            else:
                self.nm = CompactMap()
                self.nm.load_from_idx(self.idx_path)
            return before, self.content_size()

    def incremental_backup_since(self, offset: int) -> bytes:
        """Bytes appended since `offset` (volume_backup.go analog)."""
        with self._lock:
            self._dat.seek(offset)
            return self._dat.read()
