"""File-id formatting/parsing — mirror of weed/storage/needle volume_id/
file_id helpers [VERIFY: mount empty].

A file id is "<volumeId>,<keyHex><cookieHex8>", e.g. "3,01637037d6...": the
final 8 hex chars are the cookie, the rest the needle id.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{self.key:x}{self.cookie:08x}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        fid = fid.strip()
        if "," not in fid:
            raise ValueError(f"bad file id {fid!r}: missing comma")
        vid_s, rest = fid.split(",", 1)
        # tolerate the _altKey suffix some clients append
        rest = rest.split("_", 1)[0]
        if len(rest) <= 8:
            raise ValueError(f"bad file id {fid!r}: key_cookie too short")
        return cls(
            volume_id=int(vid_s),
            key=int(rest[:-8], 16),
            cookie=int(rest[-8:], 16),
        )
