"""Needle record codec — mirror of weed/storage/needle (needle.go,
needle_read_write.go) [VERIFY: mount empty; upstream v2/v3 layouts,
SURVEY.md §2.1].

On-disk record (version 2; version 3 appends a timestamp):

  header : Cookie(4 BE) | NeedleId(8 BE) | Size(4 BE)
  body   : when data present —
           DataSize(4 BE) | Data | Flags(1)
           [NameSize(1) | Name]           if FLAG_HAS_NAME
           [MimeSize(1) | Mime]           if FLAG_HAS_MIME
           [LastModified(5 BE)]           if FLAG_HAS_LAST_MODIFIED
           [Ttl(2)]                       if FLAG_HAS_TTL
           [PairsSize(2 BE) | Pairs]      if FLAG_HAS_PAIRS
  tail   : Checksum(4 BE, CRC32C of Data) | [AppendAtNs(8 BE), v3 only]
           | zero padding to an 8-byte record boundary

`Size` (the .idx/.ecx size field) counts the body bytes only.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

from seaweedfs_tpu.storage import types
from seaweedfs_tpu.utils.native import crc32c

VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2


class CrcError(ValueError):
    pass


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    data: bytes = b""
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    last_modified: int = 0  # unix seconds (40-bit on disk)
    ttl: bytes = b""  # 2 raw bytes (count, unit); empty = no ttl
    is_compressed: bool = False
    is_chunk_manifest: bool = False
    append_at_ns: int = 0
    checksum: int = 0
    size: int = field(default=0, init=False)  # body size, set on encode/parse

    @property
    def flags(self) -> int:
        f = 0
        if self.is_compressed:
            f |= FLAG_IS_COMPRESSED
        if self.name:
            f |= FLAG_HAS_NAME
        if self.mime:
            f |= FLAG_HAS_MIME
        if self.last_modified:
            f |= FLAG_HAS_LAST_MODIFIED
        if self.ttl and self.ttl != b"\x00\x00":
            f |= FLAG_HAS_TTL
        if self.pairs:
            f |= FLAG_HAS_PAIRS
        if self.is_chunk_manifest:
            f |= FLAG_IS_CHUNK_MANIFEST
        return f

    # -- encode --------------------------------------------------------------

    def to_bytes(self, version: int = CURRENT_VERSION, tombstone: bool = False) -> bytes:
        """Encode the record. Live needles always carry a body (DataSize +
        flags at minimum, so size >= 5 even for empty data); a tombstone
        (delete marker appended by Volume.delete_needle) has size == 0 —
        that's what makes deletes distinguishable from empty writes when
        rebuilding an index by .dat scan."""
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported needle version {version}")
        if len(self.name) > 255 or len(self.mime) > 255:
            raise ValueError("name/mime limited to 255 bytes")
        body = bytearray()
        if not tombstone:
            body += struct.pack(">I", len(self.data))
            body += self.data
            body.append(self.flags)
            if self.name:
                body.append(len(self.name))
                body += self.name
            if self.mime:
                body.append(len(self.mime))
                body += self.mime
            if self.last_modified:
                body += self.last_modified.to_bytes(LAST_MODIFIED_BYTES, "big")
            if self.ttl and self.ttl != b"\x00\x00":
                body += self.ttl[:TTL_BYTES].ljust(TTL_BYTES, b"\x00")
            if self.pairs:
                body += struct.pack(">H", len(self.pairs))
                body += self.pairs
        self.size = len(body)
        self.checksum = crc32c(self.data)
        out = bytearray()
        out += struct.pack(">IQi", self.cookie, self.id, self.size)
        out += body
        out += struct.pack(">I", self.checksum)
        if version == VERSION3:
            if not self.append_at_ns:
                self.append_at_ns = time.time_ns()
            out += struct.pack(">Q", self.append_at_ns)
        out += b"\x00" * types.padding_length(self.size, version)
        return bytes(out)

    # -- decode --------------------------------------------------------------

    @classmethod
    def from_bytes(cls, buf: bytes, version: int = CURRENT_VERSION, verify: bool = True) -> "Needle":
        if len(buf) < types.NEEDLE_HEADER_SIZE:
            raise ValueError("buffer shorter than needle header")
        cookie, nid, size = struct.unpack_from(">IQi", buf, 0)
        n = cls(cookie=cookie, id=nid)
        n.size = size
        pos = types.NEEDLE_HEADER_SIZE
        end_of_body = pos + max(size, 0)
        if len(buf) < end_of_body + types.NEEDLE_CHECKSUM_SIZE:
            raise ValueError(
                f"buffer too short: body says {size}, have {len(buf) - pos}"
            )
        def need(k: int) -> None:
            if pos + k > end_of_body:
                raise ValueError(
                    f"needle {nid:x}: corrupt body — field of {k} bytes at "
                    f"{pos} exceeds body end {end_of_body}"
                )

        if size > 0:
            need(4)
            (data_size,) = struct.unpack_from(">I", buf, pos)
            pos += 4
            need(data_size + 1)
            n.data = bytes(buf[pos : pos + data_size])
            pos += data_size
            flags = buf[pos]
            pos += 1
            n.is_compressed = bool(flags & FLAG_IS_COMPRESSED)
            n.is_chunk_manifest = bool(flags & FLAG_IS_CHUNK_MANIFEST)
            if flags & FLAG_HAS_NAME:
                need(1)
                ln = buf[pos]
                pos += 1
                need(ln)
                n.name = bytes(buf[pos : pos + ln])
                pos += ln
            if flags & FLAG_HAS_MIME:
                need(1)
                lm = buf[pos]
                pos += 1
                need(lm)
                n.mime = bytes(buf[pos : pos + lm])
                pos += lm
            if flags & FLAG_HAS_LAST_MODIFIED:
                need(LAST_MODIFIED_BYTES)
                n.last_modified = int.from_bytes(buf[pos : pos + LAST_MODIFIED_BYTES], "big")
                pos += LAST_MODIFIED_BYTES
            if flags & FLAG_HAS_TTL:
                need(TTL_BYTES)
                n.ttl = bytes(buf[pos : pos + TTL_BYTES])
                pos += TTL_BYTES
            if flags & FLAG_HAS_PAIRS:
                need(2)
                (lp,) = struct.unpack_from(">H", buf, pos)
                pos += 2
                need(lp)
                n.pairs = bytes(buf[pos : pos + lp])
                pos += lp
            if pos != end_of_body:
                raise ValueError(f"body parse mismatch: at {pos}, size says {end_of_body}")
        (n.checksum,) = struct.unpack_from(">I", buf, end_of_body)
        if version == VERSION3 and len(buf) >= end_of_body + 4 + 8:
            (n.append_at_ns,) = struct.unpack_from(">Q", buf, end_of_body + 4)
        if verify and crc32c(n.data) != n.checksum:
            raise CrcError(f"needle {nid:x}: crc mismatch")
        return n

    def actual_size(self, version: int = CURRENT_VERSION) -> int:
        return types.actual_size(self.size, version)
