"""Storage backends — mirror of weed/storage/backend/ (BackendStorageFile
over local disk / mmap / S3 tiered volumes) [VERIFY: mount empty;
SURVEY.md §2.1 "Storage backends" row].

All backends expose the small file-like surface Volume uses (seek/read/
tell/flush/close + write for local ones), so a tiered volume swaps its
.dat handle for a RemoteDatFile without touching the needle read path.
"""

from __future__ import annotations

import mmap
import os
import threading
from typing import Optional

from seaweedfs_tpu.remote_storage import RemoteStorageClient


class DiskFile:
    """Plain local file (the default backend) — thin alias of the stdlib
    file object, named to mark the seam."""

    def __init__(self, path: str, writable: bool = True):
        exists = os.path.exists(path)
        mode = ("r+b" if exists else "w+b") if writable else "rb"
        # weedlint: ignore[open-no-ctx] backend-lifetime handle, closed via the seam's close()
        self.f = open(path, mode)
        self.path = path

    def __getattr__(self, name):
        return getattr(self.f, name)


class MemoryMappedFile:
    """Read-only mmap backend (weed/storage/backend/memory_map): serves
    hot read-only volumes straight from the page cache."""

    def __init__(self, path: str):
        self.path = path
        # weedlint: ignore[open-no-ctx] pinned open while the mmap lives, closed in close()
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        self._pos = 0
        self._lock = threading.Lock()

    def seek(self, pos: int, whence: int = os.SEEK_SET) -> int:
        with self._lock:
            if whence == os.SEEK_SET:
                self._pos = pos
            elif whence == os.SEEK_CUR:
                self._pos += pos
            else:
                self._pos = len(self._mm) + pos
            return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, size: int = -1) -> bytes:
        with self._lock:
            if size < 0:
                size = len(self._mm) - self._pos
            out = self._mm[self._pos : self._pos + size]
            self._pos += len(out)
            return out

    def write(self, data: bytes):
        raise IOError("memory-mapped backend is read-only")

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._mm.close()
        self._f.close()


class RemoteDatFile:
    """Read-only file view over a remote-storage object (the tiered
    volume backend, weed/storage/backend/s3_backend analog)."""

    def __init__(self, client: RemoteStorageClient, key: str, size: Optional[int] = None):
        self.client = client
        self.key = key
        self._size = client.size(key) if size is None else size
        self._pos = 0
        self._lock = threading.Lock()

    def seek(self, pos: int, whence: int = os.SEEK_SET) -> int:
        with self._lock:
            if whence == os.SEEK_SET:
                self._pos = pos
            elif whence == os.SEEK_CUR:
                self._pos += pos
            else:
                self._pos = self._size + pos
            return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, size: int = -1) -> bytes:
        with self._lock:
            if size < 0:
                size = self._size - self._pos
            size = max(0, min(size, self._size - self._pos))
            if size == 0:
                return b""
            data = self.client.read_range(self.key, self._pos, size)
            self._pos += len(data)
            return data

    def write(self, data: bytes):
        raise IOError("tiered volume is read-only")

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass
