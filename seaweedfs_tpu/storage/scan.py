"""Sequential .dat walker — the engine behind `fix` (rebuild .idx from data)
and `export` (dump needles). Mirror of weed/storage/volume_read_all.go +
weed/command/fix.go's ScanVolumeFile usage [VERIFY: mount empty; SURVEY.md
§2.1 / §5 checkpoint-resume: ".idx rebuildable by scan (weed fix)"].

A scan can stop before EOF for two very different reasons that look the same
locally (a record whose claimed size overruns the file): a crash mid-append
truncated the final record (normal, recoverable — drop the partial tail), or
a corrupted size field mid-file (dangerous — everything after it is intact
but unreachable, and acting on a partial scan would destroy it). We tell
them apart by probing past the stop point for any parseable, CRC-valid
record: corruption leaves valid needles behind it, a true tail does not.
"""

from __future__ import annotations

import os
from typing import BinaryIO, Iterator

from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle import CrcError, Needle
from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE, SuperBlock

class CorruptVolume(IOError):
    """A record mid-file is structurally corrupt but valid data follows it."""


def _valid_record_after(
    f: BinaryIO, start: int, file_size: int, version: int
) -> int:
    """Probe 8-aligned offsets in (start, EOF) for a fully parseable,
    CRC-valid needle record. Returns its offset, or -1.

    Scans to EOF (not a fixed window) so a corrupted size field on a huge
    record can't hide intact data beyond an arbitrary horizon; the scan is
    mmap-backed and rejects most offsets on a 16-byte plausibility check,
    and it only runs on the rare corruption/truncation path."""
    import mmap

    probe = (start + types.NEEDLE_PADDING_SIZE - 1) // types.NEEDLE_PADDING_SIZE
    probe *= types.NEEDLE_PADDING_SIZE
    if probe + types.NEEDLE_HEADER_SIZE > file_size:
        return -1
    with mmap.mmap(f.fileno(), length=file_size, access=mmap.ACCESS_READ) as mm:
        while probe + types.NEEDLE_HEADER_SIZE <= file_size:
            size = int.from_bytes(mm[probe + 12 : probe + 16], "big", signed=True)
            if 0 < size <= file_size - probe:
                whole = types.actual_size(size, version)
                if probe + whole <= file_size:
                    try:
                        Needle.from_bytes(mm[probe : probe + whole], version, verify=True)
                        return probe
                    except (ValueError, CrcError):
                        pass
            elif size == 0:
                # a delete marker is a legitimate survivor (deletes are often
                # the last records). Its shape: cookie==0, nonzero id,
                # checksum==crc32c(b"")==0 — enough constrained bytes to make
                # a false positive on needle-data noise unlikely.
                whole = types.actual_size(0, version)
                cookie = int.from_bytes(mm[probe : probe + 4], "big")
                nid = int.from_bytes(mm[probe + 4 : probe + 12], "big")
                checksum = int.from_bytes(mm[probe + 16 : probe + 20], "big")
                if (
                    probe + whole <= file_size
                    and cookie == 0
                    and nid != 0
                    and checksum == 0
                ):
                    return probe
            probe += types.NEEDLE_PADDING_SIZE
    return -1


def scan_volume_file(
    dat_path: str, verify_crc: bool = True
) -> Iterator[tuple[int, "Needle"]]:
    """Yield (byte_offset, needle) for every record in a volume .dat, in
    append order. Delete markers (size == 0 records appended by
    delete_needle) surface as needles with size == 0.

    A crash-truncated final record is dropped silently (weed fix behavior);
    corruption mid-file raises CorruptVolume instead of silently losing the
    intact records that follow it."""
    file_size = os.path.getsize(dat_path)
    with open(dat_path, "rb") as f:
        sb = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))
        version = sb.version
        offset = SUPER_BLOCK_SIZE
        while offset + types.NEEDLE_HEADER_SIZE <= file_size:
            f.seek(offset)
            header = f.read(types.NEEDLE_HEADER_SIZE)
            size = int.from_bytes(header[12:16], "big", signed=True)
            if size < 0:
                # the volume only ever writes size >= 0 (deletes are size 0),
                # so a negative size in .dat is always corruption — never
                # yield it as a record (a flipped sign bit would otherwise
                # silently tombstone a live needle on rebuild)
                survivor = _valid_record_after(f, offset + 1, file_size, version)
                if survivor >= 0:
                    raise CorruptVolume(
                        f"{dat_path}: negative size {size} at {offset} with a "
                        f"valid record at {survivor} — corrupt size field"
                    )
                break
            whole = types.actual_size(size, version)
            body = f.read(whole - types.NEEDLE_HEADER_SIZE)
            rec = header + body
            if len(rec) < whole - types.padding_length(size, version):
                survivor = _valid_record_after(f, offset + 1, file_size, version)
                if survivor >= 0:
                    raise CorruptVolume(
                        f"{dat_path}: record at {offset} claims {whole} bytes "
                        f"past EOF but a valid record exists at {survivor} — "
                        f"corrupt size field, refusing partial scan"
                    )
                break  # true truncated tail (crash mid-append)
            try:
                n = Needle.from_bytes(rec, version, verify=verify_crc and size > 0)
            except (ValueError, CrcError) as e:
                survivor = _valid_record_after(f, offset + 1, file_size, version)
                if survivor >= 0:
                    raise CorruptVolume(
                        f"{dat_path}: corrupt record at {offset} ({e}) with a "
                        f"valid record at {survivor} — refusing partial scan"
                    ) from e
                break  # garbage at the tail only: treat like truncation
            yield offset, n
            offset += whole


def rebuild_idx(base_path: str, verify_crc: bool = True) -> int:
    """<base>.dat -> <base>.idx by full scan (weed fix semantics): records
    with a body get (offset,size) entries; size==0 delete markers get
    TOMBSTONE entries, so index replay preserves delete-after-write
    ordering. Returns total record count. On failure the partial .idx.tmp
    is removed and the existing .idx is left untouched."""
    dat_path = base_path + ".dat"
    tmp = base_path + ".idx.tmp"
    count = 0
    try:
        with open(tmp, "wb") as out:
            for offset, n in scan_volume_file(dat_path, verify_crc=verify_crc):
                if n.size > 0:
                    out.write(
                        types.pack_index_entry(
                            n.id, types.offset_to_bytes(offset), n.size
                        )
                    )
                else:
                    out.write(
                        types.pack_index_entry(n.id, 0, types.TOMBSTONE_FILE_SIZE)
                    )
                count += 1
            out.flush()
            os.fsync(out.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, base_path + ".idx")
    return count
