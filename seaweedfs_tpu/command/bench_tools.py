"""benchmark / upload / download commands — mirrors of
weed/command/benchmark.go, upload.go, download.go [VERIFY: mount empty;
SURVEY.md §2.1 "Benchmarks" + "CLI entry" rows].

`benchmark` is the built-in load generator: C concurrent writers push N
files of S bytes through assign+POST, then readers fetch them back;
prints throughput and latency percentiles like the reference's
"Unscientific benchmark" output.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from seaweedfs_tpu.command import Command, register


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def _bench_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-n", type=int, default=1000, help="number of files")
    p.add_argument("-size", type=int, default=1024, help="file size in bytes")
    p.add_argument("-c", type=int, default=16, help="concurrent workers")
    p.add_argument("-collection", default="")
    p.add_argument("-write", action="store_true", default=True)
    p.add_argument("-read", action="store_true", default=True)


def _bench_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.client import MasterClient

    client = MasterClient(args.master)
    payload = os.urandom(args.size)
    fids: list[str] = []
    fid_lock = threading.Lock()
    lat_w: list[float] = []
    lat_r: list[float] = []
    errors = [0]

    def writer(count: int) -> None:
        for _ in range(count):
            t0 = time.monotonic()
            try:
                res = client.submit(payload, collection=args.collection)
                with fid_lock:
                    fids.append(res.fid)
                    lat_w.append(time.monotonic() - t0)
            except Exception:  # noqa: BLE001
                with fid_lock:
                    errors[0] += 1

    def run_phase(fn, total: int) -> float:
        per = [total // args.c] * args.c
        for i in range(total % args.c):
            per[i] += 1
        threads = [threading.Thread(target=fn, args=(n,)) for n in per if n]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.monotonic() - t0

    print(f"benchmark: {args.n} files x {args.size} B, {args.c} workers, master {args.master}")
    wall_w = run_phase(writer, args.n)
    lat_w.sort()
    mb = len(fids) * args.size / 1e6
    print(
        f"write: {len(fids)} ok, {errors[0]} err in {wall_w:.2f}s "
        f"= {len(fids) / max(wall_w, 1e-9):.0f} req/s, {mb / max(wall_w, 1e-9):.1f} MB/s"
    )
    print(
        f"write latency ms: p50 {1e3 * _percentile(lat_w, 0.50):.1f} "
        f"p90 {1e3 * _percentile(lat_w, 0.90):.1f} p99 {1e3 * _percentile(lat_w, 0.99):.1f}"
    )

    if fids:
        idx = [0]

        def reader(count: int) -> None:
            for _ in range(count):
                with fid_lock:
                    if idx[0] >= len(fids):
                        return
                    fid = fids[idx[0] % len(fids)]
                    idx[0] += 1
                t0 = time.monotonic()
                try:
                    data = client.read(fid)
                    assert len(data) == args.size
                    with fid_lock:
                        lat_r.append(time.monotonic() - t0)
                except Exception:  # noqa: BLE001
                    with fid_lock:
                        errors[0] += 1

        wall_r = run_phase(reader, len(fids))
        lat_r.sort()
        mb = len(lat_r) * args.size / 1e6
        print(
            f"read:  {len(lat_r)} ok in {wall_r:.2f}s "
            f"= {len(lat_r) / max(wall_r, 1e-9):.0f} req/s, {mb / max(wall_r, 1e-9):.1f} MB/s"
        )
        print(
            f"read latency ms:  p50 {1e3 * _percentile(lat_r, 0.50):.1f} "
            f"p90 {1e3 * _percentile(lat_r, 0.90):.1f} p99 {1e3 * _percentile(lat_r, 0.99):.1f}"
        )
    client.close()
    return 0


register(Command("benchmark", "write/read load generator against a cluster", _bench_conf, _bench_run))


def _upload_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    p.add_argument("files", nargs="+", help="local files to upload")


def _upload_run(args: argparse.Namespace) -> int:
    import json as _json
    import mimetypes

    from seaweedfs_tpu.cluster.client import MasterClient

    client = MasterClient(args.master)
    out = []
    for path in args.files:
        with open(path, "rb") as f:
            data = f.read()
        mime = mimetypes.guess_type(path)[0] or "application/octet-stream"
        res = client.submit(
            data, collection=args.collection, replication=args.replication, mime=mime
        )
        out.append({"fileName": os.path.basename(path), "fid": res.fid, "size": res.size})
    print(_json.dumps(out, indent=2))
    client.close()
    return 0


register(Command("upload", "upload local files, printing their fids", _upload_conf, _upload_run))


def _download_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-dir", default=".", help="output directory")
    p.add_argument("fids", nargs="+", help="file ids to download")


def _download_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.client import MasterClient

    client = MasterClient(args.master)
    os.makedirs(args.dir, exist_ok=True)
    for fid in args.fids:
        data = client.read(fid)
        out = os.path.join(args.dir, fid.replace(",", "_"))
        with open(out, "wb") as f:
            f.write(data)
        print(f"{fid} -> {out} ({len(data)} bytes)")
    client.close()
    return 0


register(Command("download", "download files by fid", _download_conf, _download_run))
