"""Local-file commands: the single-chip `tpuec` slice of SURVEY.md §7.1.3 —
encode | rebuild | decode | verify on volume files — plus the maintenance
commands `fix`, `compact`, `export` (mirrors of weed/command/fix.go,
compact.go, export.go [VERIFY: mount empty]).

All of these operate on a volume *base path* (`/dir/[collection_]<vid>`,
no extension), like the reference's `-dir` + `-volumeId` flags resolve to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from contextlib import ExitStack

from seaweedfs_tpu.command import Command, register
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec.constants import (
    DATA_SHARDS_COUNT,
    ERASURE_CODING_LARGE_BLOCK_SIZE,
    ERASURE_CODING_SMALL_BLOCK_SIZE,
    TOTAL_SHARDS_COUNT,
)
from seaweedfs_tpu.ops.rs_codec import new_encoder
from seaweedfs_tpu.storage import scan as scan_mod
from seaweedfs_tpu.storage import types


def _add_base(p: argparse.ArgumentParser) -> None:
    p.add_argument("base", help="volume base path: /dir/[collection_]<vid> (no extension)")


def _add_geometry(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--large-block",
        type=int,
        default=ERASURE_CODING_LARGE_BLOCK_SIZE,
        help="large stripe block size in bytes (default 1 GiB)",
    )
    p.add_argument(
        "--small-block",
        type=int,
        default=ERASURE_CODING_SMALL_BLOCK_SIZE,
        help="small stripe block size in bytes (default 1 MiB)",
    )


def _run_encode(args: argparse.Namespace) -> int:
    if not os.path.exists(args.base + ".dat"):
        print(f"no such file: {args.base}.dat", file=sys.stderr)
        return 1
    stripe.write_ec_files(
        args.base, large_block_size=args.large_block, small_block_size=args.small_block
    )
    if os.path.exists(args.base + ".idx"):
        stripe.write_sorted_file_from_idx(args.base)
    else:
        print(f"note: {args.base}.idx missing — wrote shards only, no .ecx", file=sys.stderr)
    print(
        json.dumps(
            {
                "encoded": args.base,
                "dat_bytes": os.path.getsize(args.base + ".dat"),
                "shard_bytes": os.path.getsize(stripe.shard_file_name(args.base, 0)),
                "shards": TOTAL_SHARDS_COUNT,
            }
        )
    )
    return 0


def _run_rebuild(args: argparse.Namespace) -> int:
    rebuilt = stripe.rebuild_ec_files(args.base)
    print(json.dumps({"rebuilt_shards": rebuilt}))
    return 0


def _run_decode(args: argparse.Namespace) -> int:
    present = stripe.find_local_shards(args.base)
    missing_data = [s for s in range(DATA_SHARDS_COUNT) if s not in present]
    if missing_data:
        if len(present) < DATA_SHARDS_COUNT:
            print(
                f"cannot decode: shards {missing_data} missing and only "
                f"{len(present)} survivors",
                file=sys.stderr,
            )
            return 1
        stripe.rebuild_ec_files(args.base)
    stripe.write_dat_file(args.base, dat_file_size=args.dat_size)
    if os.path.exists(args.base + ".ecx"):
        stripe.write_idx_file_from_ec_index(args.base)
    print(json.dumps({"decoded": args.base + ".dat", "bytes": os.path.getsize(args.base + ".dat")}))
    return 0


def _run_verify(args: argparse.Namespace) -> int:
    """Re-encode data shards chunkwise and compare against stored parity."""
    import numpy as np

    present = stripe.find_local_shards(args.base)
    if len(present) != TOTAL_SHARDS_COUNT:
        print(
            f"verify needs all {TOTAL_SHARDS_COUNT} shards, found {sorted(present)}",
            file=sys.stderr,
        )
        return 1
    enc = new_encoder()
    shard_size = os.path.getsize(stripe.shard_file_name(args.base, 0))
    chunk = 4 * 1024 * 1024
    # ExitStack, not try/finally around a list comprehension: an open()
    # failing mid-comprehension would leak every handle opened before it
    with ExitStack() as stack:
        files = [
            stack.enter_context(open(stripe.shard_file_name(args.base, s), "rb"))
            for s in range(TOTAL_SHARDS_COUNT)
        ]
        for off in range(0, shard_size, chunk):
            n = min(chunk, shard_size - off)
            shards = [stripe.read_padded(f, off, n) for f in files]
            if not enc.verify(shards):
                print(json.dumps({"verified": False, "bad_chunk_offset": off}))
                return 1
    print(json.dumps({"verified": True, "shard_bytes": shard_size}))
    return 0


def _run_fix(args: argparse.Namespace) -> int:
    count = scan_mod.rebuild_idx(args.base)
    print(json.dumps({"fixed": args.base + ".idx", "records": count}))
    return 0


def _run_compact(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.storage.store import parse_base_name
    from seaweedfs_tpu.storage.volume import Volume

    d, base = os.path.split(args.base)
    parsed = parse_base_name(base)
    if parsed is None:
        print(f"cannot parse volume id from {base!r}", file=sys.stderr)
        return 1
    collection, vid = parsed
    with Volume(d or ".", vid, collection) as v:
        before, after = v.compact()
    print(json.dumps({"compacted": args.base, "bytes_before": before, "bytes_after": after}))
    return 0


def _run_export(args: argparse.Namespace) -> int:
    """Dump live needles as JSON lines (weed export analog). Two passes so
    memory stays O(index): collect live (offset,size) first, then re-read
    one needle at a time while emitting."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE, SuperBlock

    dat_path = args.base + ".dat"
    live: dict[int, tuple[int, int]] = {}
    for offset, n in scan_mod.scan_volume_file(dat_path, verify_crc=False):
        if n.size > 0:
            live[n.id] = (offset, n.size)
        else:
            live.pop(n.id, None)
    with open(dat_path, "rb") as f:
        version = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE)).version
        for nid, (offset, size) in sorted(live.items()):
            f.seek(offset)
            n = Needle.from_bytes(f.read(types.actual_size(size, version)), version)
            rec = {
                "id": f"{nid:x}",
                "cookie": f"{n.cookie:08x}",
                "offset": offset,
                "size": n.size,
                "name": n.name.decode("utf-8", "replace"),
                "mime": n.mime.decode("utf-8", "replace"),
                "data_size": len(n.data),
            }
            if args.data:
                import base64

                rec["data"] = base64.b64encode(n.data).decode()
            print(json.dumps(rec))
    return 0


def _simple(name: str, help_: str, run, extra_conf=None) -> None:
    def conf(p: argparse.ArgumentParser) -> None:
        _add_base(p)
        if extra_conf:
            extra_conf(p)

    register(Command(name, help_, conf, run))


_simple(
    "encode",
    "EC-encode a volume: <base>.dat [+.idx] -> .ec00..13 + .ecx (TPU matmul path)",
    _run_encode,
    _add_geometry,
)
_simple("rebuild", "reconstruct missing .ecNN shards from >=10 survivors", _run_rebuild)
_simple(
    "decode",
    "shards -> <base>.dat (+.idx from .ecx/.ecj)",
    _run_decode,
    lambda p: p.add_argument("--dat-size", type=int, default=None),
)
_simple("verify", "re-encode data shards and compare stored parity", _run_verify)
_simple("fix", "rebuild <base>.idx by scanning <base>.dat", _run_fix)
_simple("compact", "vacuum a volume: rewrite live needles, drop deleted", _run_compact)
_simple(
    "export",
    "dump live needles as JSON lines",
    _run_export,
    lambda p: p.add_argument("--data", action="store_true", help="include base64 data"),
)
