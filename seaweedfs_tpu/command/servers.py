"""Process-level commands — master / volume / server / shell / version,
mirroring weed/command/{master,volume,server,shell}.go [VERIFY: mount
empty; SURVEY.md §2.1 "CLI entry"]. `server` runs master+volume in one
process like `weed server`."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from seaweedfs_tpu.command import Command, register


def _version_conf(p: argparse.ArgumentParser) -> None:
    pass


def _version_run(args: argparse.Namespace) -> int:
    import seaweedfs_tpu

    print(f"seaweedfs_tpu {seaweedfs_tpu.__version__}")
    return 0


register(Command("version", "print version", _version_conf, _version_run))


def _wait_forever() -> None:
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:  # not main thread (tests)
            break
    stop.wait()


def _load_guard():
    """Build a security.Guard from security.toml (None = security off).
    TLS is NOT loaded here — __main__ activates it process-wide from the
    same TOML before any command runs."""
    from seaweedfs_tpu.security import Guard
    from seaweedfs_tpu.utils.config import get_nested, load_configuration

    conf = load_configuration("security")
    key = str(get_nested(conf, "jwt.signing.key", "") or "")
    read_key = str(get_nested(conf, "jwt.signing.read.key", "") or "")
    wl = list(get_nested(conf, "guard.white_list", []) or [])
    exp = int(get_nested(conf, "jwt.signing.expires_after_seconds", 10) or 10)
    if not (key or read_key or wl):
        return None
    return Guard(
        signing_key=key.encode() or None,
        read_signing_key=read_key.encode() or None,
        white_list=wl,
        expires_seconds=exp,
    )


def _maybe_metrics(port: int):
    if port:
        from seaweedfs_tpu.stats import start_metrics_server

        start_metrics_server(port)
        print(f"metrics on :{port}")


def _master_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=9333)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    p.add_argument("-defaultReplication", default="000")
    p.add_argument("-peers", default="", help="comma-separated master quorum (raft HA)")
    p.add_argument("-garbageThreshold", type=float, default=0.3,
                   help="auto-vacuum volumes whose dead fraction exceeds this")
    p.add_argument("-vacuumInterval", type=float, default=900.0,
                   help="seconds between automatic vacuum sweeps")
    p.add_argument("-raftDir", default="", help="raft term/vote persistence directory")
    p.add_argument("-httpPort", type=int, default=0,
                   help="HTTP API port (/dir/assign, /dir/lookup, ...); 0 = auto")
    p.add_argument("-metricsPort", type=int, default=0)


def _master_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.master import MasterServer

    peers = [a.strip() for a in args.peers.split(",") if a.strip()]
    m = MasterServer(
        port=args.port,
        host=args.ip,
        volume_size_limit=args.volumeSizeLimitMB * 1024 * 1024,
        default_replication=args.defaultReplication,
        guard=_load_guard(),
        peers=peers or None,
        raft_dir=args.raftDir,
        garbage_threshold=args.garbageThreshold,
        vacuum_interval=args.vacuumInterval,
        http_port=args.httpPort,
    )
    m.start()
    _maybe_metrics(args.metricsPort)
    print(f"master listening on {m.address} (http :{m.http_port})")
    _wait_forever()
    m.stop()
    return 0


register(Command("master", "run a master server", _master_conf, _master_run))


def _volume_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=8080)
    p.add_argument("-grpcPort", type=int, default=0)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-dir", action="append", default=None, help="storage directory (repeatable)")
    p.add_argument("-mserver", default="127.0.0.1:9333")
    p.add_argument("-dataCenter", default="DefaultDataCenter")
    p.add_argument("-rack", default="DefaultRack")
    p.add_argument("-max", type=int, default=8, help="max volume count")
    p.add_argument("-metricsPort", type=int, default=0)
    p.add_argument(
        "-index",
        default="memory",
        choices=["memory", "sorted_file"],
        help="needle map kind: memory rebuilds the id map in RAM each "
        "mount; sorted_file binary-searches a persistent .sdx sidecar "
        "(reference -index=memory|leveldb analog)",
    )


def _volume_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    vs = VolumeServer(
        args.dir or ["./data"],
        args.mserver,
        port=args.port,
        grpc_port=args.grpcPort,
        host=args.ip,
        data_center=args.dataCenter,
        rack=args.rack,
        max_volume_count=args.max,
        guard=_load_guard(),
        needle_map_kind=args.index,
    )
    vs.start()
    _maybe_metrics(args.metricsPort)
    print(f"volume server on http {vs.url} grpc {vs.grpc_address}")
    _wait_forever()
    vs.stop()
    return 0


register(Command("volume", "run a volume server", _volume_conf, _volume_run))


def _server_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-masterPort", type=int, default=9333)
    p.add_argument("-masterHttpPort", type=int, default=0,
                   help="master HTTP API port (/dir/assign, ...); 0 = auto")
    p.add_argument("-port", type=int, default=8080, help="volume server http port")
    p.add_argument("-dir", action="append", default=None)
    p.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    p.add_argument("-filer", action="store_true", help="also run a filer")
    p.add_argument("-filerPort", type=int, default=8888)
    p.add_argument("-s3", action="store_true", help="also run the S3 gateway (implies -filer)")
    p.add_argument("-s3Port", type=int, default=8333)
    p.add_argument("-webdav", action="store_true", help="also run WebDAV (implies -filer)")
    p.add_argument("-webdavPort", type=int, default=7333)
    p.add_argument(
        "-allowedHosts",
        default="",
        help="comma-separated advertised host:port names accepted as the "
        "signed Host header by the S3 gateway besides the bind address",
    )


def _server_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    m = MasterServer(
        port=args.masterPort,
        host=args.ip,
        volume_size_limit=args.volumeSizeLimitMB * 1024 * 1024,
        http_port=args.masterHttpPort,
    )
    m.start()
    vs = VolumeServer(
        args.dir or ["./data"], m.address, port=args.port, host=args.ip
    )
    vs.start()
    parts = [
        f"master {m.address} (http :{m.http_port})",
        f"volume http {vs.url} grpc {vs.grpc_address}",
    ]
    extras = []
    if args.filer or args.s3 or args.webdav:
        from seaweedfs_tpu.filer import FilerServer

        f = FilerServer(m.address, port=args.filerPort, host=args.ip)
        f.start()
        extras.append(f)
        parts.append(f"filer http {f.url} grpc {f.grpc_address}")
        if args.s3:
            from seaweedfs_tpu.s3api import S3ApiServer

            s3 = S3ApiServer(
                f.url,
                f.grpc_address,
                port=args.s3Port,
                host=args.ip,
                extra_hosts={h.strip() for h in args.allowedHosts.split(",") if h.strip()},
            )
            s3.start()
            extras.append(s3)
            parts.append(f"s3 {s3.url}")
        if args.webdav:
            from seaweedfs_tpu.webdav import WebDavServer

            w = WebDavServer(f.url, f.grpc_address, port=args.webdavPort, host=args.ip)
            w.start()
            extras.append(w)
            parts.append(f"webdav {w.url}")
    print("server: " + ", ".join(parts))
    _wait_forever()
    for srv in reversed(extras):
        srv.stop()
    vs.stop()
    m.stop()
    return 0


register(Command("server", "run master + volume server in one process", _server_conf, _server_run))


def _filer_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=8888)
    p.add_argument("-grpcPort", type=int, default=0)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-store", default="memory", help="memory|sqlite|log|log3 (log3 = per-bucket store separation)")
    p.add_argument("-dir", default="", help="store/meta-log directory (sqlite/log stores)")
    p.add_argument("-collection", default="")
    p.add_argument("-defaultReplicaPlacement", default="")
    p.add_argument("-maxMB", type=int, default=4, help="chunk size in MiB")
    p.add_argument("-metricsPort", type=int, default=0)


def _filer_run(args: argparse.Namespace) -> int:
    import os

    from seaweedfs_tpu.filer import FilerServer, make_store

    # share the cluster's jwt keys so chunk deletes/reads work secured
    guard = _load_guard()
    if args.store == "sqlite":
        store_path = os.path.join(args.dir, "filer.db") if args.dir else ""
    else:  # log-structured store takes its directory
        store_path = args.dir
    f = FilerServer(
        args.master,
        store=make_store(args.store, store_path),
        port=args.port,
        grpc_port=args.grpcPort,
        host=args.ip,
        chunk_size=args.maxMB * 1024 * 1024,
        log_dir=args.dir,
        collection=args.collection,
        replication=args.defaultReplicaPlacement,
        signing_key=guard.signing_key if guard else None,
        read_signing_key=guard.read_signing_key if guard else None,
    )
    f.start()
    _maybe_metrics(args.metricsPort)
    print(f"filer on http {f.url} grpc {f.grpc_address}")
    _wait_forever()
    f.stop()
    return 0


register(Command("filer", "run a filer (namespace) server", _filer_conf, _filer_run))


def _s3_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=8333)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="127.0.0.1:8888", help="filer http host:port")
    p.add_argument("-filerGrpc", default="", help="filer grpc host:port (default: ask filer)")
    p.add_argument("-config", default="", help="identities JSON file (reference -s3.config shape)")
    p.add_argument("-metricsPort", type=int, default=0)
    p.add_argument(
        "-allowedHosts",
        default="",
        help="comma-separated advertised host:port names (DNS/LB fronts) "
        "accepted as the signed Host header besides the bind address",
    )


def _s3_run(args: argparse.Namespace) -> int:
    import json as _json

    from seaweedfs_tpu.s3api import Iam, S3ApiServer

    iam = Iam()
    if args.config:
        with open(args.config, encoding="utf-8") as f:
            iam = Iam.from_config(_json.load(f))
    grpc_addr = args.filerGrpc
    if not iam.identities and grpc_addr:
        # no static config: pick up identities the IAM API persisted in
        # the filer KV store (and _auth re-reads on unknown access keys)
        from seaweedfs_tpu.filer.client import FilerClient
        from seaweedfs_tpu.s3api.auth import load_identities

        try:
            with FilerClient(grpc_addr) as fc:
                stored = load_identities(fc)
            if stored is not None:
                iam = stored
        except Exception:  # noqa: BLE001 — filer may not be up yet
            pass
    if not grpc_addr:
        # filer grpc defaults to the http port + 10000 convention is the
        # reference's; here we require it explicitly unless colocated
        raise SystemExit("-filerGrpc is required")
    s3 = S3ApiServer(
        args.filer,
        grpc_addr,
        port=args.port,
        host=args.ip,
        iam=iam,
        extra_hosts={h.strip() for h in args.allowedHosts.split(",") if h.strip()},
    )
    s3.start()
    _maybe_metrics(args.metricsPort)
    print(f"s3 gateway on {s3.url} -> filer {args.filer}")
    _wait_forever()
    s3.stop()
    return 0


register(Command("s3", "run an S3-compatible gateway against a filer", _s3_conf, _s3_run))


def _webdav_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=7333)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-filerGrpc", default="")
    p.add_argument("-root", default="/", help="filer directory to expose")


def _webdav_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.webdav import WebDavServer

    if not args.filerGrpc:
        raise SystemExit("-filerGrpc is required")
    w = WebDavServer(
        args.filer, args.filerGrpc, port=args.port, host=args.ip, root=args.root
    )
    w.start()
    print(f"webdav on {w.url} -> filer {args.filer}")
    _wait_forever()
    w.stop()
    return 0


register(Command("webdav", "run a WebDAV gateway against a filer", _webdav_conf, _webdav_run))


def _iam_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=8111)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filerGrpc", default="", help="filer grpc host:port")
    p.add_argument(
        "-bootstrapToken",
        default="",
        help="pre-shared token allowing the first admin to be minted on a "
        "fresh cluster; without it the API stays closed until identities "
        "are seeded via config or the S3 gateway",
    )
    p.add_argument(
        "-allowedHosts",
        default="",
        help="comma-separated advertised host:port names accepted as the "
        "signed Host header besides the bind address",
    )


def _iam_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.iamapi import IamApiServer

    if not args.filerGrpc:
        raise SystemExit("-filerGrpc is required")
    srv = IamApiServer(
        args.filerGrpc,
        port=args.port,
        host=args.ip,
        bootstrap_token=args.bootstrapToken or None,
        extra_hosts={h.strip() for h in args.allowedHosts.split(",") if h.strip()},
    )
    srv.start()
    print(f"iam api on {srv.url}")
    _wait_forever()
    srv.stop()
    return 0


register(Command("iam", "run an AWS-IAM-compatible identity API", _iam_conf, _iam_run))


def _mount_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-filerGrpc", default="", help="filer grpc host:port")
    p.add_argument("-dir", default="", help="mountpoint directory")


def _mount_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.mount.fuse_adapter import fuse_available, mount_and_serve

    if not args.filerGrpc or not args.dir:
        raise SystemExit("-filerGrpc and -dir are required")
    if not fuse_available():
        print(
            "kernel FUSE unavailable (no fusepy//dev/fuse); use the WFS API "
            "(seaweedfs_tpu.mount.WFS) for in-process access",
            file=sys.stderr,
        )
        return 2
    mount_and_serve(args.filerGrpc, args.dir)
    return 0


register(Command("mount", "mount the filer as a FUSE filesystem", _mount_conf, _mount_run))


def _mq_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=17777)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-filerGrpc", default="")


def _mq_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.mq import Broker

    if not args.filerGrpc:
        raise SystemExit("-filerGrpc is required")
    b = Broker(args.filer, args.filerGrpc, port=args.port, host=args.ip)
    b.start()
    print(f"mq broker on {b.address} -> filer {args.filer}")
    _wait_forever()
    b.stop()
    return 0


register(Command("mq.broker", "run a message-queue broker on the filer", _mq_conf, _mq_run))


def _shell_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-c", dest="script", default="", help="run `;`-separated commands and exit")


def _shell_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.shell import CommandEnv, repl, run_script

    with CommandEnv(args.master) as env:
        if args.script:
            run_script(env, args.script, sys.stdout)
        else:
            repl(env, sys.stdin, sys.stdout)
    return 0


register(Command("shell", "operator shell (REPL or -c script)", _shell_conf, _shell_run))


def _scaffold_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-config", default="security", help="security|master|shell|filer")


def _scaffold_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.utils.config import SCAFFOLDS, scaffold

    text = scaffold(args.config)
    if text is None:
        print(f"unknown config {args.config!r}; one of {sorted(SCAFFOLDS)}", file=sys.stderr)
        return 1
    print(text, end="")
    return 0


register(Command("scaffold", "print a commented TOML config template", _scaffold_conf, _scaffold_run))
