"""Process-level commands (currently: version). The cluster commands —
master, volume, server, shell, benchmark (SURVEY.md §2.1) — register here
as the cluster layer lands."""

from __future__ import annotations

import argparse
import sys

from seaweedfs_tpu.command import Command, register


def _version_conf(p: argparse.ArgumentParser) -> None:
    pass


def _version_run(args: argparse.Namespace) -> int:
    import seaweedfs_tpu

    print(f"seaweedfs_tpu {seaweedfs_tpu.__version__}")
    return 0


register(Command("version", "print version", _version_conf, _version_run))
