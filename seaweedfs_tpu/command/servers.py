"""Process-level commands — master / volume / server / shell / version,
mirroring weed/command/{master,volume,server,shell}.go [VERIFY: mount
empty; SURVEY.md §2.1 "CLI entry"]. `server` runs master+volume in one
process like `weed server`."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from seaweedfs_tpu.command import Command, register


def _version_conf(p: argparse.ArgumentParser) -> None:
    pass


def _version_run(args: argparse.Namespace) -> int:
    import seaweedfs_tpu

    print(f"seaweedfs_tpu {seaweedfs_tpu.__version__}")
    return 0


register(Command("version", "print version", _version_conf, _version_run))


def _wait_forever() -> None:
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:  # not main thread (tests)
            break
    stop.wait()


def _master_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=9333)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    p.add_argument("-defaultReplication", default="000")


def _master_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.master import MasterServer

    m = MasterServer(
        port=args.port,
        host=args.ip,
        volume_size_limit=args.volumeSizeLimitMB * 1024 * 1024,
        default_replication=args.defaultReplication,
    )
    m.start()
    print(f"master listening on {m.address}")
    _wait_forever()
    m.stop()
    return 0


register(Command("master", "run a master server", _master_conf, _master_run))


def _volume_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-port", type=int, default=8080)
    p.add_argument("-grpcPort", type=int, default=0)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-dir", action="append", default=None, help="storage directory (repeatable)")
    p.add_argument("-mserver", default="127.0.0.1:9333")
    p.add_argument("-dataCenter", default="DefaultDataCenter")
    p.add_argument("-rack", default="DefaultRack")
    p.add_argument("-max", type=int, default=8, help="max volume count")


def _volume_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    vs = VolumeServer(
        args.dir or ["./data"],
        args.mserver,
        port=args.port,
        grpc_port=args.grpcPort,
        host=args.ip,
        data_center=args.dataCenter,
        rack=args.rack,
        max_volume_count=args.max,
    )
    vs.start()
    print(f"volume server on http {vs.url} grpc {vs.grpc_address}")
    _wait_forever()
    vs.stop()
    return 0


register(Command("volume", "run a volume server", _volume_conf, _volume_run))


def _server_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-masterPort", type=int, default=9333)
    p.add_argument("-port", type=int, default=8080, help="volume server http port")
    p.add_argument("-dir", action="append", default=None)
    p.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)


def _server_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    m = MasterServer(
        port=args.masterPort,
        host=args.ip,
        volume_size_limit=args.volumeSizeLimitMB * 1024 * 1024,
    )
    m.start()
    vs = VolumeServer(
        args.dir or ["./data"], m.address, port=args.port, host=args.ip
    )
    vs.start()
    print(f"server: master {m.address}, volume http {vs.url} grpc {vs.grpc_address}")
    _wait_forever()
    vs.stop()
    m.stop()
    return 0


register(Command("server", "run master + volume server in one process", _server_conf, _server_run))


def _shell_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-c", dest="script", default="", help="run `;`-separated commands and exit")


def _shell_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.shell import CommandEnv, repl, run_script

    with CommandEnv(args.master) as env:
        if args.script:
            run_script(env, args.script, sys.stdout)
        else:
            repl(env, sys.stdin, sys.stdout)
    return 0


register(Command("shell", "operator shell (REPL or -c script)", _shell_conf, _shell_run))
