"""CLI command registry — mirror of weed/command's Command-struct pattern
[VERIFY: mount empty; SURVEY.md §2.1 "CLI entry"]. Each command module
registers a `Command(name, help, run)`; `seaweedfs_tpu.__main__` dispatches.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Callable


@dataclass
class Command:
    name: str
    help: str
    configure: Callable[[argparse.ArgumentParser], None]
    run: Callable[[argparse.Namespace], int]


_REGISTRY: dict[str, Command] = {}


def register(cmd: Command) -> Command:
    _REGISTRY[cmd.name] = cmd
    return cmd


def commands() -> dict[str, Command]:
    # import for side effect of registration
    from seaweedfs_tpu.command import bench_tools  # noqa: F401
    from seaweedfs_tpu.command import local  # noqa: F401
    from seaweedfs_tpu.command import servers  # noqa: F401
    from seaweedfs_tpu.command import sync  # noqa: F401

    return dict(_REGISTRY)
