"""Sync/backup commands — mirror of weed/command/filer_sync.go,
filer_backup.go [VERIFY: mount empty; SURVEY.md §2.1 "Replication/sync"].

  filer.sync   — continuous one-way replication filer A -> filer B
  filer.backup — drain pending metadata events into a local directory
"""

from __future__ import annotations

import argparse
import signal
import threading

from seaweedfs_tpu.command import Command, register


def _sync_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-from", dest="src_grpc", required=True, help="source filer grpc host:port")
    p.add_argument("-to", dest="dst_http", required=True, help="target filer http host:port")
    p.add_argument("-prefix", default="/", help="only sync this subtree")
    p.add_argument("-targetPath", default="/", help="root on the target filer")
    p.add_argument("-id", default="", help="checkpoint id (default: sink kind)")


def _sync_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.replication import FilerSink, Replicator

    sink = FilerSink(args.dst_http, target_root=args.targetPath)
    rep = Replicator(
        args.src_grpc, sink, prefix=args.prefix,
        sink_id=args.id or f"filer.{args.dst_http}",
    )
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            break
    print(f"filer.sync {args.src_grpc} -> {args.dst_http} (prefix {args.prefix})")
    rep.run(stop)
    rep.close()
    return 0


register(Command("filer.sync", "continuously replicate one filer into another", _sync_conf, _sync_run))


def _backup_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-filerGrpc", required=True, help="source filer grpc host:port")
    p.add_argument("-dir", required=True, help="local backup directory")
    p.add_argument("-prefix", default="/")
    p.add_argument("-id", default="", help="checkpoint id (default: local.<dir>)")


def _backup_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.replication import LocalSink, Replicator

    sink = LocalSink(args.dir)
    rep = Replicator(
        args.filerGrpc, sink, prefix=args.prefix,
        sink_id=args.id or f"local.{args.dir}",
    )
    n = rep.run_once()
    print(f"applied {n} events into {args.dir}")
    rep.close()
    return 0


register(Command("filer.backup", "apply pending filer events to a local directory", _backup_conf, _backup_run))


def _meta_tail_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-filerGrpc", required=True, help="filer grpc host:port")
    p.add_argument("-prefix", default="/", help="only events under this subtree")
    p.add_argument("-sinceNs", type=int, default=0, help="replay from this event ts")
    p.add_argument(
        "-maxIdleSeconds",
        type=float,
        default=0,
        help="exit after this much quiet (0 = follow forever)",
    )


def _meta_tail_run(args: argparse.Namespace) -> int:
    """Stream the filer metadata event log to stdout as JSON lines
    (filer.meta.tail analog) — the operator's live view of namespace
    mutations, and the same feed replication/mq consume."""
    import json

    from seaweedfs_tpu.filer.client import FilerClient

    with FilerClient(args.filerGrpc) as fc:
        try:
            for ev in fc.subscribe(
                since_ns=args.sinceNs,
                path_prefix=args.prefix,
                max_idle_s=args.maxIdleSeconds,
            ):
                print(json.dumps(ev.to_dict()), flush=True)
        except KeyboardInterrupt:
            pass
    return 0


register(Command("filer.meta.tail", "stream filer metadata events as JSON lines", _meta_tail_conf, _meta_tail_run))
