"""Sync/backup commands — mirror of weed/command/filer_sync.go,
filer_backup.go [VERIFY: mount empty; SURVEY.md §2.1 "Replication/sync"].

  filer.sync   — continuous one-way replication filer A -> filer B
  filer.backup — drain pending metadata events into a local directory
"""

from __future__ import annotations

import argparse
import signal
import threading

from seaweedfs_tpu.command import Command, register


def _sync_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-from", dest="src_grpc", required=True, help="source filer grpc host:port")
    p.add_argument("-to", dest="dst_http", required=True, help="target filer http host:port")
    p.add_argument("-prefix", default="/", help="only sync this subtree")
    p.add_argument("-targetPath", default="/", help="root on the target filer")
    p.add_argument("-id", default="", help="checkpoint id (default: sink kind)")


def _sync_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.replication import FilerSink, Replicator

    sink = FilerSink(args.dst_http, target_root=args.targetPath)
    rep = Replicator(
        args.src_grpc, sink, prefix=args.prefix,
        sink_id=args.id or f"filer.{args.dst_http}",
    )
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            break
    print(f"filer.sync {args.src_grpc} -> {args.dst_http} (prefix {args.prefix})")
    rep.run(stop)
    rep.close()
    return 0


register(Command("filer.sync", "continuously replicate one filer into another", _sync_conf, _sync_run))


def _backup_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-filerGrpc", required=True, help="source filer grpc host:port")
    p.add_argument("-dir", required=True, help="local backup directory")
    p.add_argument("-prefix", default="/")
    p.add_argument("-id", default="", help="checkpoint id (default: local.<dir>)")


def _backup_run(args: argparse.Namespace) -> int:
    from seaweedfs_tpu.replication import LocalSink, Replicator

    sink = LocalSink(args.dir)
    rep = Replicator(
        args.filerGrpc, sink, prefix=args.prefix,
        sink_id=args.id or f"local.{args.dir}",
    )
    n = rep.run_once()
    print(f"applied {n} events into {args.dir}")
    rep.close()
    return 0


register(Command("filer.backup", "apply pending filer events to a local directory", _backup_conf, _backup_run))


def _meta_tail_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-filerGrpc", required=True, help="filer grpc host:port")
    p.add_argument("-prefix", default="/", help="only events under this subtree")
    p.add_argument("-sinceNs", type=int, default=0, help="replay from this event ts")
    p.add_argument(
        "-maxIdleSeconds",
        type=float,
        default=0,
        help="exit after this much quiet (0 = follow forever)",
    )


def _meta_tail_run(args: argparse.Namespace) -> int:
    """Stream the filer metadata event log to stdout as JSON lines
    (filer.meta.tail analog) — the operator's live view of namespace
    mutations, and the same feed replication/mq consume."""
    import json

    from seaweedfs_tpu.filer.client import FilerClient

    with FilerClient(args.filerGrpc) as fc:
        try:
            for ev in fc.subscribe(
                since_ns=args.sinceNs,
                path_prefix=args.prefix,
                max_idle_s=args.maxIdleSeconds,
            ):
                print(json.dumps(ev.to_dict()), flush=True)
        except KeyboardInterrupt:
            pass
    return 0


register(Command("filer.meta.tail", "stream filer metadata events as JSON lines", _meta_tail_conf, _meta_tail_run))


def _filer_copy_conf(p: argparse.ArgumentParser) -> None:
    p.add_argument("-filer", required=True, help="filer http host:port")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    p.add_argument("-ttl", default="")
    p.add_argument("sources", nargs="+", help="local files/directories to copy")
    p.add_argument("target", help="filer directory (must end with /)")


def _filer_copy_run(args: argparse.Namespace) -> int:
    """Bulk-copy local trees into the filer over HTTP (filer.copy analog)."""
    import os
    import urllib.parse
    import urllib.request

    if not args.target.endswith("/"):
        print("target must be a filer DIRECTORY path ending with /")
        return 1
    q = {}
    for k in ("collection", "replication", "ttl"):
        if getattr(args, k):
            q[k] = getattr(args, k)
    query = ("?" + urllib.parse.urlencode(q)) if q else ""
    copied = failed = 0

    def put(local: str, remote: str) -> None:
        nonlocal copied, failed
        try:  # one unreadable source must not abort the bulk copy
            size = os.path.getsize(local)
            with open(local, "rb") as f:
                # stream the file object (constant memory on multi-GB
                # files); explicit Content-Length — the filer refuses
                # chunked uploads with 411
                req = urllib.request.Request(
                    f"http://{args.filer}{urllib.parse.quote(remote)}{query}",
                    data=f,
                    method="PUT",
                    headers={"Content-Length": str(size)},
                )
                with urllib.request.urlopen(req, timeout=600) as r:
                    r.read()
            copied += 1
            print(f"{local} -> {remote} ({size} bytes)")
        except Exception as e:  # noqa: BLE001 — keep copying the rest
            failed += 1
            print(f"FAILED {local}: {e}")

    for src in args.sources:
        if os.path.isdir(src):
            base = os.path.basename(os.path.abspath(src))
            for root, _dirs, files in os.walk(src):
                rel_root = os.path.relpath(root, src)
                for name in sorted(files):
                    rel = name if rel_root == "." else f"{rel_root}/{name}"
                    put(os.path.join(root, name), f"{args.target}{base}/{rel}")
        else:
            put(src, f"{args.target}{os.path.basename(src)}")
    print(f"filer.copy: {copied} copied, {failed} failed")
    return 0 if failed == 0 else 1


register(Command("filer.copy", "bulk-copy local files/directories into the filer", _filer_copy_conf, _filer_copy_run))
