"""`python -m seaweedfs_tpu <command>` — the `weed`-style single entry point
(ref: weed/command CLI layout, SURVEY.md §2.1 [VERIFY: mount empty])."""

from __future__ import annotations

import argparse
import sys

from seaweedfs_tpu.command import commands


def main(argv=None) -> int:
    cmds = commands()
    parser = argparse.ArgumentParser(
        prog="seaweedfs_tpu",
        description="TPU-native SeaweedFS-capability framework",
    )
    sub = parser.add_subparsers(dest="command", metavar="command")
    for cmd in cmds.values():
        p = sub.add_parser(cmd.name, help=cmd.help)
        cmd.configure(p)
        p.set_defaults(_run=cmd.run)
    args = parser.parse_args(argv)
    if not getattr(args, "_run", None):
        parser.print_help()
        return 2
    try:
        return args._run(args)
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
