"""`python -m seaweedfs_tpu <command>` — the `weed`-style single entry point
(ref: weed/command CLI layout, SURVEY.md §2.1 [VERIFY: mount empty]).

Every command accepts -cpuprofile/-memprofile (the reference's pprof
flags, SURVEY.md §5): cProfile stats / tracemalloc snapshot written on
exit."""

from __future__ import annotations

import argparse
import sys

from seaweedfs_tpu.command import commands


def main(argv=None) -> int:
    cmds = commands()
    parser = argparse.ArgumentParser(
        prog="seaweedfs_tpu",
        description="TPU-native SeaweedFS-capability framework",
    )
    sub = parser.add_subparsers(dest="command", metavar="command")
    for cmd in cmds.values():
        p = sub.add_parser(cmd.name, help=cmd.help)
        cmd.configure(p)
        p.add_argument("-cpuprofile", default="", help="write cProfile stats here on exit")
        p.add_argument("-memprofile", default="", help="write a tracemalloc snapshot here on exit")
        p.set_defaults(_run=cmd.run)
    args = parser.parse_args(argv)
    if not getattr(args, "_run", None):
        parser.print_help()
        return 2
    # process-wide TLS from security.toml [grpc]: activated before any
    # command binds a socket or dials a peer, so every server AND tool
    # (shell, upload, sync, ...) in this process speaks TLS uniformly
    from seaweedfs_tpu.security import tls as _tls
    from seaweedfs_tpu.utils.config import load_configuration as _load_conf

    try:
        _tls.configure_from_conf(_load_conf("security"))
    except (OSError, ValueError) as e:
        print(f"security.toml tls config error: {e}", file=sys.stderr)
        return 1
    profiler = None
    if getattr(args, "cpuprofile", ""):
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()
    if getattr(args, "memprofile", ""):
        import tracemalloc

        tracemalloc.start()
    try:
        return args._run(args)
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        if profiler is not None:
            profiler.disable()
            profiler.dump_stats(args.cpuprofile)
        if getattr(args, "memprofile", ""):
            import tracemalloc

            snap = tracemalloc.take_snapshot()
            with open(args.memprofile, "w", encoding="utf-8") as f:
                for stat in snap.statistics("lineno")[:200]:
                    f.write(str(stat) + "\n")


if __name__ == "__main__":
    sys.exit(main())
