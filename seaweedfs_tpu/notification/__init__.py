"""Notification — mirror of weed/notification/ (kafka/sqs/pubsub sinks
for filer metadata events) [VERIFY: mount empty; SURVEY.md §2.1
"Replication/sync" row].

No message brokers exist in this image, so the two concrete queues are
in-memory (tests, in-process consumers) and an append-only JSONL log
file (durable handoff to external shippers). The interface matches the
reference's: one `send_message(key, message)` per filer event.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Optional


class NotificationQueue:
    """Target for filer metadata event notifications."""

    def send_message(self, key: str, message: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryQueue(NotificationQueue):
    def __init__(self):
        self.messages: list[tuple[str, dict]] = []
        self._lock = threading.Lock()
        self._subscribers: list[Callable[[str, dict], None]] = []

    def send_message(self, key: str, message: dict) -> None:
        with self._lock:
            self.messages.append((key, message))
            subs = list(self._subscribers)
        for fn in subs:
            fn(key, message)

    def subscribe(self, fn: Callable[[str, dict], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)


class LogFileQueue(NotificationQueue):
    """Durable JSONL event log (one file, append-only)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # weedlint: ignore[open-no-ctx] queue-lifetime append handle
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def send_message(self, key: str, message: dict) -> None:
        with self._lock:
            self._f.write(json.dumps({"key": key, "message": message}) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.close()


def make_queue(kind: str, path: str = "") -> Optional[NotificationQueue]:
    """Factory, the `[notification.*]` filer.toml seam of the reference."""
    if kind in ("", "none"):
        return None
    if kind == "memory":
        return MemoryQueue()
    if kind == "log":
        if not path:
            raise ValueError("log notification queue needs a file path")
        return LogFileQueue(path)
    raise ValueError(f"unknown notification queue {kind!r} (memory|log|none)")
