"""weedtrace — zero-dependency observability for seaweedfs_tpu.

`obs.trace` is the end-to-end request-tracing layer: context-local
spans threaded through every hot path (degraded reads, rebuild
pipelines, scrub/repair, inline ingest, geometry conversion), trace-id
propagation across the RPC and HTTP seams, and a per-process bounded
ring of completed traces with tail-biased retention. Surfaces:
`/debug/traces` on the volume-server/master HTTP fronts, the `ec.trace`
shell command, and `slo.assemble_trace_attribution` (the per-stage
tail-attribution artifact weedload commits).
"""

from seaweedfs_tpu.obs import trace  # noqa: F401 — the package's one module
