"""weedtrace core: context-local spans, trace ids, and the bounded
per-process trace ring with tail-biased retention.

Design constraints, in order:

1. **Safe to leave ON.** The read path takes thousands of spans/second
   under load, so recording must be allocation-light and lock-free on
   the span path: a span is one `__slots__` object appended to its
   parent's list; serialization to dicts happens lazily at snapshot
   time (`/debug/traces`, weedload's scrape), never per request. With
   `WEEDTPU_TRACE=off` the root constructors return a no-op and every
   `span()` call collapses to one ContextVar read. No fsync, no I/O,
   ever — the ring lives and dies with the process.

2. **Tail-biased retention.** A uniform sample would retain exactly the
   traces the p99 is NOT about. The ring always keeps error traces and
   the N slowest per (kind, class); everything else is probabilistically
   sampled (`WEEDTPU_TRACE_SAMPLE`) into a bounded FIFO. Total memory is
   bounded by `WEEDTPU_TRACE_RING` + N x live (kind, class) keys +
   the error buffer.

3. **One id end to end.** Trace ids are minted at the HTTP fronts and
   the shell, ride gRPC invocation metadata (`weedtpu-trace` — request
   METADATA, so the pinned proto contracts are untouched) and the
   `X-Weedtpu-Trace` HTTP header, and come back on the response so a
   client can grep every process's glog lines / trace rings for one
   slow request.

Span names are a closed catalog (`SPAN_NAMES`): weedlint's obs-drift
family asserts every `span("...")` call site in the package names a
registered stage and every registered stage is used — dashboards and
the tail-attribution artifact key on these strings, so they must not
drift.
"""

from __future__ import annotations

import bisect
import contextvars
import os
import random
import re
import threading
import time
from typing import Iterator, Optional

from seaweedfs_tpu.utils import config

#: the registered stage catalog — every span()/start()/ensure() name in
#: the package MUST appear here (weedlint: obs-span-undeclared), and
#: every entry must have a call site (obs-span-unused). The tail-
#: attribution artifact and `ec.trace` render these strings verbatim.
SPAN_NAMES: dict[str, str] = {
    "http.read": "volume-server HTTP GET of one needle (the serving path)",
    "http.write": "volume-server HTTP POST/PUT of one needle",
    "master.http": "master HTTP facade route (/dir/assign, /dir/lookup, ...)",
    "shell.command": "one weed-shell command execution",
    "rpc.server": "server side of one gRPC method (method name in attrs)",
    "ec.lookup": "master LookupEcVolume round-trip (shard-location cache miss)",
    "ec.recover": "degraded interval reconstruction, client-facing wall time",
    "ec.gather": "survivor fan-out for one interval (local + remote fetches)",
    "ec.fetch": "one remote shard-interval fetch attempt (primary)",
    "ec.fetch.holder": "one holder attempt inside a fetch's failover ladder",
    "ec.hedge": "backup fetch raced against a slow primary",
    "ec.coalesce.wait": "waiter parked on another read's in-flight decode",
    "ec.decode": "GF decode dispatch (backend + batch width in attrs)",
    "cache.hit": "interval served from the decoded-interval cache (no fan-out)",
    "cache.miss": "decoded-interval cache consulted and empty for this interval",
    "rebuild.run": "one whole-volume rebuild (local or distributed)",
    "rebuild.stage": "staging-ring fill for one rebuild batch (disk/wire)",
    "rebuild.drain": "device sync + shard write-out for one rebuild batch",
    "encode.stage": "staging-ring fill for one encode batch",
    "encode.drain": "device sync + shard write-out for one encode batch",
    "ingest.encode": "inline-EC encode of newly-final large rows (one poll)",
    "ingest.seal": "inline-EC seal finalization of one volume",
    "ingest.spread.commit": "seal-time commit of one pre-spread parity shard",
    "scrub.cycle": "one full background integrity pass over mounted shards",
    "scrub.repair": "one automatic repair attempt of a quarantined shard",
    "convert.run": "one whole-volume geometry conversion",
    "convert.chunk": "one journaled chunk of a geometry conversion",
    "heal.verify": "verify-on-read culprit hunt after a body-CRC failure",
}

_ID_RE = re.compile(r"^[0-9a-fA-F][0-9a-fA-F-]{0,63}$")

#: gRPC invocation-metadata key / HTTP header the id rides on
MD_KEY = "weedtpu-trace"
HTTP_HEADER = "X-Weedtpu-Trace"
#: HTTP response header carrying the serving class a read resolved to
#: (healthy / ec_intact / cached / degraded) — weedload classifies
#: per-request latencies from it instead of guessing from topology
READ_CLASS_HEADER = "X-Weedtpu-Read-Class"

_cv: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "weedtpu_trace_span", default=None
)

#: guards only the first-child list publication in Span.add_child
_first_child_lock = threading.Lock()


def enabled() -> bool:
    return config.env("WEEDTPU_TRACE") == "on"


def new_trace_id() -> str:
    return os.urandom(8).hex()


def valid_id(tid) -> Optional[str]:
    """Sanitized inbound trace id, or None (never trust wire input)."""
    if isinstance(tid, str) and _ID_RE.match(tid):
        return tid.lower()
    return None


class _TraceState:
    """Shared per-trace state every span of the tree points at."""

    __slots__ = ("trace_id", "kind", "klass", "wall0", "t0")

    def __init__(self, trace_id: str, kind: str, klass: str):
        self.trace_id = trace_id
        self.kind = kind
        self.klass = klass
        self.wall0 = time.time()
        self.t0 = time.monotonic()


class Span:
    __slots__ = ("name", "attrs", "t0", "dur", "children", "error", "trace")

    def __init__(self, name: str, attrs: Optional[dict], trace: _TraceState):
        self.name = name
        self.attrs = attrs
        self.t0 = time.monotonic()
        self.dur = 0.0
        self.children: Optional[list] = None
        self.error: Optional[str] = None
        self.trace = trace

    def annotate(self, **kv) -> None:
        if self.attrs is None:
            self.attrs = kv
        else:
            self.attrs.update(kv)

    def add_child(self, child: "Span") -> None:
        # list.append is atomic under the GIL, so the steady state is
        # lock-free — but the FIRST-child check-then-assign is not: two
        # pool workers attaching the first two children concurrently
        # could each publish their own list and lose a span. One shared
        # lock guards only that publication (double-checked).
        if self.children is None:
            with _first_child_lock:
                if self.children is None:
                    self.children = []
        self.children.append(child)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "t_ms": round((self.t0 - self.trace.t0) * 1e3, 3),
            "dur_ms": round(self.dur * 1e3, 3),
        }
        if self.attrs:
            d["attrs"] = {k: v for k, v in self.attrs.items()}
        if self.error:
            d["error"] = self.error
        if self.children:
            d["spans"] = [c.to_dict() for c in self.children]
        return d


class _Completed:
    """One finished trace held in the ring — serialized lazily."""

    __slots__ = ("root", "state", "dur", "error")

    def __init__(self, root: Span, state: _TraceState, error: Optional[str]):
        self.root = root
        self.state = state
        self.dur = root.dur
        self.error = error

    def to_dict(self) -> dict:
        return {
            "trace_id": self.state.trace_id,
            "kind": self.state.kind,
            "class": self.state.klass,
            "start": round(self.state.wall0, 3),
            "duration_s": round(self.dur, 6),
            "error": self.error,
            "root": self.root.to_dict(),
        }


class TraceRing:
    """Bounded retention of completed traces, tail-biased:

    - every ERROR trace lands in a bounded error buffer (newest win),
    - the `slowest_n` slowest per (kind, class) are always kept,
    - the rest pass a probabilistic sample gate into a bounded FIFO.

    `seed` pins the sampler for deterministic tests; 0 = entropy."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        slowest_n: Optional[int] = None,
        sample: Optional[float] = None,
        seed: Optional[int] = None,
        errors_cap: int = 64,
    ):
        self._lock = threading.Lock()
        self.capacity = int(capacity if capacity is not None else config.env("WEEDTPU_TRACE_RING"))
        self.slowest_n = int(
            slowest_n if slowest_n is not None else config.env("WEEDTPU_TRACE_SLOWEST")
        )
        self._sample = sample
        self.errors_cap = errors_cap
        s = seed if seed is not None else config.env("WEEDTPU_TRACE_SEED")
        self._rng = random.Random(s or None)
        self._sampled: list[_Completed] = []
        self._errors: list[_Completed] = []
        #: (kind, class) -> ascending-by-duration list of _Completed
        self._slowest: dict[tuple[str, str], list[_Completed]] = {}
        self.offered = 0
        self.kept = 0

    def _sample_rate(self) -> float:
        if self._sample is not None:
            return self._sample
        return float(config.env("WEEDTPU_TRACE_SAMPLE"))

    def offer(self, done: _Completed) -> bool:
        kept = False
        with self._lock:
            self.offered += 1
            if done.error is not None:
                self._errors.append(done)
                if len(self._errors) > self.errors_cap:
                    del self._errors[0]
                kept = True
            key = (done.state.kind, done.state.klass)
            row = self._slowest.setdefault(key, [])
            if len(row) < self.slowest_n or done.dur > row[0].dur:
                # insert sorted ascending; evict the least-slow
                bisect.insort(row, done, key=lambda c: c.dur)
                if len(row) > self.slowest_n:
                    del row[0]
                kept = True
            if not kept:
                rate = self._sample_rate()
                if rate >= 1.0 or self._rng.random() < rate:
                    self._sampled.append(done)
                    if len(self._sampled) > self.capacity:
                        del self._sampled[0]
                    kept = True
            if kept:
                self.kept += 1
        return kept

    def snapshot(
        self,
        kind: Optional[str] = None,
        klass: Optional[str] = None,
        min_duration: float = 0.0,
        limit: int = 100,
    ) -> list[dict]:
        """Serialized retained traces, slowest first, deduped by identity
        (a trace can sit in both the slowest row and the sampled FIFO)."""
        with self._lock:
            all_: list[_Completed] = list(self._sampled) + list(self._errors)
            for row in self._slowest.values():
                all_.extend(row)
        seen: set[int] = set()
        out: list[_Completed] = []
        for c in all_:
            if id(c) in seen:
                continue
            seen.add(id(c))
            if kind and c.state.kind != kind:
                continue
            if klass and c.state.klass != klass:
                continue
            if c.dur < min_duration:
                continue
            out.append(c)
        out.sort(key=lambda c: c.dur, reverse=True)
        return [c.to_dict() for c in out[: max(0, int(limit))]]

    def stats(self) -> dict:
        with self._lock:
            return {
                "offered": self.offered,
                "kept": self.kept,
                "sampled": len(self._sampled),
                "errors": len(self._errors),
                "slowest_keys": len(self._slowest),
            }

    def clear(self) -> None:
        with self._lock:
            self._sampled.clear()
            self._errors.clear()
            self._slowest.clear()
            self.offered = self.kept = 0


#: the per-process ring every finished root lands in
RING = TraceRing()


# -- recording primitives ------------------------------------------------------


class _NullCtx:
    """Shared no-op for disabled tracing / span-outside-trace — one
    allocation for the whole process, not one per call."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class span:  # noqa: N801 — reads as a statement: `with span("ec.gather"):`
    """Record one child span under the ambient trace; a no-op (and
    allocation-free beyond this tiny object) when no trace is active."""

    __slots__ = ("_name", "_attrs", "_sp", "_tok")

    def __init__(self, _name: str, **attrs):
        self._name = _name
        self._attrs = attrs or None
        self._sp = None
        self._tok = None

    def __enter__(self) -> Optional[Span]:
        parent = _cv.get()
        if parent is None:
            return None
        sp = Span(self._name, self._attrs, parent.trace)
        parent.add_child(sp)
        self._sp = sp
        self._tok = _cv.set(sp)
        return sp

    def __exit__(self, et, ev, tb):
        sp = self._sp
        if sp is None:
            return False
        sp.dur = time.monotonic() - sp.t0
        if et is not None and sp.error is None:
            sp.error = et.__name__
        _cv.reset(self._tok)
        return False


class _RootCtx:
    __slots__ = ("_state", "_root", "_tok", "_ring")

    def __init__(self, state: _TraceState, ring: TraceRing):
        self._state = state
        self._ring = ring
        self._root = None
        self._tok = None

    def __enter__(self) -> Span:
        root = Span(self._state.kind, None, self._state)
        self._root = root
        self._tok = _cv.set(root)
        return root

    def __exit__(self, et, ev, tb):
        root = self._root
        root.dur = time.monotonic() - root.t0
        error = None
        if et is not None:
            error = f"{et.__name__}: {ev}"[:200]
            root.error = et.__name__
        _cv.reset(self._tok)
        self._ring.offer(_Completed(root, self._state, error))
        return False


def start(kind: str, klass: str = "healthy", trace_id=None, ring: Optional[TraceRing] = None):
    """Begin a root trace (the HTTP fronts, the shell, background
    maintenance). `trace_id` adopts a propagated id (sanitized); absent
    or invalid ids mint a fresh one. Returns a context manager yielding
    the root Span — or a no-op when tracing is off."""
    if not enabled():
        return _NULL
    tid = valid_id(trace_id) or new_trace_id()
    return _RootCtx(_TraceState(tid, kind, klass), ring or RING)


def continue_trace(kind: str, trace_id, klass: str = "rpc", ring: Optional[TraceRing] = None):
    """Root trace ONLY when a propagated id arrived — the RPC server
    seam: un-traced callers (heartbeats, bare clients) cost nothing,
    traced callers get their id continued in this process's ring."""
    tid = valid_id(trace_id)
    if tid is None or not enabled():
        return _NULL
    return _RootCtx(_TraceState(tid, kind, klass), ring or RING)


def ensure(kind: str, klass: str = "maint"):
    """A span under the ambient trace when one is active, else a fresh
    root trace — maintenance paths (rebuild, convert, scrub repair,
    seal) are always visible in the ring, and nest correctly when an
    operator's shell trace reached them over RPC."""
    if _cv.get() is not None:
        return span(kind)
    return start(kind, klass=klass)


def current() -> Optional[Span]:
    return _cv.get()


def current_trace_id() -> Optional[str]:
    sp = _cv.get()
    return sp.trace.trace_id if sp is not None else None


def current_class() -> Optional[str]:
    sp = _cv.get()
    return sp.trace.klass if sp is not None else None


def annotate(**kv) -> None:
    sp = _cv.get()
    if sp is not None:
        sp.annotate(**kv)


def set_class(klass: str) -> None:
    """Reclassify the AMBIENT trace (e.g. a read that turned degraded
    mid-flight) — retention and attribution key on the final class."""
    sp = _cv.get()
    if sp is not None:
        sp.trace.klass = klass


class attach:  # noqa: N801 — `with attach(parent):` in worker threads
    """Adopt a span captured in another thread as this thread's ambient
    span — the fetch-pool workers' bridge (ContextVars don't cross
    thread-pool submission)."""

    __slots__ = ("_sp", "_tok")

    def __init__(self, sp: Optional[Span]):
        self._sp = sp
        self._tok = None

    def __enter__(self):
        if self._sp is not None:
            self._tok = _cv.set(self._sp)
        return self._sp

    def __exit__(self, *exc):
        if self._tok is not None:
            _cv.reset(self._tok)
        return False


def traced(name: str, **attrs):
    """Decorator form of `span` for whole-function stages."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(name, **attrs):
                return fn(*a, **kw)

        return wrapper

    return deco


# -- the /debug/traces surface -------------------------------------------------


def debug_payload(request_path: str, ring: Optional[TraceRing] = None) -> dict:
    """The `/debug/traces` JSON body for one HTTP request path (query
    string included): filter by `kind`, `class`, `min_ms`, cap with
    `limit`. Shared by the volume-server and master HTTP fronts."""
    import urllib.parse

    q = {
        k: v[0]
        for k, v in urllib.parse.parse_qs(
            urllib.parse.urlparse(request_path).query
        ).items()
    }

    def _f(name: str, default: float) -> float:
        try:
            return float(q.get(name, default))
        except (TypeError, ValueError):
            return default

    ring = ring or RING
    return {
        "enabled": enabled(),
        "stats": ring.stats(),
        "traces": ring.snapshot(
            kind=q.get("kind") or None,
            klass=q.get("class") or None,
            min_duration=_f("min_ms", 0.0) / 1e3,
            limit=int(_f("limit", 100)),
        ),
    }


# -- rendering (ec.trace / tests) ---------------------------------------------


def render_trace(trace: dict) -> str:
    """Human span tree with wall times — the `ec.trace` output format.

    trace=4f1d... http.read class=degraded 812.4ms
      +-   0.1ms   810.9ms ec.recover
      |  +-   0.2ms   540.0ms ec.gather shard=3
      ...
    """
    lines = [
        f"trace={trace['trace_id']} {trace['kind']} "
        f"class={trace['class']} {trace['duration_s'] * 1e3:.1f}ms"
        + (f" ERROR={trace['error']}" if trace.get("error") else "")
    ]

    def walk(sp: dict, depth: int) -> None:
        attrs = " ".join(f"{k}={v}" for k, v in (sp.get("attrs") or {}).items())
        err = f" ERROR={sp['error']}" if sp.get("error") else ""
        lines.append(
            f"{'|  ' * depth}+- {sp['t_ms']:8.1f}ms {sp['dur_ms']:9.1f}ms "
            f"{sp['name']}" + (f" {attrs}" if attrs else "") + err
        )
        for c in sp.get("spans", ()):
            walk(c, depth + 1)

    for c in trace["root"].get("spans", ()):
        walk(c, 0)
    return "\n".join(lines)


# -- per-stage attribution (slo.py's aggregation input) ------------------------


def attribute_stages(trace: dict) -> dict[str, float]:
    """Per-stage attributed seconds for ONE trace, summing EXACTLY to
    its end-to-end duration.

    Each span's self-time (duration minus its children's) goes to its
    own name; the root's self-time goes to "other". Children that
    overlap in parallel (hedged/fan-out fetches, whose summed durations
    exceed the parent's wall time) are scaled down proportionally so a
    stage can never be attributed more wall time than actually passed —
    the property that makes per-class stage sums comparable against the
    observed e2e latencies."""
    stages: dict[str, float] = {}

    def walk(sp: dict, budget: float, is_root: bool) -> None:
        children = sp.get("spans") or []
        child_sum = sum(c["dur_ms"] for c in children) / 1e3
        scale = 1.0
        if child_sum > budget > 0:
            scale = budget / child_sum
        self_t = max(0.0, budget - child_sum * scale)
        key = "other" if is_root else sp["name"]
        stages[key] = stages.get(key, 0.0) + self_t
        for c in children:
            walk(c, (c["dur_ms"] / 1e3) * scale, False)

    walk(trace["root"], trace["duration_s"], True)
    return stages


def iter_spans(trace: dict) -> Iterator[dict]:
    stack = [trace["root"]]
    while stack:
        sp = stack.pop()
        yield sp
        stack.extend(sp.get("spans", ()))
