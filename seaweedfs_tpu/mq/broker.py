"""MQ broker — mirror of weed/mq/broker/ (publish/subscribe RPC over
log-structured topics persisted via the filer) [VERIFY: mount empty;
SURVEY.md §2.1 "Messaging" row].

RPC surface (weedtpu.MessageQueue):
  ConfigureTopic  {namespace, topic, partition_count}
  ListTopics      {namespace}
  Publish         {namespace, topic, key b64, value b64 [, partition]}
                  -> {partition, ts_ns}
  Subscribe       {namespace, topic, partition, since_ns, max_idle_s}
                  -> stream of LogRecord dicts (flushed segments first,
                     then the live tail)

Consumer groups (weed/mq sub_coordinator analog):
  JoinGroup       {namespace, topic, group, consumer_id}
                  -> {generation, partitions, partition_count}
  GroupHeartbeat  {namespace, topic, group, consumer_id} -> {generation}
  LeaveGroup      {namespace, topic, group, consumer_id}
  CommitOffset    {namespace, topic, group, partition, ts_ns}
  FetchOffset     {namespace, topic, group, partition} -> {ts_ns}

Membership is broker-resident with a session TTL (a consumer that stops
heartbeating is reaped and its partitions rebalance); the generation
bumps on every membership change so consumers detect rebalances.
Committed offsets persist through the filer KV facet, so a group
resumes where it left off across broker AND consumer restarts.

Partition assignment: explicit, else hash(key) % partitions — the
reference's key-hash routing; within a group, partitions are split
round-robin over the sorted member ids.
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.parse
import urllib.request
from typing import Optional

from seaweedfs_tpu import rpc
from seaweedfs_tpu.filer.client import FilerClient
from seaweedfs_tpu.pb import MQ_SERVICE
from seaweedfs_tpu.utils.log_buffer import LogBuffer, LogRecord
from seaweedfs_tpu.security import tls

TOPICS_ROOT = "/topics"


class _Partition:
    def __init__(self, broker: "Broker", ns: str, topic: str, index: int):
        self.broker = broker
        self.dir = f"{TOPICS_ROOT}/{ns}/{topic}/{index:04d}"
        self.buffer = LogBuffer(self._flush_segment)
        self.lock = threading.Lock()
        # bumped on every persisted segment; subscribers re-scan flushed
        # data when it moves (otherwise a flush racing the live tail
        # would hide the drained records in a segment they already read)
        self.flush_seq = 0

    def _flush_segment(self, first_ts: int, last_ts: int, records: list[LogRecord]) -> None:
        body = "\n".join(json.dumps(r.to_dict()) for r in records).encode()
        url = f"{tls.scheme()}://{self.broker.filer_http}{urllib.parse.quote(self.dir)}/{first_ts:020d}.seg"
        req = urllib.request.Request(
            url, data=body, method="PUT",
            headers={"Content-Type": "application/x-weedtpu-segment"},
        )
        with tls.urlopen(req, timeout=60) as r:
            r.read()
        self.flush_seq += 1

    def read_flushed(self, since_ns: int) -> list[LogRecord]:
        segs = sorted(
            (
                e
                for e in self.broker.filer.list(self.dir, limit=1 << 20)
                if e.name.endswith(".seg")
            ),
            key=lambda e: e.name,
        )
        firsts = [int(e.name[: -len(".seg")]) for e in segs]
        out: list[LogRecord] = []
        for i, e in enumerate(segs):
            # segment i covers [firsts[i], firsts[i+1]): skip wholly-old
            # segments by name instead of downloading + parsing them
            if i + 1 < len(firsts) and firsts[i + 1] <= since_ns + 1:
                continue
            raw = self.broker.filer.read_file(e.path)
            for line in raw.decode().splitlines():
                try:
                    rec = LogRecord.from_dict(json.loads(line))
                except (ValueError, KeyError):
                    continue
                if rec.ts_ns > since_ns:
                    out.append(rec)
        return out


class _Group:
    """Resident state of one consumer group on one topic."""

    def __init__(self):
        self.members: dict[str, float] = {}  # consumer_id -> last heartbeat
        self.generation = 0


class Broker:
    GROUP_SESSION_TIMEOUT = 10.0

    def __init__(
        self,
        filer_http_address: str,
        filer_grpc_address: str,
        port: int = 0,
        host: str = "127.0.0.1",
        group_session_timeout: float = GROUP_SESSION_TIMEOUT,
    ):
        self.filer_http = filer_http_address
        self.filer = FilerClient(filer_grpc_address)
        self.host = host
        self.group_session_timeout = group_session_timeout
        self._partitions: dict[tuple[str, str, int], _Partition] = {}
        self._groups: dict[tuple[str, str, str], _Group] = {}
        self._lock = threading.Lock()
        self._grpc = rpc.RpcServer(port=port, host=host)
        self._grpc.add_service(self._build_service())
        self.port = self._grpc.port
        self._stop = threading.Event()
        self._announce_thread = threading.Thread(
            target=self._announce_loop, daemon=True
        )

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._grpc.start()
        self._announce_thread.start()

    def _announce_loop(self) -> None:
        """Register with the master cluster-node list (node_type=broker) so
        shells discover brokers like they discover filers. The masters are
        learned through the filer's configuration — the broker only ever
        needs a filer address to join a cluster."""
        masters: list[str] = []
        while True:
            try:
                if not masters:
                    masters = self.filer.configuration().get("masters", [])
                for m in masters:
                    with rpc.RpcClient(m) as c:
                        c.call(
                            "weedtpu.Master",
                            "FilerHeartbeat",
                            {
                                "http_address": self.address,
                                "grpc_address": self.address,
                                "node_type": "broker",
                            },
                            timeout=5,
                        )
            except Exception:  # noqa: BLE001 — filer/master down; retry
                masters = []
            if self._stop.wait(5.0):
                return

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            parts = list(self._partitions.values())
        for p in parts:
            p.buffer.close()  # final flush -> filer
        self._grpc.stop()
        self.filer.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- topic bookkeeping ----------------------------------------------------

    def _topic_conf(self, ns: str, topic: str) -> Optional[dict]:
        e = self.filer.lookup(f"{TOPICS_ROOT}/{ns}/{topic}")
        if e is None:
            return None
        try:
            return json.loads(e.extended.get("mq", "{}"))
        except ValueError:
            return {}

    def _partition(self, ns: str, topic: str, index: int) -> _Partition:
        key = (ns, topic, index)
        with self._lock:
            p = self._partitions.get(key)
            if p is None:
                p = _Partition(self, ns, topic, index)
                self._partitions[key] = p
            return p

    # -- RPC ------------------------------------------------------------------

    def _build_service(self) -> rpc.Service:
        svc = rpc.Service(MQ_SERVICE)
        svc.add("ConfigureTopic", self._rpc_configure)
        svc.add("ListTopics", self._rpc_list)
        svc.add("Publish", self._rpc_publish)
        svc.add("Subscribe", self._rpc_subscribe, kind="unary_stream", resp_format="json")
        svc.add("JoinGroup", self._rpc_join_group)
        svc.add("GroupHeartbeat", self._rpc_group_heartbeat)
        svc.add("LeaveGroup", self._rpc_leave_group)
        svc.add("CommitOffset", self._rpc_commit_offset)
        svc.add("FetchOffset", self._rpc_fetch_offset)
        return svc

    # -- consumer groups ------------------------------------------------------

    def _reap_stale(self, g: _Group, now: float) -> bool:
        """Caller holds self._lock. Returns True when membership changed."""
        stale = [
            cid
            for cid, seen in g.members.items()
            if now - seen > self.group_session_timeout
        ]
        for cid in stale:
            del g.members[cid]
        if stale:
            g.generation += 1
        return bool(stale)

    def _sweep_dead_groups(self, now: float) -> None:
        """Caller holds self._lock: drop group entries whose every member
        is gone (crashed consumers never call LeaveGroup) — broker-resident
        state must not grow with the history of group names."""
        for key in list(self._groups):
            g = self._groups[key]
            self._reap_stale(g, now)
            if not g.members:
                del self._groups[key]

    def _assigned(self, g: _Group, consumer_id: str, count: int) -> list[int]:
        """Partitions for consumer_id: round-robin over sorted members —
        deterministic, so every member computes the same split."""
        members = sorted(g.members)
        if consumer_id not in members:
            return []
        rank = members.index(consumer_id)
        return [p for p in range(count) if p % len(members) == rank]

    def _rpc_join_group(self, req: dict, ctx) -> dict:
        import time as _time

        ns = req.get("namespace") or "default"
        topic = req["topic"]
        conf = self._topic_conf(ns, topic)
        if conf is None:
            raise rpc.NotFoundFault(f"topic {ns}/{topic} not configured")
        count = int(conf.get("partition_count") or 4)
        cid = req["consumer_id"]
        key = (ns, topic, req.get("group") or "default")
        now = _time.monotonic()
        with self._lock:
            # lookup-or-create and mutate under ONE lock hold: a racing
            # LeaveGroup deleting the entry between two acquisitions would
            # otherwise leave this joiner registered in an orphaned object
            self._sweep_dead_groups(now)
            g = self._groups.get(key)
            if g is None:
                g = self._groups[key] = _Group()
            if cid not in g.members:
                g.generation += 1
            g.members[cid] = now
            return {
                "generation": g.generation,
                "partitions": self._assigned(g, cid, count),
                "partition_count": count,
                "session_timeout_s": self.group_session_timeout,
            }

    def _rpc_group_heartbeat(self, req: dict, ctx) -> dict:
        import time as _time

        ns = req.get("namespace") or "default"
        key = (ns, req["topic"], req.get("group") or "default")
        now = _time.monotonic()
        with self._lock:
            # look up WITHOUT creating: a typo'd topic/group must error,
            # not grow broker-resident state forever
            g = self._groups.get(key)
            if g is not None:
                self._reap_stale(g, now)
                if not g.members:
                    del self._groups[key]  # fully reaped: drop the entry
                    g = None
            if g is None:
                # the consumer treats this as "rejoin" (it may itself have
                # been the reaped member)
                raise rpc.NotFoundFault(f"unknown group {key[2]} on {ns}/{req['topic']}")
            if req["consumer_id"] in g.members:
                g.members[req["consumer_id"]] = now
            return {"generation": g.generation}

    def _rpc_leave_group(self, req: dict, ctx) -> dict:
        ns = req.get("namespace") or "default"
        key = (ns, req["topic"], req.get("group") or "default")
        with self._lock:
            g = self._groups.get(key)
            if g is None:
                return {}
            if g.members.pop(req["consumer_id"], None) is not None:
                g.generation += 1
            if not g.members:  # last one out: drop the resident entry
                del self._groups[key]
        return {}

    @staticmethod
    def _offset_key(ns: str, topic: str, group: str, partition: int) -> str:
        return f"mq.offset/{ns}/{topic}/{group}/{partition:04d}"

    def _rpc_commit_offset(self, req: dict, ctx) -> dict:
        ns = req.get("namespace") or "default"
        key = self._offset_key(
            ns, req["topic"], req.get("group") or "default", int(req["partition"])
        )
        self.filer.kv_put(key, str(int(req["ts_ns"])).encode())
        return {}

    def _rpc_fetch_offset(self, req: dict, ctx) -> dict:
        ns = req.get("namespace") or "default"
        raw = self.filer.kv_get(
            self._offset_key(
                ns, req["topic"], req.get("group") or "default", int(req["partition"])
            )
        )
        return {"ts_ns": int(raw.decode()) if raw else 0}

    def _rpc_configure(self, req: dict, ctx) -> dict:
        from seaweedfs_tpu.filer.entry import Entry

        ns = req.get("namespace") or "default"
        topic = req["topic"]
        count = int(req.get("partition_count") or 4)
        path = f"{TOPICS_ROOT}/{ns}/{topic}"
        e = self.filer.lookup(path)
        if e is None:
            e = Entry(path=path, is_directory=True)
        e.extended["mq"] = json.dumps({"partition_count": count})
        self.filer.create(e)
        return {"partition_count": count}

    def _rpc_list(self, req: dict, ctx) -> dict:
        ns = req.get("namespace") or "default"
        out = []
        for e in self.filer.list(f"{TOPICS_ROOT}/{ns}", limit=10000):
            if e.is_directory:
                conf = {}
                try:
                    conf = json.loads(e.extended.get("mq", "{}"))
                except ValueError:
                    pass
                out.append({"topic": e.name, **conf})
        return {"topics": out}

    def _rpc_publish(self, req: dict, ctx) -> dict:
        import base64

        ns = req.get("namespace") or "default"
        topic = req["topic"]
        conf = self._topic_conf(ns, topic)
        if conf is None:
            raise rpc.NotFoundFault(f"topic {ns}/{topic} not configured")
        count = int(conf.get("partition_count") or 4)
        key = base64.b64decode(req.get("key", ""))
        value = base64.b64decode(req.get("value", ""))
        if "partition" in req:
            index = int(req["partition"]) % count
        else:
            index = int.from_bytes(
                hashlib.md5(key).digest()[:4], "big"
            ) % count if key else 0
        ts = self._partition(ns, topic, index).buffer.add(key, value)
        return {"partition": index, "ts_ns": ts}

    def _rpc_subscribe(self, req: dict, ctx):
        ns = req.get("namespace") or "default"
        topic = req["topic"]
        index = int(req.get("partition", 0))
        since = int(req.get("since_ns", 0))
        max_idle = float(req.get("max_idle_s", 5.0))
        if self._topic_conf(ns, topic) is None:
            raise rpc.NotFoundFault(f"topic {ns}/{topic} not configured")
        part = self._partition(ns, topic, index)
        stop = threading.Event()
        ctx.add_callback(stop.set)
        last = since
        idle = 0.0
        seen_seq = -1  # forces a flushed-segment scan on the first pass
        while not stop.is_set() and idle < max_idle:
            recs: list[LogRecord] = []
            if part.flush_seq != seen_seq:
                # flushed data moved since we last looked (or first pass):
                # re-scan segments so records drained out of the live
                # buffer by a racing flush are never skipped
                seen_seq = part.flush_seq
                recs = part.read_flushed(last)
            recs += part.buffer.read_since(last)
            if recs:
                for rec in sorted(recs, key=lambda r: r.ts_ns):
                    yield rec.to_dict()
                    last = max(last, rec.ts_ns)
                idle = 0.0
            else:
                part.buffer.wait_for_data(last, 0.2)
                idle += 0.2


class BrokerClient:
    """Publish/subscribe client (weed/mq/client analog)."""

    def __init__(self, broker_address: str):
        self._rpc = rpc.RpcClient(broker_address)

    def close(self) -> None:
        self._rpc.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def configure_topic(self, topic: str, partition_count: int = 4, namespace: str = "default") -> None:
        self._rpc.call(
            MQ_SERVICE,
            "ConfigureTopic",
            {"namespace": namespace, "topic": topic, "partition_count": partition_count},
        )

    def list_topics(self, namespace: str = "default") -> list[dict]:
        return self._rpc.call(MQ_SERVICE, "ListTopics", {"namespace": namespace})["topics"]

    def publish(
        self, topic: str, key: bytes, value: bytes,
        namespace: str = "default", partition: Optional[int] = None,
    ) -> dict:
        import base64

        req = {
            "namespace": namespace,
            "topic": topic,
            "key": base64.b64encode(key).decode(),
            "value": base64.b64encode(value).decode(),
        }
        if partition is not None:
            req["partition"] = partition
        return self._rpc.call(MQ_SERVICE, "Publish", req)

    def subscribe(
        self, topic: str, partition: int = 0, since_ns: int = 0,
        namespace: str = "default", max_idle_s: float = 5.0,
    ):
        for d in self._rpc.stream(
            MQ_SERVICE,
            "Subscribe",
            {
                "namespace": namespace,
                "topic": topic,
                "partition": partition,
                "since_ns": since_ns,
                "max_idle_s": max_idle_s,
            },
            resp_format="json",
        ):
            yield LogRecord.from_dict(d)

    # -- consumer groups ------------------------------------------------------

    def join_group(self, topic: str, group: str, consumer_id: str, namespace: str = "default") -> dict:
        return self._rpc.call(
            MQ_SERVICE,
            "JoinGroup",
            {"namespace": namespace, "topic": topic, "group": group, "consumer_id": consumer_id},
        )

    def group_heartbeat(self, topic: str, group: str, consumer_id: str, namespace: str = "default") -> int:
        return int(
            self._rpc.call(
                MQ_SERVICE,
                "GroupHeartbeat",
                {"namespace": namespace, "topic": topic, "group": group, "consumer_id": consumer_id},
            )["generation"]
        )

    def leave_group(self, topic: str, group: str, consumer_id: str, namespace: str = "default") -> None:
        self._rpc.call(
            MQ_SERVICE,
            "LeaveGroup",
            {"namespace": namespace, "topic": topic, "group": group, "consumer_id": consumer_id},
        )

    def commit_offset(self, topic: str, group: str, partition: int, ts_ns: int, namespace: str = "default") -> None:
        self._rpc.call(
            MQ_SERVICE,
            "CommitOffset",
            {"namespace": namespace, "topic": topic, "group": group,
             "partition": partition, "ts_ns": ts_ns},
        )

    def fetch_offset(self, topic: str, group: str, partition: int, namespace: str = "default") -> int:
        return int(
            self._rpc.call(
                MQ_SERVICE,
                "FetchOffset",
                {"namespace": namespace, "topic": topic, "group": group, "partition": partition},
            )["ts_ns"]
        )

    def consume(
        self,
        topic: str,
        group: str,
        consumer_id: str,
        namespace: str = "default",
        poll_idle_s: float = 0.5,
        auto_commit: bool = True,
        commit_every: int = 1,
        max_rounds: Optional[int] = None,
    ):
        """Group consumer loop: join, drain each assigned partition from
        its committed offset, and rebalance whenever the broker's
        generation moves. Yields (partition, LogRecord).

        Commit discipline is commit-on-next-poll (at-least-once): a
        record's offset commits only after the caller comes back for the
        next one — proof it processed the last. A caller that crashes or
        breaks mid-stream therefore sees its last <= `commit_every`
        records redelivered; call `commit_offset(topic, group, p,
        rec.ts_ns)` before a graceful stop to avoid the duplicates.
        Committing any earlier (e.g. on generator close) would silently
        LOSE a record whose processing raised. Raising `commit_every`
        batches the offset RPCs (1 filer kv_put per N records instead of
        per record) at the price of a longer redelivery window.

        Heartbeats pace themselves from the broker's advertised session
        timeout, and every blocking wait is capped below it — a live
        consumer is never reaped for being busy OR idle.

        `max_rounds` bounds the poll loop (None = run until closed)."""
        import time as _time

        state = self.join_group(topic, group, consumer_id, namespace)

        def hb_interval():
            return max(0.05, float(state.get("session_timeout_s", 10.0)) / 3)

        last_hb = _time.monotonic()
        rounds = 0
        while max_rounds is None or rounds < max_rounds:
            rounds += 1
            rebalance = False
            for p in state["partitions"]:
                # heartbeat between partitions too: several idle partitions
                # in sequence must not add up past the session timeout
                if _time.monotonic() - last_hb >= hb_interval():
                    last_hb = _time.monotonic()
                    if self._heartbeat_or_rejoin(
                        topic, group, consumer_id, namespace, state
                    ):
                        rebalance = True
                        break
                since = self.fetch_offset(topic, group, p, namespace)
                pending = 0  # records delivered but not yet committed
                last_ts = since
                for rec in self.subscribe(
                    topic, partition=p, since_ns=since,
                    # cap each blocking wait well below the session timeout:
                    # combined with the pre-partition heartbeat above, the
                    # longest un-heartbeated stretch is ~1.5/3 of the TTL
                    namespace=namespace, max_idle_s=min(poll_idle_s, hb_interval() / 2),
                ):
                    yield p, rec
                    # the caller came back: the record was processed
                    last_ts, pending = rec.ts_ns, pending + 1
                    if auto_commit and pending >= commit_every:
                        self.commit_offset(topic, group, p, last_ts, namespace)
                        pending = 0
                    # a busy partition must not starve the heartbeat —
                    # the broker would reap us as stale mid-stream
                    if _time.monotonic() - last_hb >= hb_interval():
                        last_hb = _time.monotonic()
                        if self._heartbeat_or_rejoin(
                            topic, group, consumer_id, namespace, state
                        ):
                            rebalance = True
                            break
                if auto_commit and pending:
                    self.commit_offset(topic, group, p, last_ts, namespace)
                if rebalance:
                    break
            if not rebalance:
                if not state["partitions"]:
                    # idle member (more consumers than partitions): wait for
                    # a rebalance instead of hammering the broker
                    _time.sleep(min(poll_idle_s, hb_interval()))
                last_hb = _time.monotonic()
                rebalance = self._heartbeat_or_rejoin(
                    topic, group, consumer_id, namespace, state
                )
            if rebalance:  # pick up the new split
                state = self.join_group(topic, group, consumer_id, namespace)

    def _heartbeat_or_rejoin(self, topic, group, consumer_id, namespace, state) -> bool:
        """True when the consumer must rejoin: the generation moved, or the
        broker forgot the group (we were reaped / the entry was swept)."""
        import grpc as _grpc

        try:
            return (
                self.group_heartbeat(topic, group, consumer_id, namespace)
                != state["generation"]
            )
        except _grpc.RpcError as e:
            if e.code() == _grpc.StatusCode.NOT_FOUND:
                return True
            raise
