"""Message queue — mirror of weed/mq/ (log-structured topic broker on
the filer) [VERIFY: mount empty; SURVEY.md §2.1 "Messaging" row].

Topics are partitioned append-only logs. Hot tails live in LogBuffers
(weed/util/log_buffer analog, seaweedfs_tpu.utils.log_buffer); full
segments persist as filer files under

    /topics/<namespace>/<topic>/<partition>/<first_ts_ns>.seg

so the broker is stateless across restarts: subscribers seeking back in
time read flushed segments from the filer, then continue on the live
buffer — the reference broker's read path shape.
"""

from seaweedfs_tpu.mq.broker import Broker, BrokerClient

__all__ = ["Broker", "BrokerClient"]
