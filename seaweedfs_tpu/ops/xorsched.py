"""xorsched — compiled XOR-schedule realization of the GF(2^8) matrix apply.

Any matrix the `Encoder` dispatches (encode parity, fused decode, projection
column-slice, delta-parity column block) is lowered through gf8's bit-plane
decomposition (`gf_matrix_to_bits`) into a binary 8R x 8C matrix over GF(2):
with shard bytes viewed as 8 packed bit-planes, every output bit-plane is the
XOR of a fixed subset of input bit-planes.  The compiler emits that XOR
program once per (matrix bytes, tile geometry) and caches it in a bounded
LRU, exactly like the decode-matrix memo in rs_codec:

* grouping pass — the most frequent source-pair across all outputs is
  hoisted into a reused temporary (greedy common-subexpression elimination,
  after "Accelerating XOR-based Erasure Coding using Program Optimization
  Techniques").  Pairs are only hoisted while they appear >= _GROUP_THRESHOLD
  times: a temp used twice costs one extra store per use saved, so the
  break-even is three uses, and threshold 3 measures ~8% less schedule
  memory traffic than threshold 2 on the 10+4 Cauchy matrix.
* cache tiling — execution walks the width axis in tiles sized so the whole
  slot frame (inputs + temps + outputs, tile/8 bytes per plane) stays
  cache-resident; ops are replayed per tile, not per buffer.

Two executors share the program:

* `apply` — pure-numpy bulk-XOR interpreter.  Always available; the
  byte-exact oracle the native path and the tests verify against.
* `apply_native` — `weedtpu_xor_schedule_apply` in libweedtpu.so (flat op
  list, SIMD XOR over contiguous tiles; GFNI/AVX-512 bit-plane transposes
  where the host has them, AVX2 otherwise, scalar everywhere else).  The
  symbol is version-probed so an old .so quietly yields the interpreter
  instead of crashing.

`apply_blocks` runs MANY programs — a block-diagonal fused decode, one
block per signature group — as ONE native call
(`weedtpu_xor_schedule_apply_blocks`): every (block, width-tile) pair is an
independent task, so the native side spreads the flat task list across a
thread pool (`WEEDTPU_XORSCHED_THREADS`; width tiles never share output
bytes).  Each block keeps its own LRU'd per-matrix program — the composite
is never compiled as one giant matrix.
"""

from __future__ import annotations

import ctypes
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.utils import config

# Hoist a source-pair into a temp only while it recurs at least this often
# (see module docstring for the traffic break-even).
_GROUP_THRESHOLD = 3


@dataclass(frozen=True)
class XorProgram:
    """A compiled XOR schedule for one GF(2^8) matrix.

    Slot space: [0, 8*cols) are the input bit-planes (plane 8c+i = bit i of
    input shard c), temps follow, and [out_base, out_base + 8*rows) are the
    output bit-planes.  `ops` is the flat op list the executors replay, each
    op encoded as [dest_slot, n_src, src_slot...]; n_src == 0 zero-fills
    (an all-zero matrix row) and n_src == 1 copies (an identity row).
    """

    rows: int
    cols: int
    n_slots: int
    out_base: int
    ops: np.ndarray  # int32, flat [dest, nsrc, srcs...] records
    tile_sym: int  # symbols (bytes per shard) processed per tile
    raw_xors: int  # XOR count of the ungrouped program
    xor_count: int  # XOR count after the grouping pass
    n_temps: int

    @property
    def scratch_bytes(self) -> int:
        return self.n_slots * (self.tile_sym // 8)


# ---------------------------------------------------------------------------
# Compiler
# ---------------------------------------------------------------------------


def _pair(a: int, b: int) -> tuple[int, int]:
    return (a, b) if a < b else (b, a)


def _group(sets: list[set[int]], n_slots: int) -> tuple[list[tuple[int, int, int]], int]:
    """Greedy pair-CSE over the per-output term sets (mutated in place).

    Returns (temps, n_slots'): temps as (slot, src_a, src_b) in creation
    order.  Pair counts are maintained incrementally with a lazy max-heap,
    ties broken toward the lexicographically smallest pair so the same
    matrix always compiles to the identical program.
    """
    import heapq

    cnt: dict[tuple[int, int], int] = {}
    rows_of: dict[tuple[int, int], set[int]] = {}
    heap: list[tuple[int, int, int]] = []

    def bump(p: tuple[int, int], row: int) -> None:
        cnt[p] = cnt.get(p, 0) + 1
        rows_of.setdefault(p, set()).add(row)
        heapq.heappush(heap, (-cnt[p], p[0], p[1]))

    def drop(p: tuple[int, int], row: int) -> None:
        cnt[p] -= 1
        rows_of[p].discard(row)

    for ri, s in enumerate(sets):
        ss = sorted(s)
        for i in range(len(ss)):
            for j in range(i + 1, len(ss)):
                bump((ss[i], ss[j]), ri)

    temps: list[tuple[int, int, int]] = []
    while heap:
        negc, a, b = heapq.heappop(heap)
        p = (a, b)
        if cnt.get(p, 0) != -negc:
            continue  # stale heap entry
        if -negc < _GROUP_THRESHOLD:
            break
        t = n_slots
        n_slots += 1
        temps.append((t, a, b))
        for ri in sorted(rows_of[p]):
            s = sets[ri]
            if a not in s or b not in s:
                continue
            s.discard(a)
            s.discard(b)
            for x in s:
                drop(_pair(a, x), ri)
                drop(_pair(b, x), ri)
                bump(_pair(x, t), ri)
            drop(p, ri)
            s.add(t)
    return temps, n_slots


def _default_tile_sym() -> int:
    return config.env("WEEDTPU_XORSCHED_TILE_KB") * 1024


def _default_threads() -> int:
    # 0 = hardware concurrency (resolved by the native executor)
    return max(0, config.env("WEEDTPU_XORSCHED_THREADS"))


def compile_schedule(matrix: np.ndarray, tile_sym: Optional[int] = None) -> XorProgram:
    """Compile (uncached) — `get_schedule` is the memoized entry point."""
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    if m.ndim != 2 or 0 in m.shape:
        raise ValueError(f"want a non-empty 2-D GF matrix, got shape {m.shape}")
    if tile_sym is None:
        tile_sym = _default_tile_sym()
    tile_sym = max(512, (tile_sym // 512) * 512)  # SIMD transpose granularity
    bits = gf8.gf_matrix_to_bits(m)
    r8, c8 = bits.shape
    sets = [set(np.nonzero(bits[r])[0].tolist()) for r in range(r8)]
    raw_xors = sum(max(0, len(s) - 1) for s in sets)
    temps, n_slots = _group(sets, c8)
    out_base = n_slots
    ops: list[int] = []
    for t, a, b in temps:
        ops += [t, 2, a, b]
    for r in range(r8):
        ss = sorted(sets[r])
        ops += [out_base + r, len(ss)] + ss
    xor_count = len(temps) + sum(max(0, len(s) - 1) for s in sets)
    return XorProgram(
        rows=m.shape[0],
        cols=m.shape[1],
        n_slots=out_base + r8,
        out_base=out_base,
        ops=np.asarray(ops, dtype=np.int32),
        tile_sym=tile_sym,
        raw_xors=raw_xors,
        xor_count=xor_count,
        n_temps=len(temps),
    )


# ---------------------------------------------------------------------------
# Schedule LRU (mirrors rs_codec's decode-matrix memo, but with a cap that
# re-reads WEEDTPU_XORSCHED_CACHE on clear so tests can shrink it)
# ---------------------------------------------------------------------------

_cache_lock = threading.Lock()
_cache: "OrderedDict[tuple, XorProgram]" = OrderedDict()
_cache_cap: Optional[int] = None
_hits = 0
_misses = 0
_evictions = 0


def _cap() -> int:
    global _cache_cap
    if _cache_cap is None:
        _cache_cap = max(1, config.env("WEEDTPU_XORSCHED_CACHE"))
    return _cache_cap


def get_schedule(matrix: np.ndarray, tile_sym: Optional[int] = None) -> XorProgram:
    """The compiled program for (matrix bytes, tile geometry), LRU-cached."""
    global _hits, _misses, _evictions
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    if tile_sym is None:
        tile_sym = _default_tile_sym()
    key = (m.shape, m.tobytes(), tile_sym)
    with _cache_lock:
        prog = _cache.get(key)
        if prog is not None:
            _hits += 1
            _cache.move_to_end(key)
            return prog
        _misses += 1
    prog = compile_schedule(m, tile_sym)
    with _cache_lock:
        _cache[key] = prog
        _cache.move_to_end(key)
        while len(_cache) > _cap():
            _cache.popitem(last=False)
            _evictions += 1
    return prog


def schedule_cache_info() -> dict:
    with _cache_lock:
        return {
            "hits": _hits,
            "misses": _misses,
            "evictions": _evictions,
            "size": len(_cache),
            "cap": _cap(),
        }


def clear_schedule_cache() -> None:
    """Empty the LRU and re-read the cap knob (test hook, like
    rs_codec.clear_decode_matrix_cache)."""
    global _cache_cap, _hits, _misses, _evictions
    with _cache_lock:
        _cache.clear()
        _cache_cap = None
        _hits = _misses = _evictions = 0


# ---------------------------------------------------------------------------
# Numpy interpreter — the byte-exact oracle
# ---------------------------------------------------------------------------


def _to_planes(seg: np.ndarray) -> np.ndarray:
    """(C, w) bytes -> (8C, ceil(w/8)) packed bit-planes (little-endian:
    plane byte j bit k = bit i of symbol 8j+k)."""
    c, w = seg.shape
    pw8 = -(-w // 8) * 8
    if pw8 != w:
        seg = np.pad(seg, ((0, 0), (0, pw8 - w)))
    bits = np.unpackbits(seg, axis=1, bitorder="little").reshape(c, pw8, 8)
    planes = np.packbits(bits.transpose(0, 2, 1).reshape(c * 8, pw8), axis=1, bitorder="little")
    return planes


def _from_planes(planes: np.ndarray, w: int) -> np.ndarray:
    """(8R, pw) packed bit-planes -> (R, w) bytes (inverse of _to_planes)."""
    r8, pw = planes.shape
    bits = np.unpackbits(planes, axis=1, bitorder="little").reshape(r8 // 8, 8, pw * 8)
    out = np.packbits(bits.transpose(0, 2, 1), axis=2, bitorder="little")[:, :, 0]
    return np.ascontiguousarray(out[:, :w])


def apply(prog: XorProgram, inputs: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Run the schedule with numpy bulk XOR — tile loop, packed planes."""
    if len(inputs) != prog.cols:
        raise ValueError(f"program wants {prog.cols} inputs, got {len(inputs)}")
    ins = [np.ascontiguousarray(np.frombuffer(i, dtype=np.uint8)) if not isinstance(i, np.ndarray)
           else np.ascontiguousarray(i, dtype=np.uint8) for i in inputs]
    n = ins[0].shape[0]
    for i in ins:
        if i.shape[0] != n:
            raise ValueError("input shards differ in length")
    outs = [np.empty(n, dtype=np.uint8) for _ in range(prog.rows)]
    ops = prog.ops
    for off in range(0, n, prog.tile_sym):
        w = min(prog.tile_sym, n - off)
        pw = -(-w // 8)
        seg = np.stack([i[off:off + w] for i in ins])
        slots = np.zeros((prog.n_slots, pw), dtype=np.uint8)
        slots[: prog.cols * 8] = _to_planes(seg)
        k = 0
        while k < len(ops):
            dest, nsrc = int(ops[k]), int(ops[k + 1])
            k += 2
            if nsrc:
                srcs = ops[k:k + nsrc]
                k += nsrc
                np.bitwise_xor.reduce(slots[srcs], axis=0, out=slots[dest])
        res = _from_planes(slots[prog.out_base:], w)
        for r in range(prog.rows):
            outs[r][off:off + w] = res[r]
    return outs


# ---------------------------------------------------------------------------
# Native executor binding (version-probed: an old libweedtpu.so without the
# entry point must fall back to the interpreter, never crash)
# ---------------------------------------------------------------------------


def native_available() -> bool:
    from seaweedfs_tpu.utils import native as native_mod

    lib = native_mod.load()
    return bool(lib is not None and hasattr(lib, "weedtpu_xor_schedule_apply"))


def native_level() -> str:
    """SIMD level the native executor would run at: gfni | avx2 | scalar |
    unavailable (library or symbol missing)."""
    from seaweedfs_tpu.utils import native as native_mod

    lib = native_mod.load()
    if lib is None or not hasattr(lib, "weedtpu_xorsched_level"):
        return "unavailable"
    return {2: "gfni", 1: "avx2"}.get(int(lib.weedtpu_xorsched_level()), "scalar")


def _coerce_inputs(prog: XorProgram, inputs: Sequence[np.ndarray]) -> tuple[list[np.ndarray], int]:
    ins = [np.ascontiguousarray(np.frombuffer(i, dtype=np.uint8)) if not isinstance(i, np.ndarray)
           else np.ascontiguousarray(i, dtype=np.uint8) for i in inputs]
    if len(ins) != prog.cols:
        raise ValueError(f"program wants {prog.cols} inputs, got {len(ins)}")
    n = ins[0].shape[0]
    for i in ins:
        if i.shape[0] != n:
            raise ValueError("input shards differ in length")
    return ins, n


def _native_apply_blocks(
    lib,
    progs: Sequence[XorProgram],
    ins_per_block: Sequence[Sequence[np.ndarray]],
    outs_per_block: Sequence[Sequence[np.ndarray]],
    lens: Sequence[int],
    tile_sym: int,
    threads: int,
) -> bool:
    """Marshal the parallel block arrays for `weedtpu_xor_schedule_apply_blocks`.
    Returns False when the call is rejected (caller falls back)."""
    nb = len(progs)
    sched = np.concatenate([np.ascontiguousarray(p.ops, dtype=np.int32) for p in progs])
    sched_off = np.zeros(nb, dtype=np.uint64)
    sched_words = np.asarray([p.ops.shape[0] for p in progs], dtype=np.uint64)
    np.cumsum(sched_words[:-1], out=sched_off[1:])
    n_slots = np.asarray([p.n_slots for p in progs], dtype=np.uint32)
    in_planes = np.asarray([p.cols * 8 for p in progs], dtype=np.uint32)
    out_base = np.asarray([p.out_base for p in progs], dtype=np.uint32)
    out_planes = np.asarray([p.rows * 8 for p in progs], dtype=np.uint32)
    ins_off = np.zeros(nb, dtype=np.uint64)
    in_counts = np.asarray([len(b) for b in ins_per_block], dtype=np.uint64)
    np.cumsum(in_counts[:-1], out=ins_off[1:])
    outs_off = np.zeros(nb, dtype=np.uint64)
    out_counts = np.asarray([len(b) for b in outs_per_block], dtype=np.uint64)
    np.cumsum(out_counts[:-1], out=outs_off[1:])
    flat_ins = [a for b in ins_per_block for a in b]
    flat_outs = [a for b in outs_per_block for a in b]
    InArr = ctypes.c_char_p * len(flat_ins)
    OutArr = ctypes.c_void_p * len(flat_outs)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    rc = lib.weedtpu_xor_schedule_apply_blocks(
        sched.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        sched_off.ctypes.data_as(u64p),
        sched_words.ctypes.data_as(u64p),
        n_slots.ctypes.data_as(u32p),
        in_planes.ctypes.data_as(u32p),
        out_base.ctypes.data_as(u32p),
        out_planes.ctypes.data_as(u32p),
        InArr(*[a.ctypes.data_as(ctypes.c_char_p) for a in flat_ins]),
        ins_off.ctypes.data_as(u64p),
        OutArr(*[a.ctypes.data_as(ctypes.c_void_p) for a in flat_outs]),
        outs_off.ctypes.data_as(u64p),
        np.asarray(lens, dtype=np.uint64).ctypes.data_as(u64p),
        ctypes.c_uint32(nb),
        ctypes.c_uint64(tile_sym),
        ctypes.c_uint32(threads),
    )
    return bool(rc)


def apply_native(
    prog: XorProgram,
    inputs: Sequence[np.ndarray],
    threads: Optional[int] = None,
) -> Optional[list[np.ndarray]]:
    """Run the schedule through libweedtpu.so; None when the library (or
    the xorsched entry point — stale .so) is unavailable.  threads > 1
    routes through the width-parallel blocks entry (n_blocks = 1); the
    default comes from WEEDTPU_XORSCHED_THREADS."""
    from seaweedfs_tpu.utils import native as native_mod

    lib = native_mod.load()
    if lib is None or not hasattr(lib, "weedtpu_xor_schedule_apply"):
        return None
    ins, n = _coerce_inputs(prog, inputs)
    # np.empty, not zeros: the backward transpose writes every output byte,
    # and the zeroing pass costs ~15% of the whole apply at these speeds
    outs = [np.empty(n, dtype=np.uint8) for _ in range(prog.rows)]
    if threads is None:
        threads = _default_threads()
    if threads != 1 and hasattr(lib, "weedtpu_xor_schedule_apply_blocks"):
        if _native_apply_blocks(lib, [prog], [ins], [outs], [n], prog.tile_sym, threads):
            return outs
        return None
    ops = np.ascontiguousarray(prog.ops, dtype=np.int32)
    InArr = ctypes.c_char_p * prog.cols
    OutArr = ctypes.c_void_p * prog.rows
    rc = lib.weedtpu_xor_schedule_apply(
        ops.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_uint64(ops.shape[0]),
        ctypes.c_uint32(prog.n_slots),
        ctypes.c_uint32(prog.cols * 8),
        ctypes.c_uint32(prog.out_base),
        ctypes.c_uint32(prog.rows * 8),
        InArr(*[i.ctypes.data_as(ctypes.c_char_p) for i in ins]),
        OutArr(*[o.ctypes.data_as(ctypes.c_void_p) for o in outs]),
        ctypes.c_uint64(n),
        ctypes.c_uint64(prog.tile_sym),
    )
    if not rc:
        return None
    return outs


def apply_blocks(
    progs: Sequence[XorProgram],
    inputs_per_block: Sequence[Sequence[np.ndarray]],
    threads: Optional[int] = None,
    outputs_per_block: Optional[Sequence[Sequence[np.ndarray]]] = None,
) -> list[list[np.ndarray]]:
    """Run a block-diagonal set of schedules as one stitched pass.

    Block g applies progs[g] to inputs_per_block[g]; blocks are mutually
    independent (disjoint columns of the fused decode), so the native
    executor walks one flat (block, width-tile) task list across
    `threads` workers (default WEEDTPU_XORSCHED_THREADS; tiles never
    share output bytes).  Falls back to the per-block interpreter when
    the native entry point is unavailable.  Byte-identical either way.
    Blocks may have different lengths but must share tile_sym.

    `outputs_per_block` lets the caller supply the destination arrays —
    e.g. contiguous row slices of one fused output matrix — which the
    native executor writes in place (zero-copy stitch); each must be a
    C-contiguous uint8 array of the block's input length.
    """
    if len(progs) != len(inputs_per_block):
        raise ValueError(f"{len(progs)} programs but {len(inputs_per_block)} input blocks")
    if not progs:
        return []
    tile_sym = progs[0].tile_sym
    for p in progs:
        if p.tile_sym != tile_sym:
            raise ValueError("all blocks must share tile_sym")
    if threads is None:
        threads = _default_threads()
    coerced: list[list[np.ndarray]] = []
    lens: list[int] = []
    for prog, inputs in zip(progs, inputs_per_block):
        ins, n = _coerce_inputs(prog, inputs)
        coerced.append(ins)
        lens.append(n)
    if outputs_per_block is None:
        outs = [[np.empty(n, dtype=np.uint8) for _ in range(p.rows)]
                for p, n in zip(progs, lens)]
    else:
        if len(outputs_per_block) != len(progs):
            raise ValueError(f"{len(progs)} programs but {len(outputs_per_block)} output blocks")
        outs = []
        for prog, block, n in zip(progs, outputs_per_block, lens):
            if len(block) != prog.rows:
                raise ValueError(f"program wants {prog.rows} outputs, got {len(block)}")
            for a in block:
                if not (isinstance(a, np.ndarray) and a.dtype == np.uint8
                        and a.flags.c_contiguous and a.shape == (n,)):
                    raise ValueError(
                        "outputs must be C-contiguous uint8 arrays matching the block length"
                    )
            outs.append(list(block))
    from seaweedfs_tpu.utils import native as native_mod

    lib = native_mod.load()
    if lib is not None and hasattr(lib, "weedtpu_xor_schedule_apply_blocks"):
        if _native_apply_blocks(lib, progs, coerced, outs, lens, tile_sym, threads):
            return outs
    for p, ins, block in zip(progs, coerced, outs):
        for dst, src in zip(block, apply(p, ins)):
            np.copyto(dst, src)
    return outs


def apply_matrix(matrix: np.ndarray, inputs: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Compile-and-run convenience: native executor when present, numpy
    interpreter otherwise.  Byte-identical either way."""
    prog = get_schedule(matrix)
    out = apply_native(prog, inputs)
    if out is not None:
        return out
    return apply(prog, inputs)
