"""Scan-chain throughput measurement — the ONE implementation of the
slope method shared by bench.py, scripts/kernel_sweep.py, and
scripts/device_window.py.

Method: jit a `lax.scan` of K chained applies into a single dispatch and
time K=1 vs K=8; the slope (t8-t1)/7 is the per-apply device time, with
the per-dispatch overhead (the ~65 ms axon tunnel RTT) cancelled out.
The xor-chain keeps every iteration data-dependent so XLA cannot hoist
or dedupe applies, while staying byte-reversible (cheap on the VPU).

Covers BOTH north-star shapes: encode ((B, C, N) -> (B, C+R, N) parity
append) and reconstruct ((B, C, N) survivor stack -> (B, W, N) decoded
shards) — `out_rows` names how many output rows the chain folds back
into the accumulator (W for a decode matrix, parity count for encode).
"""

from __future__ import annotations

import time


def scan_chain_gbps(
    encode_fn, data, data_bytes: int, iters: int = 3, out_rows: int = 4
) -> float:
    """Steady-state effective GB/s of `encode_fn` ((B, C, N) uint8 ->
    (B, R>=out_rows, N)) on device-resident `data`. `out_rows` is how many
    of the output's shard rows feed the xor chain (4 for RS(10+4) encode
    parity; len(wanted) for a fused decode matrix). Raises ValueError when
    timing noise swamps the slope — a non-positive slope is an invalid
    measurement, never a throughput."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    b, _c, n = data.shape

    def make_chain(k: int):
        @jax.jit
        def chain(d):
            def body(acc, i):
                return acc ^ encode_fn(d ^ i)[:, :out_rows, :], ()

            acc, _ = lax.scan(
                body,
                jnp.zeros((b, out_rows, n), jnp.uint8),
                jnp.arange(k, dtype=jnp.uint8),
            )
            return acc

        return chain

    def best_time(fn) -> float:
        jax.block_until_ready(fn(data))  # compile + warm
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(data))
            best = min(best, time.perf_counter() - t0)
        return best

    k1, k2 = 1, 8
    t1 = best_time(make_chain(k1))
    t2 = best_time(make_chain(k2))
    per = (t2 - t1) / (k2 - k1)
    if per <= 0:
        raise ValueError(f"slope not measurable: t({k1})={t1:.4f}s t({k2})={t2:.4f}s")
    return data_bytes / per / 1e9
