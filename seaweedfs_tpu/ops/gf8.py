"""GF(2^8) arithmetic core — host-side (numpy), the foundation of the RS codec.

Field: GF(2^8) with the primitive polynomial x^8 + x^4 + x^3 + x^2 + 1
(0x11D), generator alpha = 2 — the same field used by the reference's codec
dependency (klauspost/reedsolomon `galois.go` [VERIFY: reference mount empty,
see SURVEY.md §0]; upstream generates its tables from poly 0x1D low byte).

Everything here is tiny (tables, 14x14 matrices) and runs on the host; the bulk
data path lives in `rs_jax.py` / `rs_pallas.py` as MXU matmuls over the binary
lift produced by `gf_matrix_to_bits`.
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
FIELD_SIZE = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables for GF(2^8) with generator 2.

    exp is doubled (512 entries) so exp[log[a]+log[b]] needs no mod.
    """
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = -1  # log(0) undefined; sentinel
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# Full 256x256 multiplication table — used for host-side golden checks and for
# building decode matrices. ~64 KiB, negligible.
def _build_mul_table() -> np.ndarray:
    t = np.zeros((256, 256), dtype=np.uint8)
    for a in range(1, 256):
        la = GF_LOG[a]
        t[a, 1:] = GF_EXP[la + GF_LOG[1:256]]
    return t


GF_MUL_TABLE = _build_mul_table()


def gf_mul(a: int, b: int) -> int:
    """Multiply two field elements."""
    return int(GF_MUL_TABLE[a & 0xFF, b & 0xFF])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] - GF_LOG[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_exp(a: int, n: int) -> int:
    """a raised to the n-th power (klauspost `galExp` semantics)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8). a: (m,k), b: (k,n) uint8 -> (m,n) uint8."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    # products[i,j,l] = a[i,l] * b[l,j]; XOR-reduce over l
    prods = GF_MUL_TABLE[a[:, :, None], b[None, :, :]]  # (m,k,n)
    return np.bitwise_xor.reduce(prods, axis=1)


def gf_mat_vec(a: np.ndarray, x: np.ndarray) -> np.ndarray:
    """Matrix-vector product over GF(2^8) applied to byte arrays.

    a: (m,k) uint8 matrix; x: (k, ...) uint8 data -> (m, ...) uint8.
    Pure-numpy golden path (slow; used by tests and tiny host-side work).
    """
    a = np.asarray(a, dtype=np.uint8)
    x = np.asarray(x, dtype=np.uint8)
    out = np.zeros((a.shape[0],) + x.shape[1:], dtype=np.uint8)
    for i in range(a.shape[0]):
        acc = np.zeros(x.shape[1:], dtype=np.uint8)
        for l in range(a.shape[1]):
            c = a[i, l]
            if c:
                acc ^= GF_MUL_TABLE[c][x[l]]
        out[i] = acc
    return out


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(2^8) by Gauss-Jordan elimination.

    Mirrors the role of the reference codec's `matrix.Invert` +
    `inversion_tree.go` cache consumers [VERIFY]. Raises ValueError if singular.
    """
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError(f"not square: {m.shape}")
    aug = np.concatenate([m.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        # pivot
        pivot = None
        for r in range(col, n):
            if aug[r, col] != 0:
                pivot = r
                break
        if pivot is None:
            raise ValueError("singular matrix over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        # scale pivot row to 1
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = GF_MUL_TABLE[inv_p][aug[col]]
        # eliminate all other rows
        for r in range(n):
            if r != col and aug[r, col] != 0:
                aug[r] ^= GF_MUL_TABLE[int(aug[r, col])][aug[col]]
    return aug[:, n:].copy()


# ---------------------------------------------------------------------------
# Generator matrices
# ---------------------------------------------------------------------------


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """vm[r][c] = r^c — klauspost `vandermonde()` semantics [VERIFY]."""
    m = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            m[r, c] = gf_exp(r, c)
    return m


@functools.lru_cache(maxsize=64)
def build_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic generator matrix, klauspost/Backblaze default construction:
    Vandermonde(total, data) times the inverse of its top square — top `data`
    rows become identity, bottom rows are the parity generator.

    This is what `reedsolomon.New(10, 4)` (no options) uses, i.e. what the
    reference's `weed/storage/erasure_coding` relies on [VERIFY], so shards we
    write are byte-compatible with stock CPU nodes.
    """
    vm = vandermonde(total_shards, data_shards)
    top = vm[:data_shards, :data_shards]
    out = gf_mat_mul(vm, gf_mat_inv(top))
    out.setflags(write=False)
    return out


@functools.lru_cache(maxsize=64)
def build_matrix_cauchy(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic Cauchy matrix (klauspost `WithCauchyMatrix` semantics):
    identity on top; parity rows m[r][c] = 1/(r ^ c)."""
    m = np.zeros((total_shards, data_shards), dtype=np.uint8)
    for r in range(total_shards):
        for c in range(data_shards):
            if r < data_shards:
                m[r, c] = 1 if r == c else 0
            else:
                m[r, c] = gf_inv(r ^ c)
    m.setflags(write=False)
    return m


def generator_matrix(kind: str, data_shards: int, total_shards: int) -> np.ndarray:
    """Dispatch to the named systematic generator construction."""
    if kind == "vandermonde":
        return build_matrix(data_shards, total_shards)
    if kind == "cauchy":
        return build_matrix_cauchy(data_shards, total_shards)
    raise ValueError(f"unknown matrix kind {kind!r}")


def parity_matrix(data_shards: int, parity_shards: int, kind: str = "vandermonde") -> np.ndarray:
    """The (parity x data) block that maps data shards to parity shards."""
    g = generator_matrix(kind, data_shards, data_shards + parity_shards)
    return g[data_shards:]


# ---------------------------------------------------------------------------
# Binary (bit-plane) lift — the bridge from GF(2^8) to MXU int8 matmuls
# ---------------------------------------------------------------------------


def gf_const_to_bits(c: int) -> np.ndarray:
    """Lift multiplication-by-c to its 8x8 GF(2) matrix A_c.

    y = c*x is GF(2)-linear in the bits of x:  A_c[i, j] = bit i of (c * 2^j),
    with bit j meaning the coefficient of x^j (little-endian bit order).
    """
    a = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        prod = gf_mul(c, 1 << j)
        for i in range(8):
            a[i, j] = (prod >> i) & 1
    return a


def gf_project(coeffs: np.ndarray, stack: np.ndarray) -> np.ndarray:
    """Repair projection, host golden path: apply an (R, C) GF(2^8)
    coefficient matrix to a (C, N) survivor-byte stack -> (R, N).

    This is the survivor-side half of trace repair: a holder of C local
    survivor shards ships the R projected rows (R = number of shards
    being rebuilt) instead of C full slabs. Thin, named alias of
    `gf_mat_vec` so call sites read as repair math, not linear algebra."""
    return gf_mat_vec(coeffs, stack)


def gf_project_bits(coeffs: np.ndarray, stack: np.ndarray) -> np.ndarray:
    """`gf_project` through the GF(2)/GF(2^8) subfield lift: unpack the
    stack to little-endian bit-planes, multiply by the (8R, 8C) binary
    block matrix from `gf_matrix_to_bits`, reduce mod 2, repack.

    Byte-identical to `gf_project` by construction — it is the same
    GF(2)-linear map the MXU matmul path runs (SURVEY.md §7.2), kept here
    in numpy so the volume server's projection handler and the device
    kernels share one verified formulation."""
    coeffs = np.asarray(coeffs, dtype=np.uint8)
    stack = np.asarray(stack, dtype=np.uint8)
    r_n, c_n = coeffs.shape
    if stack.shape[0] != c_n:
        raise ValueError(f"stack rows {stack.shape[0]} != coeff cols {c_n}")
    b = gf_matrix_to_bits(coeffs)  # (8R, 8C) over GF(2)
    # (C, N) bytes -> (8C, N) little-endian bit-planes
    bits = np.unpackbits(stack, axis=0, bitorder="little").reshape(8 * c_n, -1)
    out_bits = (b.astype(np.uint32) @ bits.astype(np.uint32)) & 1
    return np.packbits(
        out_bits.astype(np.uint8).reshape(8 * r_n, -1), axis=0, bitorder="little"
    ).reshape(r_n, -1)


def gf_delta_parity(coeffs: np.ndarray, delta: np.ndarray) -> np.ndarray:
    """Small-write parity maintenance, host golden path: the parity rows'
    CHANGE when one data shard's bytes change.

    With generator column c = G_parity[:, d] and delta = old ⊕ new over the
    touched byte columns, GF(2^8) linearity gives

        parity' = parity ⊕ gf_delta_parity(c, delta)

    byte-exact vs re-encoding the whole stripe (the XOR-EC program-
    optimization family in PAPERS.md builds on exactly this identity —
    parity is linear in each data shard, so a small overwrite is a rank-1
    update, not a re-encode). coeffs: (P,) uint8; delta: (n,) uint8 ->
    (P, n) uint8 delta rows."""
    coeffs = np.asarray(coeffs, dtype=np.uint8).ravel()
    delta = np.asarray(delta, dtype=np.uint8).ravel()
    return GF_MUL_TABLE[coeffs[:, None], delta[None, :]]


def gf_matrix_to_bits(m: np.ndarray) -> np.ndarray:
    """Lift an (R, C) GF(2^8) matrix to its (R*8, C*8) GF(2) block matrix.

    Row r*8+i, col c*8+j: bit i of (m[r,c] * 2^j). With data bytes unpacked to
    little-endian bit-planes, `out_bits = (B @ in_bits) & 1` computes the exact
    GF(2^8) matrix-vector product — this is the matmul the MXU runs
    (SURVEY.md §7.2; PAPERS.md: arXiv:2108.02692, arXiv:1611.09968).
    """
    m = np.asarray(m, dtype=np.uint8)
    r_n, c_n = m.shape
    out = np.zeros((r_n * 8, c_n * 8), dtype=np.uint8)
    for r in range(r_n):
        for c in range(c_n):
            out[r * 8 : r * 8 + 8, c * 8 : c * 8 + 8] = gf_const_to_bits(int(m[r, c]))
    return out
