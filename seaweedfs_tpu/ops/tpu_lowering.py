"""TPU-target lowering proof for the fused Pallas kernel — no device needed.

The axon TPU tunnel has been wedged machine-wide since round 2, so the chip
itself is frequently unmeasurable. What CAN be proven without a device is
that `rs_pallas._kernel` lowers through Mosaic for the TPU target: Pallas
TPU lowering (StableHLO + serialized Mosaic module inside a
`tpu_custom_call`) runs at trace/lowering time via `jax.export`, and Mosaic
rejects unsupported patterns (layouts, reshapes, dtypes) right there —
interpret mode hides exactly this class of bug.

CAVEAT (environment): `jax.export(..., platforms=["tpu"])` hangs if the
axon PJRT plugin is importable, even under JAX_PLATFORMS=cpu — the plugin
initializes during platform resolution and blocks on the single-client
tunnel. Callers must run `export_fused_kernel` in a subprocess whose
PYTHONPATH excludes the axon site dir; `run_lowering_proof` does exactly
that. [ref: SURVEY.md §7.2; the reference's equivalent proof surface is its
amd64 assembler unit tests — klauspost galois_gen_amd64.s, mount empty]
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
from typing import Optional

# shape classes the storage engine actually hits (SURVEY §7.3.5):
#   encode:        RS(10+4) parity generation over large stripes, tile 8192
#   reconstruct:   4 lost shards from 10 survivors, tile 8192
#   small-read:    one-interval degraded read, minimum 128-byte tile
PROOF_SHAPES = (
    {"name": "encode_10p4_tile8192", "rows": 4, "cols": 10, "tile": 8192, "batch": 4},
    # the retuned defaults (auto_tile: VMEM-budget tiles + the bf16-MXU
    # variant) must lower through Mosaic too, or the sweep would be the
    # first place they ever hit the TPU toolchain
    {"name": "encode_10p4_tile32768", "rows": 4, "cols": 10, "tile": 32768, "batch": 4},
    {"name": "encode_10p4_tile24576_bf16", "rows": 4, "cols": 10, "tile": 24576,
     "batch": 4, "mxu": "bf16"},
    # the r6 staged variants (ROOFLINE_r05 verification plan): uint8-native
    # unpack, multi-plane accumulation, and the manual double-buffered DMA
    # streamer — each must lower through Mosaic BEFORE the sweep ever
    # dispatches it, or the first tunnel-alive window burns its budget on
    # compile failures instead of measurements
    {"name": "encode_10p4_tile32768_u8", "rows": 4, "cols": 10, "tile": 32768,
     "batch": 4, "mxu": "u8"},
    {"name": "encode_10p4_tile32768_mplane", "rows": 4, "cols": 10, "tile": 32768,
     "batch": 4, "mxu": "mplane"},
    {"name": "encode_10p4_tile65536_dma", "rows": 4, "cols": 10, "tile": 65536,
     "batch": 4, "mxu": "dma"},
    {"name": "reconstruct_4from10_tile32768_dma", "rows": 4, "cols": 10,
     "tile": 32768, "batch": 1, "mxu": "dma"},
    {"name": "reconstruct_4from10_tile8192", "rows": 4, "cols": 10, "tile": 8192, "batch": 1},
    {"name": "reconstruct_10from10_tile8192", "rows": 10, "cols": 10, "tile": 8192, "batch": 1},
    {"name": "small_read_tile128", "rows": 4, "cols": 10, "tile": 128, "batch": 1},
)


def export_fused_kernel(
    rows: int, cols: int, tile: int, batch: int = 1, mxu: str = "int8"
) -> tuple[str, dict]:
    """Lower `_apply_padded` for the TPU platform; return (MLIR text, meta).

    Raises whatever Mosaic raises if the kernel does not lower — that
    failure IS the signal this function exists to surface.
    """
    import jax
    import jax.export
    import jax.numpy as jnp
    import numpy as np

    from seaweedfs_tpu.ops import gf8, rs_jax, rs_pallas

    m = gf8.parity_matrix(cols, rows) if rows <= cols else None
    if m is None or m.shape != (rows, cols):
        # reconstruct-style matrices are arbitrary (rows, cols) GF matrices;
        # any valid GF matrix exercises the same kernel — build one
        rng = np.random.default_rng(1)
        m = rng.integers(1, 256, size=(rows, cols), dtype=np.uint8)
    b_bits = rs_jax.lifted_matrix(m)
    n = tile * 2

    fn = lambda b, d: rs_pallas._apply_padded(b, d, tile, False, mxu)  # noqa: E731
    args = (
        jax.ShapeDtypeStruct(b_bits.shape, jnp.int8),
        jax.ShapeDtypeStruct((batch, cols, n), jnp.uint8),
    )
    exported = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    mlir = exported.mlir_module()
    meta = {
        "rows": rows,
        "cols": cols,
        "tile": tile,
        "batch": batch,
        "mxu": mxu,
        "n": n,
        "platforms": list(exported.platforms),
        "mlir_bytes": len(mlir),
        "has_tpu_custom_call": "tpu_custom_call" in mlir,
        "mlir_sha256": hashlib.sha256(mlir.encode()).hexdigest(),
        "jax_version": jax.__version__,
    }
    return mlir, meta


def _scrubbed_env() -> dict:
    """Subprocess env with the axon site dir off PYTHONPATH and cpu pinned."""
    env = dict(os.environ)
    parts = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    ]
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if repo_root not in parts:
        parts.insert(0, repo_root)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    env["JAX_PLATFORMS"] = "cpu"
    return env


_CHILD_CODE = """
import json, sys
from seaweedfs_tpu.ops import tpu_lowering
out = []
for spec in tpu_lowering.PROOF_SHAPES:
    name = spec["name"]
    try:
        mlir, meta = tpu_lowering.export_fused_kernel(
            spec["rows"], spec["cols"], spec["tile"], spec["batch"],
            spec.get("mxu", "int8"))
        meta["name"] = name
        meta["ok"] = meta["has_tpu_custom_call"]
        out.append(meta)
        dirpath = sys.argv[1] if len(sys.argv) > 1 else ""
        if dirpath:
            with open(f"{dirpath}/{name}.tpu.mlir", "w") as f:
                f.write(mlir)
    except Exception as e:
        out.append({"name": name, "ok": False, "error": str(e)[:500]})
print(json.dumps(out))
"""


def run_lowering_proof(
    artifact_dir: Optional[str] = None, timeout: int = 600
) -> list[dict]:
    """Run the full proof suite in a scrubbed subprocess; optionally write
    the lowered .mlir artifacts to `artifact_dir`. Returns per-shape meta
    (ok/error per shape; the subprocess itself failing yields one entry)."""
    if artifact_dir:
        os.makedirs(artifact_dir, exist_ok=True)
    cmd = [sys.executable, "-c", _CHILD_CODE] + ([artifact_dir] if artifact_dir else [])
    try:
        proc = subprocess.run(
            cmd,
            env=_scrubbed_env(),
            timeout=timeout,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
    except subprocess.TimeoutExpired:
        return [{"name": "suite", "ok": False, "error": f"timeout after {timeout}s"}]
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("["):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    err = proc.stderr.decode(errors="replace")[-500:]
    return [{"name": "suite", "ok": False, "error": f"exit={proc.returncode}: {err}"}]
