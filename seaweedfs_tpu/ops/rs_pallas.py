"""Fused Pallas TPU kernel for GF(2^8) coding — the performance path.

The pure-XLA route (rs_jax.gf_apply) materializes the 8x bit-plane expansion
and an int32 accumulator in HBM; this kernel keeps both in VMEM:

    per grid step (one batch element x one stripe tile of T bytes):
      load   data tile (C, T) uint8                  HBM -> VMEM
      unpack bits (8*C, T) int8, PLANE-major         VPU (block concat — no
             (row j*C+ci = bit j of byte-row ci)     per-byte interleave;
                                                     B's columns are pre-
                                                     permuted to match)
      matmul acc = B_pm @ bits -> (R*8, T) int32     MXU
      mod-2  acc & 1
      pack   out[r] = sum_i acc[r*8+i] << i          VPU (7 shifted ORs —
                                                     cheaper than a tiny
                                                     M=R pack-matmul)
    store  out tile (R, T)                           VMEM -> HBM

HBM traffic is exactly C+R bytes/byte-position — the algorithmic minimum —
vs ~(9C + 5R) for the unfused path. Replaces the reference codec's AVX2/GFNI
galois kernels (klauspost/reedsolomon galois_gen_amd64.s [VERIFY: mount
empty]) as SURVEY.md §2.2 prescribes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.ops import gf8

# bytes of one stripe tile per grid step; 8 KiB x (C*8) bits stays well under
# VMEM while giving the MXU a wide N dimension
DEFAULT_TILE = 8192


def _kernel(b_ref, data_ref, out_ref):
    data = data_ref[0]  # (C, T) uint8
    c, t = data.shape
    # Plane-major bit layout ON BOTH SIDES (ROOFLINE_r05.md hyps 1+3):
    #   input  row j*C + ci = bit j of input byte-row ci
    #   output row i*R + r  = bit i of output byte-row r
    # Concatenating whole (C, T) blocks keeps every plane in its natural
    # VMEM layout — a byte-major stack(axis=1).reshape forces a per-byte
    # sublane interleave Mosaic must shuffle for. The lifted matrix's
    # columns AND rows are pre-permuted host-side to match (free). The
    # unpack shifts uint8 directly: an int32 widen quadruples the VMEM
    # working set and costs a relayout before the shifts.
    bits = jnp.concatenate(
        [((data >> j) & 1) for j in range(8)], axis=0
    ).astype(jnp.int8)
    acc = jax.lax.dot_general(
        b_ref[...],
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    acc = acc & 1  # (8*R, T), rows i*R + r — plane-major
    # pack on the VPU: out[r] = sum_i acc[i*R + r] << i. With plane-major
    # rows each acc3[i] is a CONTIGUOUS (R, T) block (sublane stride 1);
    # the old byte-major pack read with sublane stride 8, which Mosaic
    # lowered to per-sublane shuffles.
    rows8, _ = acc.shape
    acc3 = acc.reshape(8, rows8 // 8, t)
    out = acc3[0]
    for i in range(1, 8):
        out = out | (acc3[i] << i)
    out_ref[0] = out.astype(jnp.uint8)


def _plane_major_columns(b_bits: np.ndarray) -> np.ndarray:
    """Permute the lifted matrix's columns from byte-major (ci*8 + j) to
    plane-major (j*C + ci), AND its rows from byte-major (r*8 + i) to
    plane-major (i*R + r) — both sides of the kernel's bit layout."""
    rows8, cols8 = b_bits.shape
    c = cols8 // 8
    r = rows8 // 8
    col_perm = [(k % c) * 8 + (k // c) for k in range(cols8)]
    row_perm = [(k % r) * 8 + (k // r) for k in range(rows8)]
    return np.asarray(b_bits)[np.ix_(row_perm, col_perm)]


def _on_tpu() -> bool:
    from seaweedfs_tpu.utils.devices import is_tpu_device

    return is_tpu_device(jax.devices()[0])


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _apply_padded(b_pm, data, tile: int, interpret: bool):
    batch, c, n = data.shape
    rows = b_pm.shape[0] // 8
    grid = (batch, n // tile)
    kwargs = {}
    if not interpret:
        # every grid step is independent (disjoint tiles): telling Mosaic
        # so unlocks unconstrained pipelining of the HBM<->VMEM windows
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")
        )
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b_pm.shape[0], b_pm.shape[1]), lambda b, i: (0, 0)),
            pl.BlockSpec((1, c, tile), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, rows, tile), lambda b, i: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((batch, rows, n), jnp.uint8),
        interpret=interpret,
        **kwargs,
    )(b_pm, data)


def _apply_pm(b_pm: jax.Array, data: jax.Array, tile: int) -> jax.Array:
    """Shared pad/tile/squeeze plumbing over an already-plane-major matrix."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    batch, c, n = data.shape
    rows = b_pm.shape[0] // 8
    if n == 0:
        out = jnp.zeros((batch, rows, 0), jnp.uint8)
        return out[0] if squeeze else out
    t = min(tile, _round_up(max(n, 128), 128))
    n_pad = _round_up(n, t)
    if n_pad != n:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, n_pad - n)))
    out = _apply_padded(b_pm, data, t, not _on_tpu())
    if n_pad != n:
        out = out[..., :n]
    return out[0] if squeeze else out


def gf_apply_fused(b_bits: jax.Array, data: jax.Array, tile: int = DEFAULT_TILE) -> jax.Array:
    """Fused equivalent of rs_jax.gf_apply for TPU.

    b_bits: (R*8, C*8) int8 lifted matrix; data (C, N) or (B, C, N) uint8.
    Handles any N by zero-padding to the tile size (zero bytes encode to
    zero bytes, so padding never corrupts real lanes). Off-TPU the kernel
    runs in Pallas interpret mode so the exact kernel logic stays testable
    on the CPU mesh.
    """
    return _apply_pm(_lifted_plane_major(b_bits), data, tile)


@functools.lru_cache(maxsize=256)
def _plane_major_cached(key) -> jax.Array:
    rows8, cols8, flat = key
    arr = np.frombuffer(bytes(flat), dtype=np.int8).reshape(rows8, cols8)
    return jnp.asarray(_plane_major_columns(arr))


@functools.lru_cache(maxsize=256)
def _lift_pm_cached(key) -> jax.Array:
    rows, cols, flat = key
    m = np.frombuffer(bytes(flat), dtype=np.uint8).reshape(rows, cols)
    lifted = gf8.gf_matrix_to_bits(m).astype(np.int8)
    return jnp.asarray(_plane_major_columns(lifted))


def plane_major_matrix(m: np.ndarray) -> jax.Array:
    """Host-side: lifted + column-permuted device matrix for the kernel,
    cached by GF-matrix value — both the bit-lift (Python GF math) and the
    permutation happen once per matrix, and the hot path (apply_matrix)
    never round-trips an already-uploaded matrix through the host."""
    a = np.asarray(m, dtype=np.uint8)
    return _lift_pm_cached((a.shape[0], a.shape[1], a.tobytes()))


# id-keyed memo for the b_bits (device array) compat path: np.asarray on a
# device array is a blocking D2H transfer — ~65 ms through the axon tunnel —
# so it must happen once per matrix object, not once per call. Entries
# self-evict when their source array is collected (weakref callback), so
# the memo cannot pin dead device buffers for the life of the process.
_pm_by_id: dict[int, tuple] = {}


def _lifted_plane_major(b_bits) -> jax.Array:
    import weakref

    k = id(b_bits)
    hit = _pm_by_id.get(k)
    if hit is not None and hit[0]() is b_bits:
        return hit[1]
    a = np.asarray(b_bits, dtype=np.int8)
    pm = _plane_major_cached((a.shape[0], a.shape[1], a.tobytes()))
    try:
        ref = weakref.ref(b_bits, lambda _r, _k=k: _pm_by_id.pop(_k, None))
        _pm_by_id[k] = (ref, pm)
    except TypeError:  # non-weakrefable input (plain ndarray): value cache hit anyway
        pass
    return pm


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def apply_matrix(m: np.ndarray, shards, tile: int = DEFAULT_TILE) -> jax.Array:
    """GF(2^8) matrix application via the fused kernel: the hot path —
    lift + permute host-side once per matrix value, no device round-trip."""
    return _apply_pm(plane_major_matrix(m), jnp.asarray(shards), tile)
