"""Fused Pallas TPU kernel for GF(2^8) coding — the performance path.

The pure-XLA route (rs_jax.gf_apply) materializes the 8x bit-plane expansion
and an int32 accumulator in HBM; this kernel keeps both in VMEM:

    per grid step (one batch element x one stripe tile of T bytes):
      load   data tile (C, T) uint8                  HBM -> VMEM
      unpack bits (8*C, T) int8, PLANE-major         VPU (block concat — no
             (row j*C+ci = bit j of byte-row ci)     per-byte interleave;
                                                     B's columns are pre-
                                                     permuted to match)
      matmul acc = B_pm @ bits -> (R*8, T) int32     MXU
      mod-2  acc & 1
      pack   out[r] = sum_i acc[r*8+i] << i          VPU (7 shifted ORs —
                                                     cheaper than a tiny
                                                     M=R pack-matmul)
    store  out tile (R, T)                           VMEM -> HBM

HBM traffic is exactly C+R bytes/byte-position — the algorithmic minimum —
vs ~(9C + 5R) for the unfused path. Replaces the reference codec's AVX2/GFNI
galois kernels (klauspost/reedsolomon galois_gen_amd64.s [VERIFY: mount
empty]) as SURVEY.md §2.2 prescribes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.ops import gf8

# bytes of one stripe tile per grid step; 8 KiB x (C*8) bits stays well under
# VMEM while giving the MXU a wide N dimension. Kept as the floor of the
# retuned auto chooser below — ROOFLINE_r05 hyp 4: at 8 KiB tiles the
# per-grid-step overhead (semaphores, window swaps) is material, so the
# default now scales the tile up to the VMEM budget instead.
DEFAULT_TILE = 8192

#: VMEM the auto tile chooser may plan against. Half the v5e core's ~16 MiB
#: so Mosaic retains room to double-buffer the HBM<->VMEM windows.
DEFAULT_VMEM_BUDGET = 8 << 20

#: snap grid for auto tiles — large power-of-two-ish strides keep the
#: HBM windows aligned and the grid-step count predictable
_TILE_STEPS = (65536, 49152, 32768, 24576, 16384, 8192, 4096, 2048, 1024, 512, 256, 128)


def auto_tile(
    c: int, rows: int, mxu: str = "int8", vmem_budget: int = DEFAULT_VMEM_BUDGET
) -> int:
    """Largest tile whose per-grid-step VMEM working set fits the budget.

    Working set per byte-position of tile: data window (double-buffered,
    2C) + bit-plane expansion (8C at the MXU dtype's width) + int32
    accumulator (32R) + output window (double-buffered, 2R)."""
    bits_width = 2 if mxu == "bf16" else 1
    per_byte = 2 * c + 8 * c * bits_width + 32 * rows + 2 * rows
    cap = max(128, vmem_budget // per_byte)
    for t in _TILE_STEPS:
        if t <= cap:
            return t
    return 128


def _kernel(b_ref, data_ref, out_ref):
    data = data_ref[0]  # (C, T) uint8
    c, t = data.shape
    # Plane-major bit layout ON BOTH SIDES (ROOFLINE_r05.md hyps 1+3):
    #   input  row j*C + ci = bit j of input byte-row ci
    #   output row i*R + r  = bit i of output byte-row r
    # Concatenating whole (C, T) blocks keeps every plane in its natural
    # VMEM layout — a byte-major stack(axis=1).reshape forces a per-byte
    # sublane interleave Mosaic must shuffle for. The lifted matrix's
    # columns AND rows are pre-permuted host-side to match (free). The
    # unpack shifts int8 (same width as the bytes, so no VMEM inflation):
    # Mosaic has no uint8 shift lowering, and (x >> j) & 1 extracts bit j
    # under arithmetic shift exactly as under logical shift for j < 8.
    di = data.astype(jnp.int8)
    bits = jnp.concatenate([((di >> j) & 1) for j in range(8)], axis=0)
    acc = jax.lax.dot_general(
        b_ref[...],
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    acc = acc & 1  # (8*R, T), rows i*R + r — plane-major
    # pack on the VPU: out[r] = sum_i acc[i*R + r] << i. With plane-major
    # rows each acc3[i] is a CONTIGUOUS (R, T) block (sublane stride 1);
    # the old byte-major pack read with sublane stride 8, which Mosaic
    # lowered to per-sublane shuffles.
    rows8, _ = acc.shape
    acc3 = acc.reshape(8, rows8 // 8, t)
    out = acc3[0]
    for i in range(1, 8):
        out = out | (acc3[i] << i)
    out_ref[0] = out.astype(jnp.uint8)


def _kernel_bf16(b_ref, data_ref, out_ref):
    """Same plane-major layout as `_kernel`, but the MXU matmul runs in
    bf16: products are 0/1 and K = C*8 <= 80 for RS(10+4), so every partial
    sum <= 80 < 256 is exactly representable in bf16's 8-bit significand
    (f32 accumulate is exact a fortiori) — int8 matmul on some TPU
    generations is emulated at a fraction of bf16 rate, so this can win.
    Promoted from scripts/kernel_sweep.py so production can select it."""
    data = data_ref[0]
    di = data.astype(jnp.int8)  # int8 unpack: see _kernel
    bits = jnp.concatenate(
        [((di >> j) & 1) for j in range(8)], axis=0
    ).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        b_ref[...].astype(jnp.bfloat16),
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    acc = acc & 1
    rows8, t = acc.shape
    acc3 = acc.reshape(8, rows8 // 8, t)
    out = acc3[0]
    for i in range(1, 8):
        out = out | (acc3[i] << i)
    out_ref[0] = out.astype(jnp.uint8)


_KERNELS = {"int8": _kernel, "bf16": _kernel_bf16}


def _plane_major_columns(b_bits: np.ndarray) -> np.ndarray:
    """Permute the lifted matrix's columns from byte-major (ci*8 + j) to
    plane-major (j*C + ci), AND its rows from byte-major (r*8 + i) to
    plane-major (i*R + r) — both sides of the kernel's bit layout."""
    rows8, cols8 = b_bits.shape
    c = cols8 // 8
    r = rows8 // 8
    col_perm = [(k % c) * 8 + (k // c) for k in range(cols8)]
    row_perm = [(k % r) * 8 + (k // r) for k in range(rows8)]
    return np.asarray(b_bits)[np.ix_(row_perm, col_perm)]


def _on_tpu() -> bool:
    from seaweedfs_tpu.utils.devices import is_tpu_device

    return is_tpu_device(jax.devices()[0])


def _apply_padded_impl(b_pm, data, tile: int, interpret: bool, mxu: str):
    batch, c, n = data.shape
    rows = b_pm.shape[0] // 8
    grid = (batch, n // tile)
    kwargs = {}
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if not interpret and params_cls is not None:
        # every grid step is independent (disjoint tiles): telling Mosaic
        # so unlocks unconstrained pipelining of the HBM<->VMEM windows
        kwargs["compiler_params"] = params_cls(
            dimension_semantics=("parallel", "parallel")
        )
    return pl.pallas_call(
        _KERNELS[mxu],
        grid=grid,
        in_specs=[
            pl.BlockSpec((b_pm.shape[0], b_pm.shape[1]), lambda b, i: (0, 0)),
            pl.BlockSpec((1, c, tile), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, rows, tile), lambda b, i: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((batch, rows, n), jnp.uint8),
        interpret=interpret,
        **kwargs,
    )(b_pm, data)


_STATIC = ("tile", "interpret", "mxu")
_apply_padded_jit = jax.jit(_apply_padded_impl, static_argnames=_STATIC)
# donated twin: the (large) data buffer's HBM is released as soon as the
# dispatch consumes it — an early-release hint, not output aliasing (the
# (B, C, N) input cannot alias the smaller (B, R, N) output; see the
# rs_jax donated-twin note). No-op + warning on CPU, so callers gate on
# rs_jax.donation_supported().
_apply_padded_donated = jax.jit(
    _apply_padded_impl, static_argnames=_STATIC, donate_argnums=(1,)
)


def _apply_padded(b_pm, data, tile: int, interpret: bool, mxu: str = "int8"):
    """Compat shim (tpu_lowering exports through this name)."""
    return _apply_padded_jit(b_pm, data, tile, interpret, mxu)


def _apply_pm(
    b_pm: jax.Array,
    data: jax.Array,
    tile: int | None,
    mxu: str = "int8",
    donate: bool = False,
) -> jax.Array:
    """Shared pad/tile/squeeze plumbing over an already-plane-major matrix."""
    if mxu not in _KERNELS:
        raise ValueError(f"unknown mxu dtype {mxu!r} (want {sorted(_KERNELS)})")
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    batch, c, n = data.shape
    rows = b_pm.shape[0] // 8
    if n == 0:
        out = jnp.zeros((batch, rows, 0), jnp.uint8)
        return out[0] if squeeze else out
    if tile is None:
        tile = auto_tile(c, rows, mxu)
    t = min(tile, _round_up(max(n, 128), 128))
    n_pad = _round_up(n, t)
    if n_pad != n:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, n_pad - n)))
    if donate:
        from seaweedfs_tpu.ops import rs_jax

        if rs_jax.donation_supported():
            out = _apply_padded_donated(
                b_pm, jax.device_put(data), t, not _on_tpu(), mxu
            )
            if n_pad != n:
                out = out[..., :n]
            return out[0] if squeeze else out
    out = _apply_padded_jit(b_pm, data, t, not _on_tpu(), mxu)
    if n_pad != n:
        out = out[..., :n]
    return out[0] if squeeze else out


def gf_apply_fused(
    b_bits: jax.Array,
    data: jax.Array,
    tile: int | None = None,
    mxu: str = "int8",
) -> jax.Array:
    """Fused equivalent of rs_jax.gf_apply for TPU.

    b_bits: (R*8, C*8) int8 lifted matrix; data (C, N) or (B, C, N) uint8.
    Handles any N by zero-padding to the tile size (zero bytes encode to
    zero bytes, so padding never corrupts real lanes). Off-TPU the kernel
    runs in Pallas interpret mode so the exact kernel logic stays testable
    on the CPU mesh. tile=None picks the largest tile whose working set
    fits the VMEM budget (`auto_tile`); mxu selects the matmul dtype
    ("int8" or the exact-by-range "bf16" variant).
    """
    return _apply_pm(_lifted_plane_major(b_bits), data, tile, mxu)


@functools.lru_cache(maxsize=256)
def _plane_major_cached(key) -> jax.Array:
    rows8, cols8, flat = key
    arr = np.frombuffer(bytes(flat), dtype=np.int8).reshape(rows8, cols8)
    return jnp.asarray(_plane_major_columns(arr))


@functools.lru_cache(maxsize=256)
def _lift_pm_cached(key) -> jax.Array:
    rows, cols, flat = key
    m = np.frombuffer(bytes(flat), dtype=np.uint8).reshape(rows, cols)
    lifted = gf8.gf_matrix_to_bits(m).astype(np.int8)
    return jnp.asarray(_plane_major_columns(lifted))


def plane_major_matrix(m: np.ndarray) -> jax.Array:
    """Host-side: lifted + column-permuted device matrix for the kernel,
    cached by GF-matrix value — both the bit-lift (Python GF math) and the
    permutation happen once per matrix, and the hot path (apply_matrix)
    never round-trips an already-uploaded matrix through the host."""
    a = np.asarray(m, dtype=np.uint8)
    return _lift_pm_cached((a.shape[0], a.shape[1], a.tobytes()))


# id-keyed memo for the b_bits (device array) compat path: np.asarray on a
# device array is a blocking D2H transfer — ~65 ms through the axon tunnel —
# so it must happen once per matrix object, not once per call. Entries
# self-evict when their source array is collected (weakref callback), so
# the memo cannot pin dead device buffers for the life of the process.
_pm_by_id: dict[int, tuple] = {}


def _lifted_plane_major(b_bits) -> jax.Array:
    import weakref

    k = id(b_bits)
    hit = _pm_by_id.get(k)
    if hit is not None and hit[0]() is b_bits:
        return hit[1]
    a = np.asarray(b_bits, dtype=np.int8)
    pm = _plane_major_cached((a.shape[0], a.shape[1], a.tobytes()))
    try:
        ref = weakref.ref(b_bits, lambda _r, _k=k: _pm_by_id.pop(_k, None))
        _pm_by_id[k] = (ref, pm)
    except TypeError:  # non-weakrefable input (plain ndarray): value cache hit anyway
        pass
    return pm


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def apply_matrix(
    m: np.ndarray,
    shards,
    tile: int | None = None,
    mxu: str = "int8",
    donate: bool = False,
) -> jax.Array:
    """GF(2^8) matrix application via the fused kernel: the hot path —
    lift + permute host-side once per matrix value, no device round-trip.
    donate=True releases the input's device buffer at dispatch-consume
    time (streaming pipelines; ignored on CPU where donation is a no-op)."""
    return _apply_pm(
        plane_major_matrix(m), jnp.asarray(shards), tile, mxu, donate=donate
    )
