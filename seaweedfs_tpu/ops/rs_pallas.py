"""Fused Pallas TPU kernel for GF(2^8) coding — the performance path.

The pure-XLA route (rs_jax.gf_apply) materializes the 8x bit-plane expansion
and an int32 accumulator in HBM; this kernel keeps both in VMEM:

    per grid step (one batch element x one stripe tile of T bytes):
      load   data tile (C, T) uint8                  HBM -> VMEM
      unpack bits (C*8, T) int8 via shift/mask       VPU, VMEM-resident
      matmul acc = B @ bits -> (R*8, T) int32        MXU
      mod-2  acc & 1
      pack   out = PACK @ acc -> (R, T) uint8        MXU (packing is linear:
                                                     PACK[r, r*8+i] = 2^i)
    store  out tile (R, T)                           VMEM -> HBM

HBM traffic is exactly C+R bytes/byte-position — the algorithmic minimum —
vs ~(9C + 5R) for the unfused path. Replaces the reference codec's AVX2/GFNI
galois kernels (klauspost/reedsolomon galois_gen_amd64.s [VERIFY: mount
empty]) as SURVEY.md §2.2 prescribes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.ops import gf8

# bytes of one stripe tile per grid step; 8 KiB x (C*8) bits stays well under
# VMEM while giving the MXU a wide N dimension
DEFAULT_TILE = 8192


def _kernel(b_ref, pack_ref, data_ref, out_ref):
    data = data_ref[0]  # (C, T) uint8
    c, t = data.shape
    # unrolled bit-plane extraction, widened to int32 (Mosaic has no 8-bit
    # iota or shifts)
    wide = data.astype(jnp.int32)
    planes = [((wide >> j) & 1) for j in range(8)]
    bits = jnp.stack(planes, axis=1).reshape(c * 8, t).astype(jnp.int8)
    acc = jax.lax.dot_general(
        b_ref[...],
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    acc = (acc & 1).astype(jnp.float32)
    # pack via a second (tiny, f32) MXU matmul — packing is linear and every
    # value is an exact small integer, so f32 is exact
    packed = jax.lax.dot_general(
        pack_ref[...],
        acc,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    out_ref[0] = packed.astype(jnp.int32).astype(jnp.uint8)


def _pack_matrix(rows: int) -> np.ndarray:
    """(R, R*8) int32: PACK[r, r*8+i] = 1 << i (little-endian bit packing)."""
    p = np.zeros((rows, rows * 8), dtype=np.float32)
    for r in range(rows):
        for i in range(8):
            p[r, r * 8 + i] = 1 << i
    return p


def _on_tpu() -> bool:
    from seaweedfs_tpu.utils.devices import is_tpu_device

    return is_tpu_device(jax.devices()[0])


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _apply_padded(b_bits, pack, data, tile: int, interpret: bool):
    batch, c, n = data.shape
    rows = pack.shape[0]
    grid = (batch, n // tile)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b_bits.shape[0], b_bits.shape[1]), lambda b, i: (0, 0)),
            pl.BlockSpec((rows, rows * 8), lambda b, i: (0, 0)),
            pl.BlockSpec((1, c, tile), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, rows, tile), lambda b, i: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((batch, rows, n), jnp.uint8),
        interpret=interpret,
    )(b_bits, pack, data)


def gf_apply_fused(b_bits: jax.Array, data: jax.Array, tile: int = DEFAULT_TILE) -> jax.Array:
    """Fused equivalent of rs_jax.gf_apply for TPU.

    b_bits: (R*8, C*8) int8 lifted matrix; data (C, N) or (B, C, N) uint8.
    Handles any N by zero-padding to the tile size (zero bytes encode to
    zero bytes, so padding never corrupts real lanes). Off-TPU the kernel
    runs in Pallas interpret mode so the exact kernel logic stays testable
    on the CPU mesh.
    """
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    batch, c, n = data.shape
    rows = b_bits.shape[0] // 8
    if n == 0:
        out = jnp.zeros((batch, rows, 0), jnp.uint8)
        return out[0] if squeeze else out
    t = min(tile, _round_up(max(n, 128), 128))
    n_pad = _round_up(n, t)
    if n_pad != n:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, n_pad - n)))
    pack = jnp.asarray(_pack_matrix(rows))
    out = _apply_padded(b_bits, pack, data, t, not _on_tpu())
    if n_pad != n:
        out = out[..., :n]
    return out[0] if squeeze else out


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def apply_matrix(m: np.ndarray, shards, tile: int = DEFAULT_TILE) -> jax.Array:
    """GF(2^8) matrix application via the fused kernel (matrix cached)."""
    from seaweedfs_tpu.ops import rs_jax

    return gf_apply_fused(rs_jax.lifted_matrix(m), jnp.asarray(shards), tile)
