"""Fused Pallas TPU kernel for GF(2^8) coding — the performance path.

The pure-XLA route (rs_jax.gf_apply) materializes the 8x bit-plane expansion
and an int32 accumulator in HBM; this kernel keeps both in VMEM:

    per grid step (one batch element x one stripe tile of T bytes):
      load   data tile (C, T) uint8                  HBM -> VMEM
      unpack bits (8*C, T) int8, PLANE-major         VPU (block concat — no
             (row j*C+ci = bit j of byte-row ci)     per-byte interleave;
                                                     B's columns are pre-
                                                     permuted to match)
      matmul acc = B_pm @ bits -> (R*8, T) int32     MXU
      mod-2  acc & 1
      pack   out[r] = sum_i acc[r*8+i] << i          VPU (7 shifted ORs —
                                                     cheaper than a tiny
                                                     M=R pack-matmul)
    store  out tile (R, T)                           VMEM -> HBM

HBM traffic is exactly C+R bytes/byte-position — the algorithmic minimum —
vs ~(9C + 5R) for the unfused path. Replaces the reference codec's AVX2/GFNI
galois kernels (klauspost/reedsolomon galois_gen_amd64.s [VERIFY: mount
empty]) as SURVEY.md §2.2 prescribes.

The kernel is a staged FAMILY of variants (ROOFLINE_r05.md verification
plan; all byte-exact vs the gf8 golden, all Mosaic-lowering-proven via
tpu_lowering.PROOF_SHAPES, selected by the `mxu` argument):

  int8    the r5 baseline: int8 plane lift, 8 arithmetic shift+mask
          unpacks, one (R*8, C*8) int8 MXU matmul.
  bf16    same unpack, bf16 MXU matmul (exact: partial sums <= 80 < 256).
  u8      shift-free unpack — bit j is extracted as a mask+compare
          ((x & (1<<j)) != 0; bit 7 = sign test) instead of the 8-deep
          arithmetic-shift chain; the tile is reinterpreted int8 once
          (width-preserving — Mosaic has no uint8 elementwise lowerings
          on this toolchain) but is never widened or shifted
          (ROOFLINE hyp 1: the shift+mask chain is VPU-bound).
  mplane  multi-plane ACCUMULATION: 8 small K=C matmuls, one per bit
          plane, summed into a single int32 accumulator — the (8C, T)
          concatenated bit matrix is never materialized in VMEM, cutting
          the unpack working set 8x and folding all 8 planes of the
          lifted Cauchy/Vandermonde matrix into one grid pass.
  dma     manual DOUBLE-BUFFERED tile DMA: the data operand stays in HBM
          (pl.ANY) and the kernel streams (C, chunk) sub-tiles through a
          2-slot VMEM scratch ring with make_async_copy, overlapping the
          HBM load of chunk k+1 with the MXU/VPU work on chunk k inside
          one big grid step (ROOFLINE hyp 4: per-grid-step overhead).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.ops import gf8

# bytes of one stripe tile per grid step; 8 KiB x (C*8) bits stays well under
# VMEM while giving the MXU a wide N dimension. Kept as the floor of the
# retuned auto chooser below — ROOFLINE_r05 hyp 4: at 8 KiB tiles the
# per-grid-step overhead (semaphores, window swaps) is material, so the
# default now scales the tile up to the VMEM budget instead.
DEFAULT_TILE = 8192

#: VMEM the auto tile chooser may plan against. Half the v5e core's ~16 MiB
#: so Mosaic retains room to double-buffer the HBM<->VMEM windows.
DEFAULT_VMEM_BUDGET = 8 << 20

#: snap grid for auto tiles — large power-of-two-ish strides keep the
#: HBM windows aligned and the grid-step count predictable
_TILE_STEPS = (65536, 49152, 32768, 24576, 16384, 8192, 4096, 2048, 1024, 512, 256, 128)


#: bytes of one DMA chunk for the `dma` variant — the unit the manual
#: double buffer streams through VMEM. Small enough that two slots plus
#: the per-chunk bit expansion stay far under budget, large enough that
#: each chunk's matmul amortizes the copy-start overhead.
DMA_CHUNK = 2048


def auto_tile(
    c: int, rows: int, mxu: str = "int8", vmem_budget: int = DEFAULT_VMEM_BUDGET
) -> int:
    """Largest tile whose per-grid-step VMEM working set fits the budget.

    Working set per byte-position of tile: data window (double-buffered,
    2C) + bit-plane expansion (8C at the MXU dtype's width) + int32
    accumulator (32R) + output window (double-buffered, 2R). The `mplane`
    variant never materializes the concatenated planes (one C-wide plane
    at a time); the `dma` variant's data working set is the 2-slot chunk
    ring, not the tile, so both can plan much larger tiles."""
    bits_width = 2 if mxu == "bf16" else 1
    if mxu == "mplane":
        # one (C, T) plane live at a time instead of the (8C, T) stack
        per_byte = 2 * c + 2 * c + 32 * rows + 2 * rows
    elif mxu == "dma":
        # per-TILE-byte cost is just the output window + accumulator
        # amortization; the chunk ring is a constant (2*C*DMA_CHUNK)
        per_byte = 32 * rows + 2 * rows + 1
    else:
        per_byte = 2 * c + 8 * c * bits_width + 32 * rows + 2 * rows
    cap = max(128, vmem_budget // per_byte)
    for t in _TILE_STEPS:
        if t <= cap:
            return t
    return 128


def _kernel(b_ref, data_ref, out_ref):
    data = data_ref[0]  # (C, T) uint8
    c, t = data.shape
    # Plane-major bit layout ON BOTH SIDES (ROOFLINE_r05.md hyps 1+3):
    #   input  row j*C + ci = bit j of input byte-row ci
    #   output row i*R + r  = bit i of output byte-row r
    # Concatenating whole (C, T) blocks keeps every plane in its natural
    # VMEM layout — a byte-major stack(axis=1).reshape forces a per-byte
    # sublane interleave Mosaic must shuffle for. The lifted matrix's
    # columns AND rows are pre-permuted host-side to match (free). The
    # unpack shifts int8 (same width as the bytes, so no VMEM inflation):
    # Mosaic has no uint8 shift lowering, and (x >> j) & 1 extracts bit j
    # under arithmetic shift exactly as under logical shift for j < 8.
    di = data.astype(jnp.int8)
    bits = jnp.concatenate([((di >> j) & 1) for j in range(8)], axis=0)
    acc = jax.lax.dot_general(
        b_ref[...],
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    acc = acc & 1  # (8*R, T), rows i*R + r — plane-major
    # pack on the VPU: out[r] = sum_i acc[i*R + r] << i. With plane-major
    # rows each acc3[i] is a CONTIGUOUS (R, T) block (sublane stride 1);
    # the old byte-major pack read with sublane stride 8, which Mosaic
    # lowered to per-sublane shuffles.
    rows8, _ = acc.shape
    acc3 = acc.reshape(8, rows8 // 8, t)
    out = acc3[0]
    for i in range(1, 8):
        out = out | (acc3[i] << i)
    out_ref[0] = out.astype(jnp.uint8)


def _kernel_bf16(b_ref, data_ref, out_ref):
    """Same plane-major layout as `_kernel`, but the MXU matmul runs in
    bf16: products are 0/1 and K = C*8 <= 80 for RS(10+4), so every partial
    sum <= 80 < 256 is exactly representable in bf16's 8-bit significand
    (f32 accumulate is exact a fortiori) — int8 matmul on some TPU
    generations is emulated at a fraction of bf16 rate, so this can win.
    Promoted from scripts/kernel_sweep.py so production can select it."""
    data = data_ref[0]
    di = data.astype(jnp.int8)  # int8 unpack: see _kernel
    bits = jnp.concatenate(
        [((di >> j) & 1) for j in range(8)], axis=0
    ).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        b_ref[...].astype(jnp.bfloat16),
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    acc = acc & 1
    rows8, t = acc.shape
    acc3 = acc.reshape(8, rows8 // 8, t)
    out = acc3[0]
    for i in range(1, 8):
        out = out | (acc3[i] << i)
    out_ref[0] = out.astype(jnp.uint8)


def _pack_planes(acc):
    """(8*R, T) int32 plane-major 0/1 rows -> (R, T) uint8 bytes.

    With plane-major rows each plane is a CONTIGUOUS (R, T) block
    (sublane stride 1); a byte-major pack would read with sublane
    stride 8, which Mosaic lowers to per-sublane shuffles."""
    rows8, t = acc.shape
    acc3 = acc.reshape(8, rows8 // 8, t)
    out = acc3[0]
    for i in range(1, 8):
        out = out | (acc3[i] << i)
    return out.astype(jnp.uint8)


def _kernel_u8(b_ref, data_ref, out_ref):
    """Shift-free unpack: bit j extracted as a VPU mask+compare
    ((x & (1<<j)) != 0; bit 7 is the sign test x < 0) instead of the
    8-deep arithmetic-shift chain of `_kernel` (ROOFLINE_r05 hyp 1: the
    shift+mask unpack is the VPU-bound stage). The tile is reinterpreted
    int8 ONCE — a width-preserving convert, not a plane lift; it exists
    only because Mosaic on this toolchain has NO uint8 elementwise
    lowerings at all (`and`/`shift`/`compare` on u8 all raise
    NotImplementedError — probed r6), so the mask ops must run on int8
    lanes. Same bytes, no VMEM inflation, zero shifts."""
    di = data_ref[0].astype(jnp.int8)  # (C, T) reinterpret, not a widen
    planes = [(di & jnp.int8(1 << j)) != 0 for j in range(7)]
    planes.append(di < 0)  # bit 7 == int8 sign
    bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)
    acc = jax.lax.dot_general(
        b_ref[...],
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out_ref[0] = _pack_planes(acc & 1)


def _kernel_mplane(b_ref, data_ref, out_ref):
    """Multi-plane accumulation: instead of materializing the (8C, T)
    concatenated bit matrix and one K=8C matmul, run 8 small K=C matmuls
    — one per bit plane of the lifted matrix (B's columns are plane-major,
    so plane j is the contiguous column block [j*C, (j+1)*C)) — summed
    into ONE int32 accumulator. All 8 planes fold into a single grid
    pass with an 8x smaller unpack working set; mod-2 commutes with the
    sum (acc = sum_j B_j @ bits_j over Z, & 1 at the end)."""
    data = data_ref[0]
    c, _t = data.shape
    di = data.astype(jnp.int8)  # int8 shifts: see _kernel
    acc = None
    for j in range(8):
        plane = (di >> j) & 1  # (C, T) int8 — one plane live at a time
        part = jax.lax.dot_general(
            b_ref[:, j * c : (j + 1) * c],
            plane,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        acc = part if acc is None else acc + part
    out_ref[0] = _pack_planes(acc & 1)


def _make_dma_kernel(chunk: int):
    """Manual double-buffered HBM->VMEM streaming: the data operand stays
    in HBM (pl.ANY BlockSpec) and the kernel DMAs (C, chunk) sub-tiles
    into a 2-slot VMEM scratch ring, starting the copy of chunk k+1
    before computing on chunk k — HBM loads overlap MXU/VPU work inside
    one large grid step instead of relying on Mosaic's window pipelining
    across many small steps (ROOFLINE_r05 hyp 4)."""

    def kernel(b_ref, data_ref, out_ref):
        bi = pl.program_id(0)
        ti = pl.program_id(1)
        c = data_ref.shape[1]
        tile = out_ref.shape[2]
        nchunks = tile // chunk

        def body(scratch, sem):
            def chunk_dma(slot, k):
                return pltpu.make_async_copy(
                    data_ref.at[bi, :, pl.ds(ti * tile + k * chunk, chunk)],
                    scratch.at[slot],
                    sem.at[slot],
                )

            chunk_dma(0, 0).start()

            def loop(k, carry):
                slot = k % 2

                @pl.when(k + 1 < nchunks)
                def _():
                    chunk_dma((k + 1) % 2, k + 1).start()

                chunk_dma(slot, k).wait()
                di = scratch[slot].astype(jnp.int8)
                bits = jnp.concatenate(
                    [((di >> j) & 1) for j in range(8)], axis=0
                )
                acc = jax.lax.dot_general(
                    b_ref[...],
                    bits,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )
                out_ref[0, :, pl.ds(k * chunk, chunk)] = _pack_planes(acc & 1)
                return carry

            jax.lax.fori_loop(0, nchunks, loop, 0)

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((2, c, chunk), jnp.uint8),
            sem=pltpu.SemaphoreType.DMA((2,)),
        )

    return kernel


_KERNELS = {
    "int8": _kernel,
    "bf16": _kernel_bf16,
    "u8": _kernel_u8,
    "mplane": _kernel_mplane,
    "dma": None,  # built per tile/chunk by _make_dma_kernel
}

#: the staged fused-kernel family, in sweep order. The canonical name
#: tuple lives jax-free in rs_codec (evidence parsing in bench's parent
#: must not import this module); the kernel table here must match it.
from seaweedfs_tpu.ops.rs_codec import FUSED_VARIANTS as VARIANTS  # noqa: E402

assert tuple(_KERNELS) == VARIANTS, (
    f"kernel table {tuple(_KERNELS)} drifted from rs_codec.FUSED_VARIANTS {VARIANTS}"
)


def _plane_major_columns(b_bits: np.ndarray) -> np.ndarray:
    """Permute the lifted matrix's columns from byte-major (ci*8 + j) to
    plane-major (j*C + ci), AND its rows from byte-major (r*8 + i) to
    plane-major (i*R + r) — both sides of the kernel's bit layout."""
    rows8, cols8 = b_bits.shape
    c = cols8 // 8
    r = rows8 // 8
    col_perm = [(k % c) * 8 + (k // c) for k in range(cols8)]
    row_perm = [(k % r) * 8 + (k // r) for k in range(rows8)]
    return np.asarray(b_bits)[np.ix_(row_perm, col_perm)]


def _on_tpu() -> bool:
    from seaweedfs_tpu.utils.devices import is_tpu_device

    return is_tpu_device(jax.devices()[0])


def _dma_chunk(tile: int) -> int:
    """Largest chunk <= DMA_CHUNK dividing the tile (tiles are always
    multiples of 128, so 128 is the floor)."""
    for ch in (DMA_CHUNK, 1024, 512, 256, 128):
        if ch <= tile and tile % ch == 0:
            return ch
    return 128


def _apply_padded_impl(b_pm, data, tile: int, interpret: bool, mxu: str):
    batch, c, n = data.shape
    rows = b_pm.shape[0] // 8
    grid = (batch, n // tile)
    kwargs = {}
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if not interpret and params_cls is not None:
        # every grid step is independent (disjoint tiles): telling Mosaic
        # so unlocks unconstrained pipelining of the HBM<->VMEM windows
        kwargs["compiler_params"] = params_cls(
            dimension_semantics=("parallel", "parallel")
        )
    if mxu == "dma":
        # the data operand never gets a Mosaic-managed VMEM window: it
        # stays in HBM and the kernel streams it through its own 2-slot
        # scratch ring (chunk k+1's copy overlaps chunk k's compute)
        kernel = _make_dma_kernel(_dma_chunk(tile))
        data_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    else:
        kernel = _KERNELS[mxu]
        data_spec = pl.BlockSpec((1, c, tile), lambda b, i: (b, 0, i))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b_pm.shape[0], b_pm.shape[1]), lambda b, i: (0, 0)),
            data_spec,
        ],
        out_specs=pl.BlockSpec((1, rows, tile), lambda b, i: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((batch, rows, n), jnp.uint8),
        interpret=interpret,
        **kwargs,
    )(b_pm, data)


_STATIC = ("tile", "interpret", "mxu")
_apply_padded_jit = jax.jit(_apply_padded_impl, static_argnames=_STATIC)
# donated twin: the (large) data buffer's HBM is released as soon as the
# dispatch consumes it — an early-release hint, not output aliasing (the
# (B, C, N) input cannot alias the smaller (B, R, N) output; see the
# rs_jax donated-twin note). No-op + warning on CPU, so callers gate on
# rs_jax.donation_supported().
_apply_padded_donated = jax.jit(
    _apply_padded_impl, static_argnames=_STATIC, donate_argnums=(1,)
)


def _apply_padded(b_pm, data, tile: int, interpret: bool, mxu: str = "int8"):
    """Compat shim (tpu_lowering exports through this name)."""
    return _apply_padded_jit(b_pm, data, tile, interpret, mxu)


def _apply_pm(
    b_pm: jax.Array,
    data: jax.Array,
    tile: int | None,
    mxu: str = "int8",
    donate: bool = False,
) -> jax.Array:
    """Shared pad/tile/squeeze plumbing over an already-plane-major matrix."""
    if mxu not in _KERNELS:
        raise ValueError(f"unknown mxu dtype {mxu!r} (want {sorted(_KERNELS)})")
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    batch, c, n = data.shape
    rows = b_pm.shape[0] // 8
    if n == 0:
        out = jnp.zeros((batch, rows, 0), jnp.uint8)
        return out[0] if squeeze else out
    if tile is None:
        tile = auto_tile(c, rows, mxu)
    t = min(tile, _round_up(max(n, 128), 128))
    n_pad = _round_up(n, t)
    if n_pad != n:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, n_pad - n)))
    if donate:
        from seaweedfs_tpu.ops import rs_jax

        if rs_jax.donation_supported():
            out = _apply_padded_donated(
                b_pm, jax.device_put(data), t, not _on_tpu(), mxu
            )
            if n_pad != n:
                out = out[..., :n]
            return out[0] if squeeze else out
    out = _apply_padded_jit(b_pm, data, t, not _on_tpu(), mxu)
    if n_pad != n:
        out = out[..., :n]
    return out[0] if squeeze else out


def gf_apply_fused(
    b_bits: jax.Array,
    data: jax.Array,
    tile: int | None = None,
    mxu: str = "int8",
) -> jax.Array:
    """Fused equivalent of rs_jax.gf_apply for TPU.

    b_bits: (R*8, C*8) int8 lifted matrix; data (C, N) or (B, C, N) uint8.
    Handles any N by zero-padding to the tile size (zero bytes encode to
    zero bytes, so padding never corrupts real lanes). Off-TPU the kernel
    runs in Pallas interpret mode so the exact kernel logic stays testable
    on the CPU mesh. tile=None picks the largest tile whose working set
    fits the VMEM budget (`auto_tile`); mxu selects the staged kernel
    variant (`VARIANTS`: "int8", "bf16", "u8", "mplane", "dma" — see the
    module docstring for strategies).
    """
    return _apply_pm(_lifted_plane_major(b_bits), data, tile, mxu)


@functools.lru_cache(maxsize=256)
def _plane_major_cached(key) -> jax.Array:
    rows8, cols8, flat = key
    arr = np.frombuffer(bytes(flat), dtype=np.int8).reshape(rows8, cols8)
    return jnp.asarray(_plane_major_columns(arr))


@functools.lru_cache(maxsize=256)
def _lift_pm_cached(key) -> jax.Array:
    rows, cols, flat = key
    m = np.frombuffer(bytes(flat), dtype=np.uint8).reshape(rows, cols)
    lifted = gf8.gf_matrix_to_bits(m).astype(np.int8)
    return jnp.asarray(_plane_major_columns(lifted))


def plane_major_matrix(m: np.ndarray) -> jax.Array:
    """Host-side: lifted + column-permuted device matrix for the kernel,
    cached by GF-matrix value — both the bit-lift (Python GF math) and the
    permutation happen once per matrix, and the hot path (apply_matrix)
    never round-trips an already-uploaded matrix through the host."""
    a = np.asarray(m, dtype=np.uint8)
    return _lift_pm_cached((a.shape[0], a.shape[1], a.tobytes()))


# id-keyed memo for the b_bits (device array) compat path: np.asarray on a
# device array is a blocking D2H transfer — ~65 ms through the axon tunnel —
# so it must happen once per matrix object, not once per call. Entries
# self-evict when their source array is collected (weakref callback), so
# the memo cannot pin dead device buffers for the life of the process.
_pm_by_id: dict[int, tuple] = {}


def _lifted_plane_major(b_bits) -> jax.Array:
    import weakref

    k = id(b_bits)
    hit = _pm_by_id.get(k)
    if hit is not None and hit[0]() is b_bits:
        return hit[1]
    a = np.asarray(b_bits, dtype=np.int8)
    pm = _plane_major_cached((a.shape[0], a.shape[1], a.tobytes()))
    try:
        ref = weakref.ref(b_bits, lambda _r, _k=k: _pm_by_id.pop(_k, None))
        _pm_by_id[k] = (ref, pm)
    except TypeError:  # non-weakrefable input (plain ndarray): value cache hit anyway
        pass
    return pm


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def apply_matrix(
    m: np.ndarray,
    shards,
    tile: int | None = None,
    mxu: str = "int8",
    donate: bool = False,
) -> jax.Array:
    """GF(2^8) matrix application via the fused kernel: the hot path —
    lift + permute host-side once per matrix value, no device round-trip.
    donate=True releases the input's device buffer at dispatch-consume
    time (streaming pipelines; ignored on CPU where donation is a no-op)."""
    return _apply_pm(
        plane_major_matrix(m), jnp.asarray(shards), tile, mxu, donate=donate
    )
