"""Reed-Solomon codec facade — the `reedsolomon.Encoder`-shaped seam.

Mirrors the API surface the reference consumes from klauspost/reedsolomon
(`New(d, p)`, `Encode`, `Reconstruct`, `ReconstructData`, `Verify`,
`Split`/`Join` [VERIFY: reference mount empty — upstream API, SURVEY.md §2.1])
with three backends behind one factory, the same seam SURVEY.md §1 identifies
for backend selection:

  * "numpy"  — host CPU golden path (table-driven GF(2^8)), the correctness
    oracle and fallback when no accelerator is present.
  * "jax"    — pure-XLA bit-plane path (rs_jax); any accelerator.
  * "pallas" — the TPU path: the fused VMEM-resident kernel (rs_pallas).

Per-loss-pattern decode matrices are built host-side by GF Gaussian
elimination and cached — the role of the reference codec's inversion tree
(`inversion_tree.go`).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
from typing import Optional, Sequence

import numpy as np

from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.utils import config

#: committed on-chip measurement evidence older than this many days no
#: longer flips the auto backend away from its conservative default: the
#: kernels under measurement keep changing round to round, so an ancient
#: number says nothing about today's binary.
EVIDENCE_MAX_AGE_DAYS = config.env("WEEDTPU_EVIDENCE_MAX_AGE_DAYS")

#: the staged fused-kernel family (rs_pallas re-exports this as VARIANTS
#: and asserts its kernel table matches). Lives HERE, jax-free, so
#: evidence parsing (parse_fused_variant — called from bench's parent
#: process, which must never import jax: a jax import can wedge the
#: single-client TPU tunnel) needs no rs_pallas/jax import.
FUSED_VARIANTS = ("int8", "bf16", "u8", "mplane", "dma")

_BACKENDS = ("numpy", "native", "xorsched", "jax", "pallas", "mesh")


# -- code-family registry (the geometry-flexible seam) ------------------------
#
# Geometry (k, m, generator family) is a first-class Encoder parameter, no
# longer pinned at the legacy 10+4. Each registered family names one
# (data_shards, parity_shards, matrix_kind) triple; the `.eci` sidecar
# records a volume's family so mounts, rebuilds, and scrubs agree on the
# layout, and `ec.convert` re-encodes a volume from one family to another
# without ever materializing the .dat (see seaweedfs_tpu/ec/convert.py).


@dataclasses.dataclass(frozen=True)
class CodeGeometry:
    """One registered erasure-code geometry."""

    family: str
    data_shards: int
    parity_shards: int
    matrix_kind: str  # gf8.generator_matrix dispatch: vandermonde | cauchy

    @property
    def total_shards(self) -> int:
        return self.data_shards + self.parity_shards

    @property
    def overhead(self) -> float:
        """Storage overhead factor (total/data) — the tiering cost signal
        conversions optimize: colder data wants a smaller factor."""
        return self.total_shards / self.data_shards


#: the registered code families. `rs_10_4` is the legacy wire-default
#: (klauspost-compatible Vandermonde 10+4 — what every pre-geometry .eci
#: implies); `cauchy_12_3` is the wider, cheaper cold-tier code (overhead
#: 1.25 vs 1.4, Cauchy parity rows are provably MDS for any k+m <= 256);
#: `merge_20_4` is the 10+4 -> 20+4 stripe-merge layout (two source data
#: rows regroup into one target row; overhead 1.2).
CODE_FAMILIES: dict[str, CodeGeometry] = {
    g.family: g
    for g in (
        CodeGeometry("rs_10_4", 10, 4, "vandermonde"),
        CodeGeometry("cauchy_12_3", 12, 3, "cauchy"),
        CodeGeometry("merge_20_4", 20, 4, "cauchy"),
    )
}

DEFAULT_FAMILY = "rs_10_4"


def geometry_for(family: str) -> CodeGeometry:
    """The registered geometry behind a family name; unknown names raise
    (a typo'd conversion target must fail loudly, not encode garbage)."""
    geom = CODE_FAMILIES.get(str(family))
    if geom is None:
        raise ValueError(
            f"unknown code family {family!r} (registered: "
            f"{sorted(CODE_FAMILIES)})"
        )
    return geom


def family_of(
    data_shards: int, parity_shards: int, matrix_kind: str
) -> Optional[str]:
    """Reverse lookup: the registered family name for a geometry triple,
    or None for an unregistered ad-hoc geometry (tests use scaled ones)."""
    for name, g in CODE_FAMILIES.items():
        if (g.data_shards, g.parity_shards, g.matrix_kind) == (
            int(data_shards), int(parity_shards), str(matrix_kind),
        ):
            return name
    return None

#: LRU cap on cached decode matrices. A long-lived volume server whose
#: shard-loss patterns churn (peers flapping, rolling repairs) sees an
#: unbounded stream of (survivors, wanted) keys — C(14,10) x wanted sets is
#: thousands of patterns — so the memo must evict, not grow for the life of
#: the process. Matrices are tiny; the cap bounds the GF-elimination *keys*.
DECODE_MATRIX_CACHE_SIZE = config.env("WEEDTPU_DECODE_MATRIX_CACHE")


@functools.lru_cache(maxsize=max(16, DECODE_MATRIX_CACHE_SIZE))
def _reconstruction_matrix(
    kind: str,
    data_shards: int,
    parity_shards: int,
    survivors: tuple,
    wanted: tuple,
) -> np.ndarray:
    """(len(wanted) x data_shards) matrix mapping survivor shards to wanted
    shards. `survivors` must be exactly `data_shards` present shard ids."""
    gen = gf8.generator_matrix(kind, data_shards, data_shards + parity_shards)
    sub = gen[list(survivors), :]  # (D, D)
    inv = gf8.gf_mat_inv(sub)  # survivors -> data
    rows = []
    for w in wanted:
        if w < data_shards:
            rows.append(inv[w])
        else:
            rows.append(gf8.gf_mat_mul(gen[w : w + 1], inv)[0])
    out = np.stack(rows).astype(np.uint8)
    out.setflags(write=False)
    return out


def decode_matrix_cache_info():
    """The decode-matrix memo's (hits, misses, maxsize, currsize) — lets
    operators/tests assert the cache stays bounded under loss-pattern churn."""
    return _reconstruction_matrix.cache_info()


def clear_decode_matrix_cache() -> None:
    _reconstruction_matrix.cache_clear()


class _FusedBlocks:
    """Lazy handle for a block-diagonal fused decode on a non-xorsched
    backend: per-block device dispatches stay in flight until np.asarray()
    (the one sync point per staging batch, mirroring reconstruct_lazy's
    contract).  Rows past a block's own output count inside its columns
    are unspecified, like the materialized form."""

    def __init__(self, shape: tuple[int, int], parts: list):
        self.shape = shape
        self._parts = parts  # (rows, col_start, width, backend handle)
        self._out: Optional[np.ndarray] = None

    def __array__(self, dtype=None, copy=None):
        if self._out is None:
            out = np.empty(self.shape, dtype=np.uint8)
            for rows, c0, w, h in self._parts:
                out[:rows, c0:c0 + w] = np.asarray(h)[:rows]
            self._out = out
            self._parts = []
        if dtype is not None and dtype != self._out.dtype:
            return self._out.astype(dtype)
        return self._out


class Encoder:
    """RS(d+p) encoder/reconstructor over GF(2^8).

    All shards in one call must share a length (like the reference codec);
    striping/padding policy lives a layer up in `ec.stripe`.

    Reconstructs on the jax/pallas backends are PAD-AND-MASKED to a fixed
    bucket set of shard lengths: XLA caches compiles per shape, so without
    bucketing every new interval size pays a fresh compile on the
    degraded-read serving path (r3 bench: 26x cold/warm gap). Zero padding
    is exact — GF matmul maps zero columns to zero columns — and the pad is
    sliced off before returning (SURVEY.md §7.3.5).
    """

    #: shard-length buckets for small-shape reconstructs (serving-path
    #: intervals are needle records: ~KBs; block-sized reads cap at 1 MiB)
    RECONSTRUCT_BUCKETS = (4 << 10, 64 << 10, 1 << 20)

    def __init__(
        self,
        data_shards: int = 10,
        parity_shards: int = 4,
        matrix_kind: str = "vandermonde",
        backend: str = "numpy",
        pallas_mxu: str = "int8",
        pallas_tile: Optional[int] = None,
        mesh_shape: Optional[Sequence[int]] = None,
        mesh_rebuild: Optional[str] = None,
    ):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("shard counts must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("GF(2^8) supports at most 256 total shards")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        if backend not in _BACKENDS:
            raise ValueError(
                f"unknown backend {backend!r} (want one of {_BACKENDS})"
            )
        self.matrix_kind = matrix_kind
        #: registered family name when the (k, m, kind) triple matches one
        #: (None for ad-hoc geometries, e.g. tests' scaled shard counts)
        self.family = family_of(data_shards, parity_shards, matrix_kind)
        self.backend = backend
        # fused-kernel variant config (pallas backend only): which staged
        # kernel (rs_pallas.VARIANTS) and tile the dispatches use — set by
        # new_encoder("auto") from the winning committed measurement
        self.pallas_mxu = pallas_mxu
        self.pallas_tile = pallas_tile
        # mesh backend config: dp x sp axis shape and distributed-rebuild
        # variant (None = resolve from WEEDTPU_MESH_SHAPE / committed
        # MULTICHIP evidence / the all-devices default at first dispatch)
        self.mesh_shape = tuple(int(v) for v in mesh_shape) if mesh_shape else None
        self.mesh_rebuild = mesh_rebuild
        self._mesh_obj = None
        #: how this encoder's backend was chosen (new_encoder fills it;
        #: direct construction is an explicit choice)
        self.selection: dict = {"backend": backend, "source": "explicit"}
        self.gen_matrix = gf8.generator_matrix(matrix_kind, data_shards, self.total_shards)
        self.parity_matrix = np.ascontiguousarray(self.gen_matrix[data_shards:])

    # -- kernel dispatch ----------------------------------------------------

    def _mesh_dispatch(self):
        """The lazily-built mesh state (imports jax; builds the Mesh and
        exports the weedtpu_ec_mesh_devices gauge on first use)."""
        if self._mesh_obj is None:
            from seaweedfs_tpu.parallel import backend as mesh_backend

            self._mesh_obj = mesh_backend.MeshDispatch(
                shape=self.mesh_shape, rebuild=self.mesh_rebuild
            )
        return self._mesh_obj

    @property
    def width_align(self) -> int:
        """Staging-width multiple the streaming pipelines should round
        their spans to so every steady-state batch dispatches pad-free
        (1 on single-device backends; dp*sp on the mesh backend)."""
        if self.backend != "mesh":
            return 1
        return self._mesh_dispatch().width_align

    def _count_dispatch(self) -> None:
        try:
            from seaweedfs_tpu import stats

            stats.EcDispatchTotal.labels(self.backend).inc()
        except Exception:  # noqa: BLE001 — metrics must never break dispatch
            pass

    def _apply_lazy(self, m: np.ndarray, shards: np.ndarray, donate: bool = False):
        """Apply GF matrix m without forcing the result to the host: the
        jax/pallas backends return a device array (async dispatch), numpy/
        native an ndarray. The ONE backend dispatch point — _apply and
        encode_parity_lazy are both defined in terms of it. donate=True
        (jax/pallas, off-CPU only) releases the input's device buffer at
        dispatch-consume time so a streaming pipeline's inflight HBM stays
        bounded (an early-release hint — see rs_jax's donated-twin note)."""
        self._count_dispatch()
        if self.backend == "mesh":
            return self._mesh_dispatch().apply(m, shards, donate=donate)
        if self.backend == "pallas":
            from seaweedfs_tpu.ops import rs_pallas

            return rs_pallas.apply_matrix(
                m, shards, tile=self.pallas_tile, mxu=self.pallas_mxu,
                donate=donate,
            )
        if self.backend == "jax":
            from seaweedfs_tpu.ops import rs_jax

            return rs_jax.apply_matrix(m, shards, donate=donate)
        if self.backend == "native":
            out = self._apply_native(m, shards)
            if out is not None:
                return out
            # library unavailable/unbuildable: numpy keeps serving
        if self.backend == "xorsched":
            return self._apply_xorsched(m, shards)
        if shards.ndim == 3:
            return np.moveaxis(gf8.gf_mat_vec(m, np.moveaxis(shards, 0, 1)), 1, 0)
        return gf8.gf_mat_vec(m, shards)

    @staticmethod
    def _apply_native(m: np.ndarray, shards: np.ndarray):
        """C++ AVX2 PSHUFB apply (utils/native, all cores) — ~30x the
        numpy table path on CPU-only volume servers. None when the
        library can't load (caller falls back to numpy)."""
        from seaweedfs_tpu.utils import native as native_mod

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if shards.ndim == 2:
            outs = native_mod.gf_matrix_apply_native(
                m, list(shards), shards.shape[1], threads=0
            )
            return None if outs is None else np.stack(outs)
        # batched: one library call with per-element slice pointers — one
        # worker pool for the whole flush and zero host-side repacking
        return native_mod.gf_matrix_apply_batch_native(m, shards, threads=0)

    @staticmethod
    def _apply_xorsched(m: np.ndarray, shards: np.ndarray) -> np.ndarray:
        """Compiled XOR-schedule apply (ops/xorsched): the GF(2^8) matrix is
        lowered once to a binary bit-plane XOR program (bounded LRU keyed by
        matrix bytes + tile geometry) and replayed over the shard widths.
        Never returns None — the numpy bulk-XOR interpreter inside xorsched
        is the always-available floor when libweedtpu.so lacks the
        weedtpu_xor_schedule_apply entry point."""
        from seaweedfs_tpu.ops import xorsched

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if shards.ndim == 2:
            out = np.stack(xorsched.apply_matrix(m, list(shards)))
        else:
            out = np.stack(
                [np.stack(xorsched.apply_matrix(m, list(b))) for b in shards]
            )
        try:
            from seaweedfs_tpu import stats

            for event, v in xorsched.schedule_cache_info().items():
                stats.XorschedCache.labels(event).set(v)
        except Exception:  # noqa: BLE001 — metrics must never break dispatch
            pass
        return out

    def _apply(self, m: np.ndarray, shards: np.ndarray) -> np.ndarray:
        """Apply GF matrix m (R x C) to a shard stack (C, N) -> (R, N) or a
        batched stack (B, C, N) -> (B, R, N), materialized on the host."""
        return np.asarray(self._apply_lazy(m, shards))

    # -- public API (reedsolomon.Encoder parity) ----------------------------

    def encode(self, shards: Sequence[np.ndarray]) -> list[np.ndarray]:
        """Fill parity shards from data shards.

        `shards` holds `data_shards` equal-length uint8 arrays (extra entries
        beyond data_shards are ignored/overwritten). Returns the full list of
        `total_shards` arrays (data passed through, parity computed).
        """
        data = np.stack([np.asarray(s, dtype=np.uint8) for s in shards[: self.data_shards]])
        parity = self._apply(self.parity_matrix, data)
        return [data[i] for i in range(self.data_shards)] + [
            parity[i] for i in range(self.parity_shards)
        ]

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """Batched encode: (B, data_shards, N) -> (B, total_shards, N).

        One device dispatch for the whole batch — the TPU-first replacement
        for the reference's per-segment goroutine loop (SURVEY.md §2.5)."""
        return np.concatenate(
            [np.asarray(data, dtype=np.uint8),
             np.asarray(self.encode_parity_lazy(data))],
            axis=1,
        )

    def encode_parity_lazy(self, data: np.ndarray, donate: bool = False):
        """Batched parity WITHOUT forcing the result to the host:
        (B, data_shards, N) -> (B, parity_shards, N) — or the flat 2-D form
        (data_shards, N) -> (parity_shards, N), which streaming pipelines
        prefer (one wide matmul, no batch axis) — as a device array (jax/
        pallas backends) or ndarray (numpy). JAX's async dispatch returns
        immediately, so the caller can overlap the NEXT batch's disk reads
        with this batch's device compute (SURVEY §7.1 double buffering);
        np.asarray() on the result is the synchronization point. donate=True
        releases the batch's device buffer at dispatch-consume time
        (off-CPU; an early-release hint, see rs_jax's donated-twin note)."""
        data = np.asarray(data, dtype=np.uint8)
        if data.ndim == 2:
            if data.shape[0] != self.data_shards:
                raise ValueError(f"want ({self.data_shards}, N), got {data.shape}")
        elif data.ndim != 3 or data.shape[1] != self.data_shards:
            raise ValueError(f"want (B, {self.data_shards}, N), got {data.shape}")
        return self._apply_lazy(self.parity_matrix, data, donate=donate)

    # -- delta parity maintenance (the small-write/inline-ingest seam) -------

    def parity_delta(self, shard_index: int, old_block, new_block):
        """The parity CHANGE for a single data shard's byte change:
        (parity_shards, n) rows to XOR into the stored parity columns
        covering the same byte range — parity' = parity ⊕ delta rows.

        GF(2^8) linearity makes a small overwrite a rank-1 update instead
        of a stripe re-encode (gf8.gf_delta_parity is the numpy golden
        this is tested byte-exact against): the generator-matrix COLUMN
        for `shard_index` is applied to (old ⊕ new) through the same
        backend dispatch the bulk encode runs, so inline-ingest delta
        updates ride whatever kernel the encode path measured fastest."""
        if not 0 <= int(shard_index) < self.data_shards:
            raise ValueError(
                f"shard_index {shard_index} out of range 0..{self.data_shards - 1}"
            )
        old = np.asarray(old_block, dtype=np.uint8).ravel()
        new = np.asarray(new_block, dtype=np.uint8).ravel()
        if old.shape != new.shape:
            raise ValueError(
                f"old/new blocks disagree on length: {old.shape} vs {new.shape}"
            )
        delta = old ^ new
        col = np.ascontiguousarray(
            self.parity_matrix[:, int(shard_index) : int(shard_index) + 1]
        )  # (P, 1)
        return np.asarray(self._apply_lazy(col, delta[None, :]))

    def update_parity(
        self, parity, shard_index: int, old_block, new_block
    ) -> np.ndarray:
        """Delta parity update: given the stored parity columns `parity`
        ((parity_shards, n) uint8, covering the SAME byte range as the
        blocks), return the parity of the stripe with data shard
        `shard_index`'s bytes changed old -> new — byte-exact vs a full
        re-encode of the updated stripe, at O(changed bytes) instead of
        O(stripe). The caller rewrites only the touched parity ranges."""
        parity = np.asarray(parity, dtype=np.uint8)
        old = np.asarray(old_block, dtype=np.uint8).ravel()
        if parity.ndim != 2 or parity.shape[0] != self.parity_shards:
            raise ValueError(
                f"want ({self.parity_shards}, n) parity, got {parity.shape}"
            )
        if parity.shape[1] != old.size:
            raise ValueError(
                f"parity covers {parity.shape[1]} bytes but the block "
                f"changes {old.size}"
            )
        return parity ^ self.parity_delta(shard_index, old, new_block)

    def _pick_survivors(self, shards: Sequence[Optional[np.ndarray]]) -> list[int]:
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.data_shards:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {self.data_shards}"
            )
        # Deterministically use the first `data_shards` present shards, like
        # the reference codec's Reconstruct.
        return present[: self.data_shards]

    def reconstruct(
        self,
        shards: Sequence[Optional[np.ndarray]],
        data_only: bool = False,
        wanted: Optional[Sequence[int]] = None,
    ) -> list[np.ndarray]:
        """Recompute missing shards in place-semantics: returns a full list
        where every previously-None entry (or only missing data entries when
        `data_only`) is filled. `wanted` restricts to specific shard ids."""
        shards = list(shards)
        if len(shards) != self.total_shards:
            raise ValueError(f"expected {self.total_shards} entries, got {len(shards)}")
        if wanted is None:
            limit = self.data_shards if data_only else self.total_shards
            wanted = [i for i in range(limit) if shards[i] is None]
        else:
            for w in wanted:
                if not 0 <= w < self.total_shards:
                    raise ValueError(f"wanted shard id {w} out of range 0..{self.total_shards - 1}")
            wanted = [i for i in wanted if shards[i] is None]
        if not wanted:
            return shards
        survivors = self._pick_survivors(shards)
        m = _reconstruction_matrix(
            self.matrix_kind,
            self.data_shards,
            self.parity_shards,
            tuple(survivors),
            tuple(wanted),
        )
        stack = np.stack([np.asarray(shards[i], dtype=np.uint8) for i in survivors])
        out = self._apply_bucketed(m, stack)
        for k, w in enumerate(wanted):
            shards[w] = out[k]
        return shards

    # -- batched reconstruct (the repair-path mirror of encode_parity_lazy) --

    def reconstruction_matrix(
        self, survivors: Sequence[int], wanted: Sequence[int]
    ) -> np.ndarray:
        """The fused decode matrix (len(wanted) x data_shards) mapping a
        survivor stack to the wanted shards — ONE matrix for any mix of
        data and parity losses, built once per loss pattern via the cached
        GF Gaussian elimination. `survivors` must be exactly `data_shards`
        distinct present shard ids; stack rows must follow its order."""
        survivors = tuple(int(s) for s in survivors)
        wanted = tuple(int(w) for w in wanted)
        if len(survivors) != self.data_shards or len(set(survivors)) != len(survivors):
            raise ValueError(
                f"survivors must be {self.data_shards} distinct shard ids, got {survivors}"
            )
        if not wanted:
            raise ValueError("wanted must name at least one shard id")
        for i in survivors + wanted:
            if not 0 <= i < self.total_shards:
                raise ValueError(f"shard id {i} out of range 0..{self.total_shards - 1}")
        return _reconstruction_matrix(
            self.matrix_kind, self.data_shards, self.parity_shards, survivors, wanted
        )

    def repair_projection_plan(
        self, survivors: Sequence[int], wanted: Sequence[int]
    ) -> dict[int, np.ndarray]:
        """Per-survivor coefficient columns of the fused decode matrix:
        shard id -> (len(wanted),) uint8 coefficients. The trace-repair
        wire plan: a holder of local survivor set L ships the projection
          row w = XOR_{s in L} plan[s][w] * shard_s
        and XORing the holders' projections reproduces the decode matrix
        applied to the full survivor stack EXACTLY (GF addition is XOR,
        and matrix-vector products split column-wise), so trace rebuilds
        are byte-identical to slab rebuilds on the same survivor set."""
        m = self.reconstruction_matrix(survivors, wanted)
        return {
            int(s): np.ascontiguousarray(m[:, i])
            for i, s in enumerate(survivors)
        }

    def project(self, coeffs: np.ndarray, stack: np.ndarray) -> np.ndarray:
        """Survivor-side repair projection: apply an arbitrary (R, C)
        GF(2^8) coefficient matrix to a (C, N) local-survivor stack
        -> (R, N) host ndarray, through this encoder's backend (the same
        bit-plane matmul the encode/decode paths run — gf8.gf_project is
        the numpy golden it is tested byte-exact against). C is the
        holder's LOCAL shard count, not data_shards."""
        coeffs = np.asarray(coeffs, dtype=np.uint8)
        stack = np.asarray(stack, dtype=np.uint8)
        if coeffs.ndim != 2 or stack.ndim != 2:
            raise ValueError(
                f"want (R, C) coeffs and (C, N) stack, got {coeffs.shape} "
                f"and {stack.shape}"
            )
        if coeffs.shape[1] != stack.shape[0]:
            raise ValueError(
                f"coeff cols {coeffs.shape[1]} != stack rows {stack.shape[0]}"
            )
        return np.asarray(self._apply_lazy(coeffs, stack))

    def project_lazy(self, coeffs: np.ndarray, stack: np.ndarray, donate: bool = False):
        """`project` without forcing the result to the host — the trace
        rebuild pipeline's combine step (XOR of holder projections IS a
        GF matmul by an all-ones row) rides the same async-dispatch
        contract as encode_parity_lazy/reconstruct_lazy; np.asarray() on
        the result is the synchronization point."""
        coeffs = np.asarray(coeffs, dtype=np.uint8)
        stack = np.asarray(stack, dtype=np.uint8)
        if coeffs.ndim != 2 or stack.ndim != 2 or coeffs.shape[1] != stack.shape[0]:
            raise ValueError(
                f"want (R, C) coeffs and (C, N) stack, got {coeffs.shape} "
                f"and {stack.shape}"
            )
        return self._apply_lazy(coeffs, stack, donate=donate)

    def reconstruct_lazy(
        self,
        stack: np.ndarray,
        survivors: Sequence[int],
        wanted: Sequence[int],
        donate: bool = False,
    ):
        """Batched repair WITHOUT forcing the result to the host: a
        (B, data_shards, N) survivor stack (rows in `survivors` order)
        -> (B, len(wanted), N) — or the flat 2-D (data_shards, N) ->
        (len(wanted), N) form streaming rebuilds prefer — as a device
        array (jax/pallas) or ndarray (numpy/native). ONE device dispatch
        for the whole batch, the `encode_parity_lazy` contract mirrored
        for the repair path; np.asarray() on the result is the
        synchronization point. donate=True releases the stack's device
        buffer at dispatch-consume time (off-CPU early-release hint)."""
        stack = np.asarray(stack, dtype=np.uint8)
        if stack.ndim == 2:
            if stack.shape[0] != self.data_shards:
                raise ValueError(f"want ({self.data_shards}, N), got {stack.shape}")
        elif stack.ndim != 3 or stack.shape[1] != self.data_shards:
            raise ValueError(f"want (B, {self.data_shards}, N), got {stack.shape}")
        if self.backend == "mesh":
            # the bulk-repair path rides the DISTRIBUTED formulations
            # (ring ppermute / all_to_all over the mesh) rather than the
            # generic column-sharded apply — same bytes, pod bandwidth
            self._count_dispatch()
            return self._mesh_dispatch().reconstruct(
                self.reconstruction_matrix(survivors, wanted), stack, donate=donate
            )
        return self._apply_lazy(
            self.reconstruction_matrix(survivors, wanted), stack, donate=donate
        )

    def reconstruct_batch(
        self,
        stack: np.ndarray,
        survivors: Sequence[int],
        wanted: Sequence[int],
        bucketed: bool = False,
    ) -> np.ndarray:
        """Materialized batched repair: (B, data_shards, N) survivor stack
        -> (B, len(wanted), N) host ndarray. `bucketed` pads N to the
        serving-path shard-length buckets (jax/pallas only) so degraded
        reads of odd interval sizes never pay a fresh XLA compile."""
        stack = np.asarray(stack, dtype=np.uint8)
        if stack.ndim != 3 or stack.shape[1] != self.data_shards:
            raise ValueError(f"want (B, {self.data_shards}, N), got {stack.shape}")
        m = self.reconstruction_matrix(survivors, wanted)
        if bucketed:
            return self._apply_bucketed(m, stack)
        return np.asarray(self._apply_lazy(m, stack))

    def reconstruct_block(
        self,
        staging: np.ndarray,
        blocks: Sequence[dict],
    ):
        """Block-diagonal fused decode: ONE dispatch over a staging batch
        that packs MANY signature groups' survivor columns side by side.

        `staging` is a (max_k, W) uint8 matrix; block g is a dict with
        `survivors` / `wanted` (shard-id sequences), `col_start` / `width`
        (its column range of the staging batch, disjoint across blocks) and
        an optional `encoder` (its geometry; defaults to self — this is how
        converted volumes join the same dispatch).  Block g's survivor rows
        occupy staging[:k_g, col_start:col_start+width] and its decoded
        shards land at the same columns of the returned (max_m, W) array,
        rows [0, len(wanted_g)).  Rows past len(wanted_g) inside a block's
        columns are UNSPECIFIED (never zeroed — the composite's zero blocks
        are structural, not materialized).

        GF matmul is column-independent, so packing different volumes'
        columns into one batch is byte-exact; each block keeps its own
        LRU'd decode matrix and (on the xorsched backend) its own compiled
        XOR program — the stitched pass is dispatched as per-block column
        ranges, never as one giant composite matrix.  Host backends return
        the materialized ndarray; device backends return a lazy handle
        whose np.asarray() is the synchronization point, like
        reconstruct_lazy."""
        staging = np.asarray(staging, dtype=np.uint8)
        if staging.ndim != 2:
            raise ValueError(f"want a 2-D (max_k, W) staging batch, got {staging.shape}")
        if not blocks:
            raise ValueError("blocks must name at least one signature group")
        max_k, width_total = staging.shape
        spans = []
        for g, b in enumerate(blocks):
            enc = b.get("encoder") or self
            c0, w = int(b["col_start"]), int(b["width"])
            if w <= 0 or c0 < 0 or c0 + w > width_total:
                raise ValueError(
                    f"block {g} columns [{c0}, {c0 + w}) outside staging width {width_total}"
                )
            if enc.data_shards > max_k:
                raise ValueError(
                    f"block {g} needs {enc.data_shards} survivor rows, staging has {max_k}"
                )
            m = enc.reconstruction_matrix(b["survivors"], b["wanted"])
            spans.append((enc, m, c0, w))
        by_col = sorted(spans, key=lambda s: s[2])
        for (_, _, a0, aw), (_, _, b0, _bw) in zip(by_col, by_col[1:]):
            if a0 + aw > b0:
                raise ValueError("block column ranges overlap")
        max_m = max(m.shape[0] for _, m, _, _ in spans)
        if self.backend == "xorsched":
            return self._reconstruct_block_xorsched(staging, spans, max_m)
        # other backends: per-block dispatches (async on device backends,
        # so blocks overlap in flight; _apply_lazy counts each), one sync
        # point for the whole batch via the lazy wrapper
        parts = []
        for enc, m, c0, w in spans:
            sub = staging[: enc.data_shards, c0:c0 + w]
            if self.backend == "mesh":
                self._count_dispatch()
                h = self._mesh_dispatch().apply(m, sub, donate=False)
            else:
                h = self._apply_lazy(m, sub, donate=False)
            parts.append((m.shape[0], c0, w, h))
        return _FusedBlocks((max_m, width_total), parts)

    def _reconstruct_block_xorsched(
        self, staging: np.ndarray, spans: Sequence[tuple], max_m: int
    ) -> np.ndarray:
        """The stitched path: one native (or interpreter) pass over the
        flat (block, width-tile) task list, each block writing its row
        slices of the fused output in place."""
        from seaweedfs_tpu.ops import xorsched

        self._count_dispatch()
        staging = np.ascontiguousarray(staging)
        out = np.empty((max_m, staging.shape[1]), dtype=np.uint8)
        progs, ins, outs = [], [], []
        for enc, m, c0, w in spans:
            progs.append(xorsched.get_schedule(m))
            ins.append([staging[r, c0:c0 + w] for r in range(enc.data_shards)])
            outs.append([out[r, c0:c0 + w] for r in range(m.shape[0])])
        xorsched.apply_blocks(progs, ins, outputs_per_block=outs)
        try:
            from seaweedfs_tpu import stats

            for event, v in xorsched.schedule_cache_info().items():
                stats.XorschedCache.labels(event).set(v)
        except Exception:  # noqa: BLE001 — metrics must never break dispatch
            pass
        return out

    def _bucket_for(self, n: int) -> Optional[int]:
        if self.backend in ("numpy", "native", "xorsched") or n == 0:
            return None  # host backends have no compile cache to miss —
            # padding would only make the AVX2 kernel chew dead bytes
        for b in self.RECONSTRUCT_BUCKETS:
            if n <= b:
                return b
        return None

    def _apply_bucketed(self, m: np.ndarray, stack: np.ndarray) -> np.ndarray:
        n = stack.shape[-1]
        b = self._bucket_for(n)
        if b is None or b == n:
            return self._apply(m, stack)
        padded = np.zeros(stack.shape[:-1] + (b,), dtype=np.uint8)
        padded[..., :n] = stack
        return self._apply(m, padded)[..., :n]

    def warm_reconstruct(
        self,
        wanted_counts: Sequence[int] = (1,),
        buckets: Optional[Sequence[int]] = None,
    ) -> int:
        """Pre-compile the bucketed reconstruct shapes so the first degraded
        read never pays an XLA compile (jit caches key on shapes only — any
        GF matrix of the right shape covers every decode matrix). Returns
        the number of shapes compiled (0 on the host backends)."""
        if self.backend in ("numpy", "native", "xorsched"):
            return 0  # no XLA compile cache to warm (xorsched's schedule
            # LRU fills on first dispatch; compiles are ~100ms host-side)
        count = 0
        for L in wanted_counts:
            m = self.gen_matrix[: max(1, L), : self.data_shards]
            for b in buckets or self.RECONSTRUCT_BUCKETS:
                self._apply(m, np.zeros((self.data_shards, b), dtype=np.uint8))
                count += 1
        return count

    def warm_decode_matrices(self, local_shards: Sequence[int] = ()) -> int:
        """Pre-build decode matrices for the dominant serving-path loss
        patterns: one shard lost, all 13 others reachable (survivors are
        picked in shard-id order, so the pattern per lost shard is
        deterministic). The GF Gaussian elimination these need was the
        bulk of r3's 4.4 ms cold reconstruct. Returns patterns built."""
        count = 0
        for lost in range(self.total_shards):
            if lost in local_shards:
                continue  # a locally-present shard never needs reconstructing
            survivors = [s for s in range(self.total_shards) if s != lost]
            _reconstruction_matrix(
                self.matrix_kind,
                self.data_shards,
                self.parity_shards,
                tuple(survivors[: self.data_shards]),
                (lost,),
            )
            count += 1
        return count

    def reconstruct_data(self, shards):
        """reedsolomon.ReconstructData: only repair data shards."""
        return self.reconstruct(shards, data_only=True)

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        """True iff parity shards match the data shards."""
        if len(shards) != self.total_shards:
            raise ValueError(f"expected {self.total_shards} shards")
        data = np.stack([np.asarray(s, dtype=np.uint8) for s in shards[: self.data_shards]])
        parity = self._apply(self.parity_matrix, data)
        for i in range(self.parity_shards):
            if not np.array_equal(parity[i], np.asarray(shards[self.data_shards + i])):
                return False
        return True

    def split(self, data: bytes | np.ndarray) -> list[np.ndarray]:
        """Split a byte blob into data_shards equal arrays (zero-padded).

        Empty input raises, matching the reference codec's ErrShortData."""
        buf = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.asarray(data, dtype=np.uint8)
        if len(buf) == 0:
            raise ValueError("short data: cannot split an empty blob")
        per = -(-len(buf) // self.data_shards)
        padded = np.zeros(per * self.data_shards, dtype=np.uint8)
        padded[: len(buf)] = buf
        return list(padded.reshape(self.data_shards, per))

    def join(self, shards: Sequence[np.ndarray], out_size: int) -> bytes:
        return np.concatenate([np.asarray(s, dtype=np.uint8) for s in shards[: self.data_shards]]).tobytes()[:out_size]


def _cpu_backend() -> str:
    """Best CPU path: the C++ AVX2 library when it loads, else numpy."""
    try:
        from seaweedfs_tpu.utils import native as native_mod

        return "native" if native_mod.load() is not None else "numpy"
    except Exception:  # noqa: BLE001 — any loader surprise: numpy serves
        return "numpy"


# -- on-chip measurement evidence (the auto-backend decision input) ----------


def _artifacts_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "artifacts",
    )


def load_device_evidence(art_dir: Optional[str] = None) -> Optional[dict]:
    """Newest committed `DEVICE_MEASUREMENT_r*.json` (lexically latest
    round), with `_file` recording its provenance. None when no readable
    measurement artifact exists."""
    art_dir = art_dir or _artifacts_dir()
    try:
        names = sorted(
            f
            for f in os.listdir(art_dir)
            if f.startswith("DEVICE_MEASUREMENT_") and f.endswith(".json")
        )
    except OSError:
        return None
    for name in reversed(names):
        try:
            import json

            with open(os.path.join(art_dir, name), encoding="utf-8") as f:
                ev = json.load(f)
            if isinstance(ev, dict):
                ev["_file"] = name
                return ev
        except (OSError, ValueError):
            continue  # an unreadable newest artifact must not hide older ones
    return None


def _evidence_age_days(ev: dict) -> Optional[float]:
    """Days since the measurement's `when` stamp; None when unparseable."""
    import datetime

    when = str(ev.get("when", ""))
    for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%MZ", "%Y-%m-%d"):
        try:
            t = datetime.datetime.strptime(when, fmt)
            return (datetime.datetime.utcnow() - t).total_seconds() / 86400.0
        except ValueError:
            continue
    return None


def parse_fused_variant(label: str) -> tuple[str, Optional[int]]:
    """Map a measurement key / sweep variant name to (mxu, tile) kernel
    config: 'pallas-bf16-16384' -> ('bf16', 16384), 'pallas_tile8192_
    steady_gbps' -> ('int8', 8192), 'pallas-auto'/'pallas_steady_gbps'
    -> ('int8', None), 'pallas-dma-65536' -> ('dma', 65536)."""
    s = label.replace("_steady_gbps", "").replace("_", "-")
    mxu, tile = "int8", None
    for tok in s.split("-"):
        if not tok or tok in ("pallas", "rebuild", "auto"):
            continue
        if tok in FUSED_VARIANTS:
            mxu = tok
        else:
            digits = tok[4:] if tok.startswith("tile") else tok
            if digits.isdigit():
                tile = int(digits)
    return mxu, tile


def _best_fused(ev: dict) -> tuple[Optional[str], float]:
    """Best committed fused-kernel ENCODE number in a measurement dict:
    scans both the stage-1 `pallas*_steady_gbps` keys and the assembled
    sweep section (`sweep.encode`: variant name -> steady GB/s). Rebuild-
    path numbers never pick the encode backend."""
    best_label, best = None, 0.0
    for k, v in ev.items():
        if (
            k.startswith("pallas")
            and k.endswith("_steady_gbps")
            and isinstance(v, (int, float))
            and v > best
        ):
            best_label, best = k, float(v)
    sweep = ev.get("sweep") or {}
    for name, v in (sweep.get("encode") or {}).items():
        if (
            str(name).startswith("pallas")
            and isinstance(v, (int, float))
            and v > best
        ):
            best_label, best = str(name), float(v)
    return best_label, best


def pick_device_backend(art_dir: Optional[str] = None) -> tuple[str, dict]:
    """The auto-backend decision ON TPU: flip to the fused Pallas kernel
    ONLY when a committed on-chip measurement shows a fused variant
    beating the XLA steady-state; otherwise the XLA bit-plane path. The
    returned decision dict (also exported through stats and reported by
    bench.py) names the evidence file, both numbers, and the reason, so
    the selection is auditable rather than folklore."""
    ev = load_device_evidence(art_dir)
    if ev is None:
        return "jax", {
            "backend": "jax",
            "reason": "no committed on-chip measurement evidence",
        }
    decision: dict = {"evidence_file": ev.get("_file")}
    xla = ev.get("xla_steady_gbps") or 0.0
    rm = ev.get("remeasured") or {}
    if isinstance(rm, dict) and rm.get("xla_steady_gbps"):
        xla = max(xla, rm["xla_steady_gbps"])
    # a sweep-only assembly (watch fired the sweep but the window worker
    # never ran — the short-tunnel case the incremental harvest exists
    # for) carries its XLA anchor in the sweep table, not stage-1 keys
    sweep_xla = ((ev.get("sweep") or {}).get("encode") or {}).get("xla")
    if isinstance(sweep_xla, (int, float)):
        xla = max(xla, sweep_xla)
    label, fused = _best_fused(ev)
    decision["xla_steady_gbps"] = xla
    decision["fused_steady_gbps"] = fused or None
    decision["fused_variant"] = label
    age = _evidence_age_days(ev)
    if "tpu" not in str(ev.get("platform", "")).lower():
        decision.update(backend="jax", reason="evidence is not an on-chip measurement")
        return "jax", decision
    if age is None:
        # conservative default: evidence whose age cannot be established
        # must not flip production (a hand-edited or malformed `when`
        # would otherwise count as fresh forever)
        decision.update(
            backend="jax",
            reason=f"evidence age unparseable (when={ev.get('when')!r}): treated as stale",
        )
        return "jax", decision
    if age > EVIDENCE_MAX_AGE_DAYS:
        decision.update(
            backend="jax",
            reason=f"evidence stale ({age:.0f}d > {EVIDENCE_MAX_AGE_DAYS:.0f}d)",
        )
        return "jax", decision
    if label is not None and xla and fused > xla:
        mxu, tile = parse_fused_variant(label)
        decision.update(
            backend="pallas",
            pallas_mxu=mxu,
            pallas_tile=tile,
            reason=f"committed on-chip {label}={fused} beats xla_steady={xla}",
        )
        return "pallas", decision
    decision.update(
        backend="jax",
        reason=(
            f"no fused number beats xla_steady={xla}"
            if xla
            else "evidence lacks an XLA steady-state to beat"
        ),
    )
    return "jax", decision


# -- committed mesh evidence (the pod-scale promotion input) -----------------


def _multichip_dir() -> str:
    """MULTICHIP_r*.json artifacts live at the repo root (beside
    BENCH_r*.json), not under artifacts/."""
    return os.path.dirname(_artifacts_dir())


def load_mesh_evidence(art_dir: Optional[str] = None) -> Optional[dict]:
    """Newest committed `MULTICHIP_r*.json` (lexically latest round), with
    `_file` recording provenance. None when no readable artifact exists."""
    art_dir = art_dir or _multichip_dir()
    try:
        names = sorted(
            f
            for f in os.listdir(art_dir)
            if f.startswith("MULTICHIP_r") and f.endswith(".json")
        )
    except OSError:
        return None
    for name in reversed(names):
        try:
            import json

            with open(os.path.join(art_dir, name), encoding="utf-8") as f:
                ev = json.load(f)
            if isinstance(ev, dict):
                ev["_file"] = name
                return ev
        except (OSError, ValueError):
            continue  # an unreadable newest artifact must not hide older ones
    return None


def _evidence_round(ev: dict) -> Optional[int]:
    r = ev.get("round")
    if isinstance(r, int):
        return r
    name = str(ev.get("_file", ""))
    digits = "".join(c for c in name if c.isdigit())
    return int(digits) if digits else None


def pick_mesh_backend(
    n_devices: int, art_dir: Optional[str] = None
) -> tuple[bool, dict]:
    """The pod-scale promotion decision: flip `auto` to the mesh backend
    ONLY when a committed `MULTICHIP_r*.json` carries fresh ON-CHIP
    per-mesh-shape measurements (the PR-4 evidence rule generalized from
    per-kernel to per-mesh-shape) in which an achievable shape's encode
    beats the single-device number recorded beside it. Absent, stale,
    off-chip, or losing evidence keeps the current backend. The decision
    dict names the evidence file/round, the winning shape, and both
    numbers, so the selection stays auditable."""
    ev = load_mesh_evidence(art_dir)
    if ev is None:
        return False, {
            "reason": "no committed mesh evidence (MULTICHIP_r*.json)",
        }
    decision: dict = {
        "evidence_file": ev.get("_file"),
        "evidence_round": _evidence_round(ev),
    }
    shapes = ev.get("shapes")
    if not isinstance(shapes, dict) or not shapes:
        decision["reason"] = "evidence has no per-mesh-shape measurements"
        return False, decision
    if "tpu" not in str(ev.get("platform", "")).lower():
        decision["reason"] = "mesh evidence is not an on-chip measurement"
        return False, decision
    age = _evidence_age_days(ev)
    if age is None:
        decision["reason"] = (
            f"mesh evidence age unparseable (when={ev.get('when')!r}): treated as stale"
        )
        return False, decision
    if age > EVIDENCE_MAX_AGE_DAYS:
        decision["reason"] = (
            f"mesh evidence stale ({age:.0f}d > {EVIDENCE_MAX_AGE_DAYS:.0f}d)"
        )
        return False, decision
    single = (ev.get("single_device") or {}).get("encode_gbps")
    single = float(single) if isinstance(single, (int, float)) else 0.0
    best_label, best = None, 0.0
    for label, rec in shapes.items():
        if not isinstance(rec, dict):
            continue
        # parse `DPxSP` locally — this function runs in jax-free parents
        # (bench), so it must not import the parallel package
        parts = str(label).lower().split("x")
        if len(parts) != 2 or not all(p.isdigit() and int(p) > 0 for p in parts):
            continue
        dims = (int(parts[0]), int(parts[1]))
        if dims[0] * dims[1] > int(n_devices):
            continue  # shape not achievable on this pod
        if rec.get("match") is not True or rec.get("error"):
            # only a shape that COMPLETED byte-verification is evidence —
            # a missing `match` (e.g. a rebuild variant crashed after the
            # encode measurement landed) must not promote
            continue
        gbps = rec.get("encode_gbps")
        if not isinstance(gbps, (int, float)) or gbps <= 0:
            continue
        if single and gbps <= single:
            continue  # aggregate number must beat the single-device one
        if gbps > best:
            best_label, best = str(label), float(gbps)
    if best_label is None:
        decision["reason"] = (
            "no achievable mesh shape beats the single-device number"
            if single
            else "no achievable mesh shape with a usable encode measurement"
        )
        return False, decision
    rec = shapes[best_label]
    ring = rec.get("rebuild_ring_gbps")
    a2a = rec.get("rebuild_alltoall_gbps")
    variant = "ring"
    if isinstance(a2a, (int, float)) and (
        not isinstance(ring, (int, float)) or a2a > ring
    ):
        variant = "alltoall"
    decision.update(
        mesh_shape=best_label,
        mesh_rebuild=variant,
        encode_gbps=best,
        single_device_gbps=single or None,
        reason=(
            f"committed on-chip mesh evidence: {best_label} encode={best} "
            f"beats single-device {single}"
        ),
    )
    return True, decision


# -- committed CPU bench evidence (the xorsched promotion input) --------------


def _host_fingerprint() -> dict:
    """Identity of THIS host for same-host evidence matching: cpu model
    string + logical core count. Hostnames are ephemeral in the fleet;
    the model+cores pair is what decides whether a committed BENCH number
    was measured on silicon equivalent to the one now selecting."""
    model = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    if not model:
        import platform

        model = platform.processor() or platform.machine() or ""
    return {"cpu": model, "cores": int(os.cpu_count() or 0)}


def load_cpu_bench_evidence(art_dir: Optional[str] = None) -> Optional[dict]:
    """Newest committed `BENCH_r*.json` (repo root, beside MULTICHIP_r*)
    whose payload carries an `xor` section, unwrapped from the round
    wrapper ({"n", "cmd", "rc", "tail", "parsed"}) when present, with
    `_file` recording provenance. Rounds without an xor section are
    skipped rather than treated as de-promoting evidence: most bench
    rounds measure other subsystems, and the newest XOR measurement is
    the current truth for the xorsched decision. None when no readable
    artifact carries one."""
    art_dir = art_dir or _multichip_dir()
    try:
        names = sorted(
            f
            for f in os.listdir(art_dir)
            if f.startswith("BENCH_r") and f.endswith(".json")
        )
    except OSError:
        return None
    for name in reversed(names):
        try:
            import json

            with open(os.path.join(art_dir, name), encoding="utf-8") as f:
                ev = json.load(f)
            if not isinstance(ev, dict):
                continue
            if isinstance(ev.get("parsed"), dict):
                ev = dict(ev["parsed"], n=ev.get("n"))
            if isinstance(ev.get("xor"), dict):
                ev["_file"] = name
                return ev
        except (OSError, ValueError):
            continue  # an unreadable newest artifact must not hide older ones
    return None


def pick_cpu_backend(art_dir: Optional[str] = None) -> tuple[str, dict]:
    """The CPU-floor promotion decision: flip `auto`'s plain-CPU pick from
    the AVX2 library to the compiled XOR-schedule backend ONLY when a
    committed `BENCH_r*.json` carries a fresh SAME-HOST byte-verified
    measurement in which xorsched's encode beats the native number
    recorded in the SAME run (shared boxes are noisy — both numbers move
    with the noise together, so the committed ratio is the evidence;
    cross-host or cross-run comparisons never are). Absent, stale,
    other-host, unverified, or losing evidence keeps `_cpu_backend()`'s
    pick, and so does a libweedtpu.so predating the xor executor entry
    point — the pure-numpy interpreter cannot beat AVX2, only the
    GFNI/AVX2 transpose path can. The decision dict mirrors
    pick_device_backend's: evidence file/round, both numbers, reason."""
    base = _cpu_backend()
    ev = load_cpu_bench_evidence(art_dir)
    if ev is None:
        return base, {
            "backend": base,
            "reason": "no committed CPU bench evidence with an xor section",
        }
    xor = ev["xor"]
    decision: dict = {
        "evidence_file": ev.get("_file"),
        "evidence_round": _evidence_round(ev),
    }
    age = _evidence_age_days(xor)
    if age is None:
        decision.update(
            backend=base,
            reason=(
                f"xor evidence age unparseable (when={xor.get('when')!r}): "
                "treated as stale"
            ),
        )
        return base, decision
    if age > EVIDENCE_MAX_AGE_DAYS:
        decision.update(
            backend=base,
            reason=f"xor evidence stale ({age:.0f}d > {EVIDENCE_MAX_AGE_DAYS:.0f}d)",
        )
        return base, decision
    host = xor.get("host") or {}
    here = _host_fingerprint()
    if (
        str(host.get("cpu", "")) != here["cpu"]
        or int(host.get("cores") or 0) != here["cores"]
    ):
        decision.update(
            backend=base,
            reason=(
                f"evidence measured on a different host "
                f"({host.get('cpu')!r} x{host.get('cores')}): not transferable"
            ),
        )
        return base, decision
    if xor.get("match") is not True:
        # only a run that COMPLETED byte-verification against the numpy
        # oracle is evidence — a fast-but-wrong executor must not promote
        decision.update(
            backend=base,
            reason="xor evidence did not complete byte-verification",
        )
        return base, decision
    enc = xor.get("encode") or {}
    xs = enc.get("xorsched_gbps")
    nat = enc.get("native_gbps")
    decision["xorsched_gbps"] = float(xs) if isinstance(xs, (int, float)) else None
    decision["native_gbps"] = float(nat) if isinstance(nat, (int, float)) else None
    if (
        not isinstance(xs, (int, float))
        or not isinstance(nat, (int, float))
        or nat <= 0
    ):
        decision.update(
            backend=base,
            reason="xor evidence lacks a same-run xorsched/native encode pair",
        )
        return base, decision
    if xs <= nat:
        decision.update(
            backend=base,
            reason=(
                f"committed xorsched encode {xs} does not beat "
                f"same-run native {nat}"
            ),
        )
        return base, decision
    try:
        from seaweedfs_tpu.ops import xorsched as _xs_mod

        native_ok = _xs_mod.native_available()
    except Exception:  # noqa: BLE001 — a broken probe must not break auto
        native_ok = False
    if not native_ok:
        decision.update(
            backend=base,
            reason=(
                "libweedtpu.so lacks weedtpu_xor_schedule_apply "
                "(stale binary: run make -C native): library path keeps serving"
            ),
        )
        return base, decision
    decision.update(
        backend="xorsched",
        reason=(
            f"committed same-host bench: xorsched encode {xs} beats "
            f"same-run native {nat}"
        ),
    )
    return "xorsched", decision


def _export_selection(selection: dict) -> None:
    """Mirror the factory's decision into the Prometheus registry: the
    previously-selected label (if any) drops to 0 so a scrape shows ONE
    current backend (read-modify-write under a lock: concurrent factories
    must not leave two label-sets at 1)."""
    try:
        from seaweedfs_tpu import stats

        global _last_selection_labels
        backend = str(selection.get("backend", ""))
        source = str(selection.get("source", ""))
        with _selection_lock:
            prev = _last_selection_labels
            if prev is not None and prev != (backend, source):
                stats.EcBackendSelected.labels(*prev).set(0)
            stats.EcBackendSelected.labels(backend, source).set(1)
            _last_selection_labels = (backend, source)
    except Exception:  # noqa: BLE001 — metrics must never break the factory
        pass


_last_selection_labels: Optional[tuple] = None
_selection_lock = threading.Lock()


def new_encoder(
    data_shards: int = 10,
    parity_shards: int = 4,
    backend: str = "auto",
    matrix_kind: str = "vandermonde",
    family: Optional[str] = None,
) -> Encoder:
    """Encoder factory — the backend-selection seam (SURVEY.md §1, §7.1 step 5).

    `family` names a registered code geometry (CODE_FAMILIES) and overrides
    data_shards/parity_shards/matrix_kind — the geometry-flexible entry
    point `ec.convert` and geometry-recording `.eci` mounts use. Without
    it the explicit shard counts apply (legacy default: the 10+4
    Vandermonde wire geometry).

    backend: "auto" picks the measured-fastest device path on TPU, the XLA
    path on other accelerators, and the C++ AVX2 library (numpy if it can't
    load) on plain CPU — the reference's SIMD role; explicit values force a
    path. `WEEDTPU_BACKEND` overrides an "auto" request (operator seam;
    explicit callers are never overridden).

    On TPU the decision is EVIDENCE-BASED: `pick_device_backend` reads the
    newest committed `artifacts/DEVICE_MEASUREMENT_r*.json` and flips to
    the fused Pallas kernel (with the winning variant's tile/mxu config)
    only when a committed on-chip steady-state number beats the XLA path's;
    absent, stale, or losing evidence keeps the measured-safe XLA default
    (r4 numbers: XLA 31-32 GB/s vs fused 18.7). The decision lands on
    `encoder.selection`, in the `weedtpu_ec_backend_selected` stats gauge,
    and in bench.py output. backend="pallas" still forces the fused kernel.

    POD promotion: with more than one device, `pick_mesh_backend` extends
    the same rule to per-mesh-shape measurements in the committed
    `MULTICHIP_r*.json` artifact — fresh on-chip evidence of an achievable
    dp x sp shape beating the single-device encode flips `auto` to the
    mesh backend (shape + ring/all_to_all rebuild variant from the
    evidence); absent/stale/off-chip mesh evidence keeps whatever the
    per-chip decision chose. backend="mesh" forces the mesh path with
    `WEEDTPU_MESH_SHAPE`/`WEEDTPU_MESH_REBUILD` (or evidence/default)
    config; the selection audit records the mesh shape and evidence round.

    CPU promotion: on plain-CPU hosts `pick_cpu_backend` extends the same
    evidence rule to the compiled XOR-schedule backend — a fresh committed
    `BENCH_r*.json` xor section measured on THIS host (cpu model + cores
    fingerprint) in which xorsched's byte-verified encode beats the native
    AVX2 number from the same run flips `auto` to "xorsched"; absent,
    stale, other-host, or losing evidence keeps the AVX2 library (numpy
    when it can't load).
    """
    if family is not None:
        geom = geometry_for(family)
        data_shards, parity_shards = geom.data_shards, geom.parity_shards
        matrix_kind = geom.matrix_kind
    selection: dict = {"requested": backend}
    pallas_kwargs: dict = {}
    if backend == "auto":
        env = config.env("WEEDTPU_BACKEND").strip().lower()
        if env and env != "auto":
            if env not in _BACKENDS:
                raise ValueError(
                    f"WEEDTPU_BACKEND={env!r} is not one of {('auto',) + _BACKENDS}"
                )
            backend = env
            selection.update(backend=backend, source="env:WEEDTPU_BACKEND")
    if backend == "auto":
        try:
            import jax

            from seaweedfs_tpu.utils.devices import honor_platform_env, is_tpu_device

            # JAX_PLATFORMS=cpu must win over the axon sitecustomize or a
            # cpu-pinned server process blocks on the one-client TPU tunnel
            honor_platform_env()
            d = jax.devices()[0]
            n_dev = jax.device_count()
            if is_tpu_device(d):
                backend, decision = pick_device_backend()
                selection.update(decision)
                # provenance must be honest: absent evidence is a default,
                # not an evidence-based decision
                selection["source"] = (
                    "on-chip-evidence"
                    if decision.get("evidence_file")
                    else "tpu-default-no-evidence"
                )
                if backend == "pallas":
                    pallas_kwargs = {
                        "pallas_mxu": decision.get("pallas_mxu", "int8"),
                        "pallas_tile": decision.get("pallas_tile"),
                    }
                # pod promotion: >1 device + committed per-mesh-shape
                # evidence outranks any per-chip kernel choice (the
                # aggregate number is the one the rebuild target is
                # stated against)
                if n_dev > 1:
                    mesh_ok, mesh_dec = pick_mesh_backend(n_dev)
                    selection["mesh"] = mesh_dec
                    if mesh_ok:
                        backend = "mesh"
                        dims = tuple(
                            int(p) for p in mesh_dec["mesh_shape"].split("x")
                        )
                        pallas_kwargs = {
                            "mesh_shape": dims,
                            "mesh_rebuild": mesh_dec["mesh_rebuild"],
                        }
                        selection.update(
                            backend="mesh",
                            source="mesh-evidence",
                            reason=mesh_dec["reason"],
                        )
            elif d.platform != "cpu":
                backend = "jax"
                selection.update(
                    backend="jax", source="platform",
                    reason=f"non-TPU accelerator ({d.platform}): XLA path",
                )
            else:
                backend, cpu_dec = pick_cpu_backend()
                selection.update(cpu_dec)
                # provenance must be honest: promotion (or an explicit
                # keep-native verdict) backed by a committed artifact is
                # evidence; everything else is the platform default
                selection["source"] = (
                    "cpu-bench-evidence"
                    if cpu_dec.get("evidence_file")
                    else "platform"
                )
            if n_dev > 1 and "mesh" not in selection:
                # audit-only on non-TPU multi-device hosts: the decision
                # dict records WHY the pod path is not promoted here, so
                # `ec.backend` can print it (off-chip hosts never promote
                # even when committed evidence would qualify)
                mesh_ok, mesh_dec = pick_mesh_backend(n_dev)
                if mesh_ok:
                    mesh_dec = dict(
                        mesh_dec,
                        reason="qualifying evidence exists but this host "
                        "is not a TPU pod: not promoted",
                    )
                selection["mesh"] = mesh_dec
        except Exception:
            # jax-free hosts still honor committed CPU bench evidence —
            # pick_cpu_backend touches only os/json/ctypes
            try:
                backend, cpu_dec = pick_cpu_backend()
                selection.update(cpu_dec)
                selection["source"] = (
                    "cpu-bench-evidence"
                    if cpu_dec.get("evidence_file")
                    else "platform"
                )
            except Exception:  # noqa: BLE001 — the factory must not fail
                backend = _cpu_backend()
                selection.update(
                    backend=backend, source="platform",
                    reason="no jax backend: cpu fallback",
                )
    else:
        selection.setdefault("backend", backend)
        selection.setdefault("source", "explicit")
    enc = Encoder(
        data_shards, parity_shards, matrix_kind=matrix_kind, backend=backend,
        **pallas_kwargs,
    )
    if enc.backend == "mesh":
        # audit must name the ACTUAL mesh (explicit/env requests resolve
        # their shape inside MeshDispatch) — build it now so a mesh
        # encoder that cannot construct its mesh fails at the factory,
        # not mid-stream
        md = enc._mesh_dispatch()
        selection.setdefault("mesh_shape", md.shape_str())
        selection.setdefault("mesh_rebuild", md.rebuild_variant)
        selection["mesh_devices"] = md.n_devices
        selection["audit"] = (
            f"mesh {md.shape_str()} ({md.n_devices} devices, "
            f"rebuild={md.rebuild_variant}, evidence="
            f"r{selection.get('mesh', {}).get('evidence_round', '-')})"
        )
    enc.selection = selection
    _export_selection(selection)
    return enc
