"""JAX/XLA Reed-Solomon kernels — the TPU replacement for the reference codec's
SIMD assembly (klauspost/reedsolomon galois_amd64.s PSHUFB nibble tables
[VERIFY: reference mount empty, SURVEY.md §2.2]).

Formulation (SURVEY.md §7.2): GF(2^8) multiply-by-constant is linear over
GF(2), so an (R x C) GF(2^8) coding matrix lifts to an (R*8 x C*8) binary
matrix B. Unpack data bytes into little-endian bit-planes, then

    out_bits = (B @ in_bits) mod 2

is the exact GF(2^8) matrix product — one int8 matmul on the MXU with an
int32 accumulator (K = C*8 <= 112*8 < 2^31, no overflow) and a final `& 1`.
Encode, reconstruct, and verify all reduce to this one kernel with different
(host-built, cached) matrices. Arithmetic intensity is fixed (~R*8 int8
MACs/byte), so the design problem is feeding the MXU — callers batch tiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from seaweedfs_tpu.ops import gf8


def bytes_to_bits(x: jax.Array) -> jax.Array:
    """(..., C, N) uint8 -> (..., C*8, N) int8 little-endian bit-planes."""
    *lead, c, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(*lead, c * 8, n).astype(jnp.int8)


def bits_to_bytes(bits: jax.Array) -> jax.Array:
    """(..., R*8, N) int -> (..., R, N) uint8, little-endian bit-planes."""
    *lead, r8, n = bits.shape
    b = bits.reshape(*lead, r8 // 8, 8, n).astype(jnp.uint8)
    out = b[..., 0, :]
    for i in range(1, 8):
        out = out | (b[..., i, :] << np.uint8(i))
    return out


def _gf_apply_impl(b_bits: jax.Array, data: jax.Array) -> jax.Array:
    bits = bytes_to_bits(data)
    if data.ndim == 2:
        acc = jax.lax.dot_general(
            b_bits,
            bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
    else:
        acc = jnp.einsum(
            "rk,bkn->brn", b_bits, bits, preferred_element_type=jnp.int32
        )
    return bits_to_bytes(acc & 1)


@jax.jit
def gf_apply(b_bits: jax.Array, data: jax.Array) -> jax.Array:
    """Apply a lifted GF(2^8) matrix to byte shards.

    b_bits: (R*8, C*8) int8 binary matrix (from gf8.gf_matrix_to_bits).
    data:   (C, N) or (batch, C, N) uint8 input shards.
    Returns (R, N) / (batch, R, N) uint8 output shards.
    """
    return _gf_apply_impl(b_bits, data)


# Donated twin: the data argument's device buffer is donated to XLA. The
# (C, N) input cannot alias the smaller (R<=4, N) output (XLA aliasing
# requires matching shape+dtype), so this is NOT output aliasing — it is a
# deterministic early-release hint: the batch's input HBM is freed as soon
# as the dispatch consumes it rather than when host-side references die,
# bounding a depth-N pipeline's inflight footprint. Whether that moves the
# steady number is one of the device-window hypotheses to measure. Only
# selected off-CPU — XLA CPU ignores donation and warns.
_gf_apply_donated = jax.jit(_gf_apply_impl, donate_argnums=(1,))


@functools.lru_cache(maxsize=1)
def donation_supported() -> bool:
    """Buffer donation is a no-op (plus a warning per dispatch) on the XLA
    CPU backend; only the accelerator paths should request it."""
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:  # noqa: BLE001 — no backend: no donation either
        return False


@functools.lru_cache(maxsize=256)
def _lifted(matrix_key) -> jax.Array:
    rows = np.array(matrix_key, dtype=np.uint8)
    return jnp.asarray(gf8.gf_matrix_to_bits(rows), dtype=jnp.int8)


def lifted_matrix(m: np.ndarray) -> jax.Array:
    """Device int8 binary lift of a GF(2^8) matrix, cached by value."""
    m = np.asarray(m, dtype=np.uint8)
    return _lifted(tuple(tuple(int(v) for v in row) for row in m))


def encode_parity(data: jax.Array, parity_m: np.ndarray) -> jax.Array:
    """data: (D, N) or (B, D, N) uint8 -> parity (P, N) / (B, P, N)."""
    return gf_apply(lifted_matrix(parity_m), data)


def apply_matrix(m: np.ndarray, shards: jax.Array, donate: bool = False) -> jax.Array:
    """Apply an arbitrary GF(2^8) matrix (e.g. a cached decode matrix).

    donate=True routes through the donated jit so the input's device buffer
    is released the moment the dispatch consumes it (streaming pipelines
    dispatch hundreds of same-shaped batches; the early release keeps the
    inflight HBM footprint at depth x (in + out) instead of trusting
    host-side GC timing — see the donated-twin note above for why this is
    a release hint, not output aliasing). The host array is explicitly
    device_put first so the donated buffer is one jax owns — never a
    zero-copy alias of caller memory."""
    b = lifted_matrix(m)
    if donate and donation_supported():
        return _gf_apply_donated(b, jax.device_put(jnp.asarray(shards)))
    return gf_apply(b, shards)
