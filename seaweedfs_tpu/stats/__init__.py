"""Metrics — mirror of weed/stats/metrics.go [VERIFY: mount empty;
SURVEY.md §2.1 "Metrics" row, §5]: Prometheus-model counters / gauges /
histograms on a process-global registry, exposed in text exposition
format. Stdlib-only (the prometheus client isn't a dependency); the
format is wire-compatible with Prometheus scrapers.

North-star EC metrics (SURVEY.md §5) are pre-registered:
  weedtpu_ec_encode_bytes_total, weedtpu_ec_encode_seconds,
  weedtpu_ec_reconstruct_seconds (p50 shard-reconstruct latency source).
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional


class _Labeled:
    """One metric family; children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._children: dict[tuple, "_Child"] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str) -> "_Child":
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label values, got {len(values)}"
            )
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def _make_child(self) -> "_Child":
        raise NotImplementedError

    def _label_str(self, key: tuple) -> str:
        if not self.label_names:
            return ""
        pairs = ",".join(
            f'{n}="{v}"' for n, v in zip(self.label_names, key)
        )
        return "{" + pairs + "}"

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            lines.extend(child.render(self.name, self._label_str(key)))
        return lines


class _Child:
    def render(self, name: str, labels: str) -> list[str]:
        raise NotImplementedError


class _CounterChild(_Child):
    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    @property
    def value(self) -> float:
        return self._v

    def render(self, name, labels):
        return [f"{name}{labels} {self._v}"]


class Counter(_Labeled):
    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    # label-less sugar
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    @property
    def value(self) -> float:
        return self.labels().value


class _GaugeChild(_CounterChild):
    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(_Labeled):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self.labels().set(v)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    @property
    def value(self) -> float:
        return self.labels().value


_DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class _HistogramChild(_Child):
    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.total += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket upper bounds (ops dashboards;
        the p50 reconstruct-latency metric reads this)."""
        with self._lock:
            if self.total == 0:
                return 0.0
            rank = q * self.total
            seen = 0
            for i, c in enumerate(self.counts):
                seen += c
                if seen >= rank:
                    return self.buckets[i] if i < len(self.buckets) else float("inf")
            return float("inf")

    def render(self, name, labels):
        out = []
        cum = 0
        inner = labels[1:-1] if labels else ""
        for ub, c in zip(self.buckets, self.counts):
            cum += c
            le = f'le="{ub}"'
            lab = "{" + (inner + "," if inner else "") + le + "}"
            out.append(f"{name}_bucket{lab} {cum}")
        lab = "{" + (inner + "," if inner else "") + 'le="+Inf"' + "}"
        out.append(f"{name}_bucket{lab} {cum + self.counts[-1]}")
        out.append(f"{name}_sum{labels} {self.sum}")
        out.append(f"{name}_count{labels} {self.total}")
        return out


class Histogram(_Labeled):
    kind = "histogram"

    def __init__(self, name, help_, label_names=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def quantile(self, q: float) -> float:
        return self.labels().quantile(q)


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Labeled] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Labeled) -> _Labeled:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
        if not metric.label_names:
            metric.labels()  # label-less metrics expose a zero sample eagerly
        return metric

    def counter(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Counter:
        return self.register(Counter(name, help_, labels))  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Gauge:
        return self.register(Gauge(name, help_, labels))  # type: ignore[return-value]

    def histogram(
        self, name: str, help_: str = "", labels: tuple[str, ...] = (),
        buckets=_DEFAULT_BUCKETS,
    ) -> Histogram:
        return self.register(Histogram(name, help_, labels, buckets))  # type: ignore[return-value]

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# -- the framework's standard metric set (metrics.go analog) -----------------

VolumeServerRequestCounter = REGISTRY.counter(
    "weedtpu_volume_request_total", "volume server http/grpc requests", ("type",)
)
VolumeServerRequestHistogram = REGISTRY.histogram(
    "weedtpu_volume_request_seconds", "volume server request latency", ("type",)
)
MasterReceivedHeartbeatCounter = REGISTRY.counter(
    "weedtpu_master_received_heartbeats_total", "heartbeats ingested by the master"
)
MasterAssignCounter = REGISTRY.counter(
    "weedtpu_master_assign_total", "fid assignments served"
)
EcEncodeBytes = REGISTRY.counter(
    "weedtpu_ec_encode_bytes_total", "data bytes erasure-encoded"
)
EcEncodeSeconds = REGISTRY.histogram(
    "weedtpu_ec_encode_seconds", "wall time of volume EC encodes"
)
EcReconstructSeconds = REGISTRY.histogram(
    "weedtpu_ec_reconstruct_seconds",
    "latency of shard-interval reconstructions (p50 is the north-star)",
)
EcRebuildSeconds = REGISTRY.histogram(
    "weedtpu_ec_rebuild_seconds",
    "wall time of whole-shard ec.rebuild runs (local or remote survivors)",
)
EcRebuildRemoteBytes = REGISTRY.counter(
    "weedtpu_ec_rebuild_remote_bytes_total",
    "survivor bytes fetched from peer holders by distributed rebuilds",
)
EcRepairNetworkBytes = REGISTRY.counter(
    "weedtpu_ec_repair_network_bytes_total",
    "survivor payload bytes a rebuild target pulled over the network, by "
    "source mode: `trace` = GF projection rows (|missing| rows per holder "
    "group), `slab` = full survivor slabs — the repair-bandwidth headline "
    "(trace must run strictly below slab for the same rebuild)",
    ("mode",),
)
DegradedReadSeconds = REGISTRY.histogram(
    "weedtpu_degraded_read_seconds",
    "end-to-end latency of degraded (reconstructing) interval reads — the "
    "availability face of repair; weedload's SLO artifact tracks its p99",
)
HedgeFired = REGISTRY.counter(
    "weedtpu_hedge_fired_total",
    "backup shard fetches launched after the per-peer hedge delay",
)
HedgeWon = REGISTRY.counter(
    "weedtpu_hedge_won_total",
    "hedged fetches whose BACKUP answered first (the primary was slow or "
    "wedged; the hedge converted a tail-latency read into a normal one)",
)
CoalescedReads = REGISTRY.counter(
    "weedtpu_coalesced_reads_total",
    "degraded decodes absorbed by single-flight coalescing (waiters served "
    "from the leader's reconstruction instead of decoding again)",
)
ReadCacheHits = REGISTRY.counter(
    "weedtpu_read_cache_hits_total",
    "interval reads served from the decoded-interval cache — no fetch "
    "fan-out, no hedge, no reconstruct histogram observation",
)
ReadCacheMisses = REGISTRY.counter(
    "weedtpu_read_cache_misses_total",
    "decoded-interval cache lookups that found nothing (including "
    "TTL-expired entries) and fell through to the remote/reconstruct rungs",
)
ReadCacheEvictions = REGISTRY.counter(
    "weedtpu_read_cache_evictions_total",
    "decoded intervals dropped by the WEEDTPU_READ_CACHE_MB LRU budget or "
    "the WEEDTPU_READ_CACHE_TTL_S age bound",
)
ReadCacheInvalidations = REGISTRY.counter(
    "weedtpu_read_cache_invalidations_total",
    "decoded intervals flushed by correctness events — quarantine, shard "
    "remount, inline-ingest delta update, unmount/convert cut-over",
)
ReadCacheBytes = REGISTRY.gauge(
    "weedtpu_read_cache_bytes",
    "bytes currently held by the decoded-interval cache",
)
RebuildAdmissionWaits = REGISTRY.counter(
    "weedtpu_rebuild_admission_waits_total",
    "rebuild slab-read streams that had to WAIT for an admission token "
    "(the gate held a rebuild storm off the foreground read lane)",
)
DegradedReadErrors = REGISTRY.counter(
    "weedtpu_degraded_read_errors_total",
    "degraded reads failed, by typed error class (EcNoViableHolders, "
    "EcDegradedReadTimeout, EcShardCorrupt, HedgeMismatch)",
    ("class",),
)
ScrubBytesScanned = REGISTRY.counter(
    "weedtpu_scrub_bytes_scanned_total",
    "EC shard bytes CRC-verified by the background scrubber (rate-capped, "
    "admission-gated — repair traffic, never foreground)",
)
ScrubCorruptionsFound = REGISTRY.counter(
    "weedtpu_scrub_corruptions_found_total",
    "shard integrity failures detected by scrub/verify, by class: corrupt "
    "= CRC32 disagrees with the .eci record, truncated = file shorter "
    "than the stripe geometry demands, missing = mounted shard whose "
    "file vanished",
    ("class",),
)
ScrubRepairs = REGISTRY.counter(
    "weedtpu_scrub_repairs_total",
    "automatic repairs of quarantined shards, by result (ok = rebuilt or "
    "re-pulled, re-verified against .eci, and remounted; failed = attempt "
    "errored and was re-queued with backoff)",
    ("result",),
)
ScrubCycles = REGISTRY.counter(
    "weedtpu_scrub_cycles_total",
    "completed full passes of the background shard-integrity scrubber",
)
InlineEcRows = REGISTRY.counter(
    "weedtpu_inline_ec_rows_total",
    "large stripe rows encoded by the inline-EC ingest path (encode "
    "amortized into writes instead of a seal-time batch conversion)",
)
InlineEcBytes = REGISTRY.counter(
    "weedtpu_inline_ec_bytes_total",
    "volume data bytes whose parity was computed inline at ingest time",
)
InlineEcDeltaUpdates = REGISTRY.counter(
    "weedtpu_inline_ec_delta_updates_total",
    "delta parity updates applied to already-encoded inline stripe rows "
    "(overwrites folded in as GF rank-1 updates, not re-encodes)",
)
InlineEcDeltaBytes = REGISTRY.counter(
    "weedtpu_inline_ec_delta_bytes_total",
    "bytes computed+moved by inline delta parity updates (changed bytes x "
    "(2 data + 2x parity-shard read-modify-write) — compare against "
    "full-stripe re-encode bytes for the <0.5x small-write gate)",
)
InlineEcSeals = REGISTRY.counter(
    "weedtpu_inline_ec_seals_total",
    "volume seals by how the shard files were produced: inline = live "
    "stripe state finalized, resumed = journaled state recovered after a "
    "restart then finalized, warm = full .dat re-encode fallback",
    ("mode",),
)
InlineEcSpreadBytes = REGISTRY.counter(
    "weedtpu_inline_ec_spread_bytes_total",
    "parity bytes streamed to their placement-planned eventual holders "
    "DURING inline encode (WEEDTPU_INLINE_EC_SPREAD) — seal cut-over "
    "then ships only the tail",
)
InlineEcSpreadCommits = REGISTRY.counter(
    "weedtpu_inline_ec_spread_commits_total",
    "seal-time spread commits by result (ok = the target CRC-verified, "
    "mounted, and now hosts the parity shard; failed = the shard stayed "
    "local — spreading is an optimization, never an availability trade)",
    ("result",),
)
EcConvertBytes = REGISTRY.counter(
    "weedtpu_ec_convert_bytes_total",
    "bytes the geometry converter moved, by direction: read = source "
    "shard bytes consumed (pass-through data + survivor reads when a "
    "source data shard needed reconstructing), written = target shard "
    "bytes materialized — compare written against the decode->re-encode "
    "round trip's total I/O for the <=0.5x conversion gate",
    ("direction",),
)
EcConvertSeconds = REGISTRY.histogram(
    "weedtpu_ec_convert_seconds",
    "wall time of whole-volume geometry conversions (ec.convert)",
)
EcMeshDevices = REGISTRY.gauge(
    "weedtpu_ec_mesh_devices",
    "devices in the mesh backend's dp x sp device mesh (0 = every dispatch "
    "is single-device; set when a mesh encoder builds its mesh)",
)
EcDispatchTotal = REGISTRY.counter(
    "weedtpu_ec_dispatch_total",
    "codec matrix dispatches by backend (one batched device/host apply per "
    "increment — the per-backend traffic split behind the selection gauge)",
    ("backend",),
)
EcBackendSelected = REGISTRY.gauge(
    "weedtpu_ec_backend_selected",
    "codec backend chosen by new_encoder (1 = currently selected; source "
    "says why: on-chip-evidence, cpu-bench-evidence, platform, "
    "env:WEEDTPU_BACKEND, explicit)",
    ("backend", "source"),
)
XorschedCache = REGISTRY.gauge(
    "weedtpu_xorsched_schedule_cache",
    "compiled XOR-schedule LRU counters by event (hits/misses/evictions/"
    "size/cap), mirrored from ops.xorsched at each xorsched dispatch — "
    "steady-state serving should be all hits; churning misses mean the "
    "matrix working set exceeds WEEDTPU_XORSCHED_CACHE",
    ("event",),
)
RepairQueueDepth = REGISTRY.gauge(
    "weedtpu_repair_queue_depth",
    "under-replicated stripes currently queued by the master's fleet "
    "repair scheduler (ranked 2-missing strictly before 1-missing)",
)
RepairInflight = REGISTRY.gauge(
    "weedtpu_repair_inflight",
    "stripes whose batched rebuild dispatch is currently running, "
    "bounded by WEEDTPU_REPAIR_MAX_INFLIGHT",
)
RepairDispatch = REGISTRY.counter(
    "weedtpu_repair_dispatch_total",
    "stripe repairs the fleet scheduler dispatched, by missing-shard "
    "count at dispatch time (the priority class: '2' rows must start "
    "before '1' rows during a storm)",
    ("missing",),
)
RepairBackoff = REGISTRY.counter(
    "weedtpu_repair_backoff_total",
    "repair dispatches deferred by exponential backoff after a 503/"
    "RESOURCE_EXHAUSTED (the rebuild admission lane pushing back) or a "
    "transport failure",
)
RepairFusedVolumes = REGISTRY.counter(
    "weedtpu_repair_fused_volumes_total",
    "volumes whose rebuilds rode a fused batch dispatch (heterogeneous "
    "block-diagonal decode) — divided by dispatch count this is the "
    "batch occupancy a storm achieved",
)
RepairDispatchGroups = REGISTRY.gauge(
    "weedtpu_repair_dispatch_groups",
    "decode dispatch groups the most recent repair batch ran: 1 means "
    "the whole cohort fused into one block-diagonal dispatch, higher "
    "values mean per-signature-group dispatches (fusion off or absent)",
)
PlacementViolations = REGISTRY.gauge(
    "weedtpu_placement_violations",
    "stripes x domains currently violating the failure-domain invariant "
    "(a rack holding more than m shards of one stripe), from the repair "
    "scheduler's last status audit",
)
RpcServerSeconds = REGISTRY.histogram(
    "weedtpu_rpc_server_seconds",
    "server-side wall time of one gRPC method execution, by method — "
    "recorded at the generic dispatch seam, so every registered RPC is "
    "covered without per-handler wiring",
    ("method",),
)
RpcInflight = REGISTRY.gauge(
    "weedtpu_rpc_inflight",
    "gRPC method executions currently on a server worker thread, by "
    "method (a saturated worker pool shows up here before it shows up "
    "as tail latency)",
    ("method",),
)
VolumeServerVolumeGauge = REGISTRY.gauge(
    "weedtpu_volume_server_volumes", "volumes hosted", ("type",)
)
FilerRequestCounter = REGISTRY.counter(
    "weedtpu_filer_request_total", "filer http requests", ("type",)
)
S3RequestCounter = REGISTRY.counter(
    "weedtpu_s3_request_total", "s3 gateway requests", ("action",)
)


def start_metrics_server(port: int, host: str = "127.0.0.1"):
    """Standalone pull endpoint (the reference's -metricsPort). Returns the
    http.server instance (caller owns shutdown)."""
    import http.server
    import threading as _threading

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = REGISTRY.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.HTTPServer((host, port), H)
    _threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv
