"""North-star benchmark: device-side RS(10+4) EC encode throughput, GB/s/chip
(BASELINE.md config 2 analog: batched warm-volume encode on one chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol per BASELINE.md: GB/s counts DATA bytes in (10 shards) / kernel
wall time with data device-resident (the axon tunnel's ~25 MB/s host<->device
path would otherwise swamp the measurement; device-side is what the 40 GB/s
target is defined on). vs_baseline is value / 40.0 — the fraction of the
driver's 40 GB/s/chip target, since BASELINE.json.published is empty
(SURVEY.md §6: no reference numbers could be measured).
"""

import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

TARGET_GBPS = 40.0


def main() -> None:
    from seaweedfs_tpu.ops import gf8, rs_jax

    on_accel = any(d.platform != "cpu" for d in jax.devices())
    # batch x shards x tile-bytes; modest on CPU so dev runs finish
    if on_accel:
        b, n = 8, 4 * 1024 * 1024
        iters, warmup = 10, 3
    else:
        b, n = 2, 256 * 1024
        iters, warmup = 3, 1

    parity_bits = rs_jax.lifted_matrix(gf8.parity_matrix(10, 4))

    @jax.jit
    def encode(data):
        return rs_jax.gf_apply(parity_bits, data)

    key = jax.random.PRNGKey(0)
    data = jax.random.randint(key, (b, 10, n), 0, 256, dtype=jnp.uint8)
    data = jax.block_until_ready(data)

    for _ in range(warmup):
        jax.block_until_ready(encode(data))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(encode(data))
        times.append(time.perf_counter() - t0)

    data_bytes = b * 10 * n
    gbps = data_bytes / statistics.median(times) / 1e9
    print(
        json.dumps(
            {
                "metric": "ec_encode_device_gbps_10p4",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / TARGET_GBPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
