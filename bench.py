"""North-star benchmark: device-side RS(10+4) EC encode throughput, GB/s/chip
(BASELINE.md config 2 analog: batched warm-volume encode on one chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol per BASELINE.md: GB/s counts DATA bytes in (10 shards) / kernel
wall time with data device-resident (the axon tunnel's ~25 MB/s host<->device
path would otherwise swamp the measurement; device-side is what the 40 GB/s
target is defined on). vs_baseline is value / 40.0 — the fraction of the
driver's 40 GB/s/chip target, since BASELINE.json.published is empty
(SURVEY.md §6: no reference numbers could be measured).
"""

import json
import os
import statistics
import subprocess
import sys
import time

TARGET_GBPS = 40.0
WATCHDOG_SECS = int(os.environ.get("BENCH_WATCHDOG_SECS", "900"))


def _run_watchdogged() -> None:
    """Run the measurement in a child process; if the device tunnel wedges
    (init can block forever in native code, unkillable by in-process
    signals), kill the child and still emit the one JSON line."""
    env = dict(os.environ, BENCH_CHILD="1")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            timeout=WATCHDOG_SECS,
            stdout=subprocess.PIPE,
        )
        sys.stdout.buffer.write(proc.stdout)
        sys.exit(proc.returncode)
    except subprocess.TimeoutExpired:
        print(
            json.dumps(
                {
                    "metric": "ec_encode_device_gbps_10p4",
                    "value": 0.0,
                    "unit": "GB/s",
                    "vs_baseline": 0.0,
                    "error": f"watchdog: device unresponsive after {WATCHDOG_SECS}s",
                }
            ),
            flush=True,
        )
        sys.exit(2)


def main() -> None:
    import jax
    import jax.numpy as jnp

    # honor an explicit CPU request even though the axon sitecustomize
    # force-updates jax_platforms at interpreter start
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from seaweedfs_tpu.ops import gf8, rs_jax

    on_accel = any(d.platform != "cpu" for d in jax.devices())
    # batch x shards x tile-bytes; modest on CPU so dev runs finish
    if on_accel:
        b, n = 8, 4 * 1024 * 1024
        iters, warmup = 10, 3
    else:
        b, n = 2, 256 * 1024
        iters, warmup = 3, 1

    parity_bits = rs_jax.lifted_matrix(gf8.parity_matrix(10, 4))

    @jax.jit
    def encode_xla(data):
        return rs_jax.gf_apply(parity_bits, data)

    def encode_pallas(data):
        from seaweedfs_tpu.ops import rs_pallas

        return rs_pallas.gf_apply_fused(parity_bits, data)

    key = jax.random.PRNGKey(0)
    data = jax.random.randint(key, (b, 10, n), 0, 256, dtype=jnp.uint8)
    data = jax.block_until_ready(data)
    data_bytes = b * 10 * n

    # race the fused Pallas kernel against the pure-XLA path and report
    # the best; a kernel failure on an unexpected toolchain must never
    # zero the benchmark, so each candidate is fenced
    candidates = {"xla": encode_xla}
    if on_accel:
        candidates["pallas"] = encode_pallas
    best_gbps, best_name = 0.0, "none"
    for name, fn in candidates.items():
        try:
            for _ in range(warmup):
                jax.block_until_ready(fn(data))
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(data))
                times.append(time.perf_counter() - t0)
            gbps = data_bytes / statistics.median(times) / 1e9
        except Exception:  # noqa: BLE001 — fall back to the other path
            continue
        if gbps > best_gbps:
            best_gbps, best_name = gbps, name
    print(
        json.dumps(
            {
                "metric": "ec_encode_device_gbps_10p4",
                "value": round(best_gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(best_gbps / TARGET_GBPS, 4),
                "backend": best_name,
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD"):
        main()
    else:
        _run_watchdogged()
