"""North-star benchmark: RS(10+4) EC encode throughput, GB/s/chip, plus the
p50 shard-reconstruct latency (BASELINE.md configs 2 and 3).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Staged harness — measurements can never be zeroed by a wedged device tunnel:

  stage 1  device probe   child process calls jax.devices() on the default
                          (axon/TPU) platform under a hard timeout; the axon
                          tunnel has been observed to block >400 s in native
                          code, unkillable in-process, so the probe is a
                          separate pid the parent can kill.
  stage 2  CPU suite      always runs (JAX_PLATFORMS=cpu child): XLA-on-CPU
                          encode GB/s, numpy golden-path GB/s, the native
                          AVX2 library GB/s, and p50/p99 single-needle
                          reconstruct latency through the real EcVolume
                          degraded-read ladder.
  stage 3  device suite   only if a probe succeeded: compile-check the XLA
                          kernel at a tiny shape, then sweep XLA and Pallas
                          candidates on the real chip (each fenced — a
                          kernel failure must not zero the run). The probe
                          is retried after the CPU suite in case the tunnel
                          unwedged mid-run.
  last-ditch              if even the CPU child dies, the parent measures
                          the numpy path inline (no jax import) so `value`
                          is still a real measured number.

Protocol per BASELINE.md: GB/s counts DATA bytes in (10 shards) / kernel
wall time with data device-resident (device-side number; the axon tunnel's
host<->device path would otherwise swamp the measurement). vs_baseline is
value / 40.0 — the fraction of the driver's 40 GB/s/chip target, since
BASELINE.json.published is empty (SURVEY.md §6: no reference numbers exist).
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

TARGET_GBPS = 40.0
WATCHDOG_SECS = int(os.environ.get("BENCH_WATCHDOG_SECS", "900"))
PROBE_SECS = int(os.environ.get("BENCH_PROBE_SECS", "75"))
CPU_SUITE_SECS = int(os.environ.get("BENCH_CPU_SECS", "420"))


# ---------------------------------------------------------------------------
# child-process plumbing
# ---------------------------------------------------------------------------


def _run_child(mode: str, timeout: int, extra_env: dict | None = None):
    """Run this file with BENCH_MODE=mode; return (parsed JSON | None, err)."""
    env = dict(os.environ, BENCH_MODE=mode)
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            timeout=timeout,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    except Exception as e:  # noqa: BLE001
        return None, f"spawn failed: {e}"
    # stdout may carry jax warnings; the child's result is the last JSON line
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"exit={proc.returncode}, no JSON on stdout"


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


# ---------------------------------------------------------------------------
# stage 1: device probe (child)
# ---------------------------------------------------------------------------


def mode_probe() -> None:
    t0 = time.perf_counter()
    import jax

    devs = jax.devices()
    _emit(
        {
            "ok": True,
            "secs": round(time.perf_counter() - t0, 2),
            "platform": devs[0].platform,
            "devices": [str(d) for d in devs[:8]],
        }
    )


# ---------------------------------------------------------------------------
# timing helpers (shared by cpu + device suites)
# ---------------------------------------------------------------------------


def _median_time(fn, iters: int, warmup: int) -> float:
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _measure_numpy_gbps() -> float:
    """Golden-path table-driven GF(2^8) encode on host numpy."""
    import numpy as np

    from seaweedfs_tpu.ops.rs_codec import Encoder

    enc = Encoder(10, 4, backend="numpy")
    n = 1 << 20
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(10, n), dtype=np.uint8)
    t = _median_time(lambda: enc._apply(enc.parity_matrix, data), iters=3, warmup=1)
    return 10 * n / t / 1e9


def _measure_avx2() -> tuple[float | None, bool, float | None, int]:
    """The native C++ library (AVX2 PSHUFB when the host supports it):
    (single-core GB/s, avx2?, all-cores GB/s, host core count). The MT
    split mirrors the reference codec's WithAutoGoroutines; on a 1-core
    host the two numbers coincide."""
    import numpy as np

    from seaweedfs_tpu.ops import gf8
    from seaweedfs_tpu.utils import native

    cores = os.cpu_count() or 1
    if native.load() is None:
        return None, False, None, cores
    n = 8 << 20
    rng = np.random.default_rng(0)
    bufs = [rng.integers(0, 256, size=n, dtype=np.uint8).tobytes() for _ in range(10)]
    pm = gf8.parity_matrix(10, 4)
    t = _median_time(
        lambda: native.gf_matrix_apply_native(pm, bufs, n), iters=5, warmup=1
    )
    mt_gbps = None
    if cores > 1 and native.has_mt():  # a stale pre-MT .so must not report
        t_mt = _median_time(  # a duplicate ST number as "-mt"
            lambda: native.gf_matrix_apply_native(pm, bufs, n, threads=0),
            iters=5,
            warmup=1,
        )
        mt_gbps = 10 * n / t_mt / 1e9
    return 10 * n / t / 1e9, native.has_avx2(), mt_gbps, cores


def _measure_xla_gbps(batch: int, n: int, iters: int, warmup: int) -> float:
    """Jitted bit-plane matmul encode on whatever device jax resolves."""
    import jax

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    import jax.numpy as jnp

    from seaweedfs_tpu.ops import gf8, rs_jax

    parity_bits = rs_jax.lifted_matrix(gf8.parity_matrix(10, 4))

    @jax.jit
    def encode(data):
        return rs_jax.gf_apply(parity_bits, data)

    key = jax.random.PRNGKey(0)
    data = jax.block_until_ready(
        jax.random.randint(key, (batch, 10, n), 0, 256, dtype=jnp.uint8)
    )
    t = _median_time(lambda: jax.block_until_ready(encode(data)), iters, warmup)
    return batch * 10 * n / t / 1e9


def _measure_reconstruct_latency(tmpdir: str) -> dict:
    """p50/p99 single-needle degraded-read latency through the real EcVolume
    ladder (SURVEY §3.2): build a synthetic volume, stripe it, delete one
    data shard's file, then time reads that must reconstruct intervals from
    the 13 survivors. Cold = first read (builds+caches the decode matrix),
    warm = steady state."""
    import numpy as np

    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ec.ec_volume import EcVolume
    from seaweedfs_tpu.ops.rs_codec import Encoder
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage import types

    enc = Encoder(10, 4, backend="numpy")
    large, small = 64 << 10, 4 << 10
    base = os.path.join(tmpdir, "bench_vol")
    rng = np.random.default_rng(7)
    offset = types.NEEDLE_PADDING_SIZE
    blobs = [b"\x03" + bytes(7)]
    records = {}
    for nid in range(1, 301):
        body = int(rng.integers(256, 4096))
        total = types.actual_size(body, version=3)
        records[nid] = (offset, body)
        blobs.append(rng.integers(0, 256, size=total, dtype=np.uint8).tobytes())
        offset += total
    with open(base + ".dat", "wb") as f:
        f.write(b"".join(blobs))
    idx_mod.write_entries(
        [(nid, types.offset_to_bytes(off), sz) for nid, (off, sz) in records.items()],
        base + ".idx",
    )
    stripe.write_ec_files(
        base, large_block_size=large, small_block_size=small, encoder=enc
    )
    stripe.write_sorted_file_from_idx(base)
    lost = 2
    os.unlink(stripe.shard_file_name(base, lost))  # lose one data shard

    recon_ms: list[float] = []
    local_ms: list[float] = []
    cold_ms = None
    with EcVolume(
        base, encoder=enc, large_block_size=large, small_block_size=small
    ) as ev:
        if ev.warm_thread is not None:
            ev.warm_thread.join(30)  # mount warmup precedes traffic (r4)
        for nid in records:
            # only reads whose intervals hit the lost shard exercise the
            # reconstruct ladder; the rest are the local-read baseline
            _, _, intervals = ev.locate_needle(nid)
            degraded = any(
                iv.to_shard_id_and_offset(large, small)[0] == lost
                for iv in intervals
            )
            t0 = time.perf_counter()
            ev.read_needle_blob(nid)
            dt = (time.perf_counter() - t0) * 1e3
            if degraded and cold_ms is None:
                cold_ms = dt  # first reconstruct builds+caches decode matrix
            elif degraded:
                recon_ms.append(dt)
            else:
                local_ms.append(dt)
    recon_ms.sort()
    local_ms.sort()

    def q(xs, p):
        return round(xs[min(len(xs) - 1, int(p * len(xs)))], 4) if xs else None

    return {
        "reconstruct_p50_ms": q(recon_ms, 0.50),
        "reconstruct_p99_ms": q(recon_ms, 0.99),
        "reconstruct_cold_ms": round(cold_ms, 4) if cold_ms is not None else None,
        "reconstruct_reads": len(recon_ms) + (cold_ms is not None),
        "local_read_p50_ms": q(local_ms, 0.50),
    }


# ---------------------------------------------------------------------------
# stage 2: CPU suite (child, JAX_PLATFORMS=cpu)
# ---------------------------------------------------------------------------


def _measure_file_encode_e2e(td: str) -> dict:
    """BASELINE config-1 end-to-end: synthetic .dat file -> 14 shard files
    through write_ec_files (reads + kernel + writes + pipeline overlap),
    with the auto backend (native AVX2 on CPU, XLA bit-plane on TPU —
    the measured-fastest path per DEVICE_MEASUREMENT_r04)."""
    import numpy as np

    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ops.rs_codec import new_encoder

    size = 128 << 20  # dat bytes; tmpfs-backed in most CI images
    base = os.path.join(td, "9")
    rng = np.random.default_rng(5)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    enc = new_encoder()
    t0 = time.perf_counter()
    stripe.write_ec_files(
        base,
        large_block_size=4 << 20,
        small_block_size=1 << 20,
        encoder=enc,
    )
    dt = time.perf_counter() - t0
    out = {
        "file_encode_e2e_gbps": round(size / dt / 1e9, 3),
        "file_encode_backend": enc.backend,
        "file_encode_dat_mib": size >> 20,
    }
    # pipeline-depth sweep: what the depth-N inflight pipeline buys over
    # the one-deep scheme on this host. The run above already measured the
    # configured default depth; the remaining depths are measured here
    # (skipping whichever of them the default already covered, so an env
    # override like WEEDTPU_PIPELINE_DEPTH=1 never overwrites or drops a
    # sweep point).
    sweep = {str(stripe.DEFAULT_PIPELINE_DEPTH): out["file_encode_e2e_gbps"]}
    for depth in (1, 2, 4):
        if str(depth) in sweep:
            continue
        try:
            t0 = time.perf_counter()
            stripe.write_ec_files(
                base,
                large_block_size=4 << 20,
                small_block_size=1 << 20,
                encoder=enc,
                pipeline_depth=depth,
            )
            sweep[str(depth)] = round(size / (time.perf_counter() - t0) / 1e9, 3)
        except Exception as e:  # noqa: BLE001 — one depth must not zero the sweep
            sweep[str(depth)] = f"error: {str(e)[:120]}"
    out["file_encode_depth_sweep_gbps"] = sweep
    return out


def _measure_rebuild(td: str) -> dict:
    """ec_rebuild_gbps (the north star's SECOND target: >=10x the AVX2
    baseline on a 1 TB volume set): rebuild a 4-missing-shard volume end
    to end through the pipelined `rebuild_ec_files` (slab reads + one
    fused-decode device dispatch per batch + one-deep read/compute
    overlap), vs the serial numpy golden path (one blocking reconstruct
    per chunk — the pre-pipeline shape).

    GB/s counts the volume's data footprint (DATA_SHARDS x shard bytes) /
    wall time, matching the encode protocol. Loss pattern: 2 data + 2
    parity shards — the worst loss count RS(10+4) allows."""
    import numpy as np

    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT
    from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder
    from seaweedfs_tpu.utils import native as native_mod

    size = 128 << 20
    base = os.path.join(td, "rb")
    rng = np.random.default_rng(9)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    stripe.write_ec_files(
        base, large_block_size=4 << 20, small_block_size=1 << 20, encoder=new_encoder()
    )
    os.unlink(base + ".dat")
    missing = [0, 5, 11, 13]
    golden: dict[int, bytes] = {}
    for s in missing:
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    shard_size = len(golden[missing[0]])
    data_bytes = shard_size * DATA_SHARDS_COUNT

    def run(fn, enc, iters: int) -> tuple[float, bool]:
        """Best-of-`iters` rebuild wall time (first run swallows any XLA
        compile); outputs checked byte-identical against the survivors'
        original shard files after the last run."""
        times = []
        for _ in range(iters):
            for s in missing:
                os.unlink(stripe.shard_file_name(base, s))
            t0 = time.perf_counter()
            fn(base, encoder=enc, buffer_size=1 << 20)
            times.append(time.perf_counter() - t0)
        match = True
        for s in missing:
            with open(stripe.shard_file_name(base, s), "rb") as f:
                match = match and f.read() == golden[s]
        return data_bytes / min(times) / 1e9, match

    out: dict = {
        "dat_mib": size >> 20,
        "missing": missing,
        "protocol": "GB/s = data footprint (10 x shard bytes) / rebuild wall time",
    }
    serial, ok = run(stripe.rebuild_ec_files_serial, Encoder(10, 4, backend="numpy"), 2)
    out["numpy_serial_gbps"] = round(serial, 3)
    candidates: dict[str, float] = {}
    suite = [("numpy", Encoder(10, 4, backend="numpy"), 2)]
    if native_mod.load() is not None:
        suite.append(("native", Encoder(10, 4, backend="native"), 3))
    suite.append(("xla_cpu", Encoder(10, 4, backend="jax"), 3))
    for name, enc, iters in suite:
        try:
            gbps, match = run(stripe.rebuild_ec_files, enc, iters)
            out[f"{name}_gbps"] = round(gbps, 3)
            if not match:
                out[f"{name}_match"] = False  # a wrong rebuild is not a result
                continue
            candidates[name] = gbps
        except Exception as e:  # noqa: BLE001 — one backend must not zero the section
            out[f"{name}_error"] = str(e)[:200]
    if not ok:
        out["numpy_serial_match"] = False
    if candidates and serial > 0:
        best = max(candidates, key=candidates.get)
        out["best_backend"] = best
        out["pipelined_vs_serial"] = round(candidates[best] / serial, 2)
        # pipeline-depth sweep on the best backend: the depth-N inflight
        # rebuild pipeline vs the one-deep r5 scheme, same volume
        import functools

        enc_by_name = {name: e for name, e, _ in suite}
        sweep: dict = {}
        for depth in (1, 2, 4):
            try:
                gbps, match = run(
                    functools.partial(stripe.rebuild_ec_files, pipeline_depth=depth),
                    enc_by_name[best],
                    1,
                )
                sweep[str(depth)] = round(gbps, 3) if match else "mismatch"
            except Exception as e:  # noqa: BLE001 — one depth must not zero the sweep
                sweep[str(depth)] = f"error: {str(e)[:120]}"
        out["depth_sweep_gbps"] = sweep
    return out


def mode_cpu() -> None:
    import tempfile

    # the axon sitecustomize outranks JAX_PLATFORMS at interpreter start;
    # re-assert cpu before any jax backend touch or this child wedges on
    # the single-client TPU tunnel
    import jax  # noqa: F401

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()

    out: dict = {}
    try:
        out["xla_cpu_gbps"] = round(
            _measure_xla_gbps(batch=2, n=1 << 20, iters=5, warmup=2), 3
        )
    except Exception as e:  # noqa: BLE001
        out["xla_cpu_error"] = str(e)[:200]
    try:
        out["numpy_gbps"] = round(_measure_numpy_gbps(), 3)
    except Exception as e:  # noqa: BLE001
        out["numpy_error"] = str(e)[:200]
    try:
        gbps, avx2, mt_gbps, cores = _measure_avx2()
        out["host_cores"] = cores
        if gbps is not None:
            out["native_gbps"] = round(gbps, 3)
            out["native_avx2"] = avx2
        if mt_gbps is not None:
            out["native_mt_gbps"] = round(mt_gbps, 3)
    except Exception as e:  # noqa: BLE001
        out["native_error"] = str(e)[:200]
    try:
        with tempfile.TemporaryDirectory() as td:
            out.update(_measure_reconstruct_latency(td))
    except Exception as e:  # noqa: BLE001
        out["reconstruct_error"] = str(e)[:200]
    try:
        with tempfile.TemporaryDirectory() as td:
            out.update(_measure_file_encode_e2e(td))
    except Exception as e:  # noqa: BLE001
        out["file_encode_error"] = str(e)[:200]
    try:
        with tempfile.TemporaryDirectory() as td:
            out["ec_rebuild"] = _measure_rebuild(td)
    except Exception as e:  # noqa: BLE001
        out["ec_rebuild_error"] = str(e)[:200]
    try:
        from seaweedfs_tpu.ops.rs_codec import new_encoder

        # the factory's audited decision (evidence file, numbers, reason)
        out["auto_backend"] = new_encoder().selection
    except Exception as e:  # noqa: BLE001
        out["auto_backend_error"] = str(e)[:200]
    _emit(out)


# ---------------------------------------------------------------------------
# stage 2i: compiled XOR-schedule backend vs the native library (child)
# ---------------------------------------------------------------------------


def _min_time(fn, iters: int, warmup: int = 1) -> float:
    """min-of-iters wall time: the xorsched-vs-native gate is a SAME-RUN
    ratio on a shared noisy box, and min is the estimator least polluted
    by scheduler preemption (median still absorbs a slow neighbor)."""
    for _ in range(warmup):
        fn()
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _xor_matrix_forms(enc):
    """The four matrix shapes Encoder dispatches, as (name, matrix) —
    everything the schedule compiler must lower byte-exactly."""
    import numpy as np

    survivors = [i for i in range(14) if i not in (2, 11)][:10]
    decode = enc.reconstruction_matrix(survivors, [2, 11])
    plan = enc.repair_projection_plan(survivors, [2, 11])
    local = survivors[:5]  # a holder owning 5 of the survivors
    projection = np.stack([plan[s] for s in local], axis=1)
    delta = enc.parity_matrix[:, [3]]  # generator column: rank-1 update
    return [
        ("encode", enc.parity_matrix),
        ("decode", decode),
        ("projection", projection),
        ("delta", delta),
    ]


def mode_xor(smoke: bool = False) -> None:
    """BENCH_MODE=xor: the compiled XOR-schedule backend (ops/xorsched)
    vs the native AVX2 library, measured in the SAME run so the committed
    ratio is noise-immune (both numbers move with the box together).
    Compile and execute are reported separately — the schedule is built
    once per (matrix, tile) and cached, so steady-state cost is execute
    only. Every form is byte-verified against the gf8 numpy golden before
    any throughput number is trusted: `match` gates promotion in
    rs_codec.pick_cpu_backend. `--smoke` is the deterministic tier-1
    variant: byte-verification across tail-exercising widths, no timing
    (and no `when` stamp, so the output is stable run to run)."""
    import numpy as np

    from seaweedfs_tpu.ops import gf8, xorsched
    from seaweedfs_tpu.ops.rs_codec import Encoder, _host_fingerprint
    from seaweedfs_tpu.utils import config, native

    enc = Encoder(10, 4, backend="numpy")  # matrices only; no dispatch here
    forms = _xor_matrix_forms(enc)
    out: dict = {
        "host": _host_fingerprint(),
        "native_level": xorsched.native_level(),
        "tile_kb": config.env("WEEDTPU_XORSCHED_TILE_KB"),
    }
    if not smoke:
        out["when"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    # compile pass: fresh cache, per-form compile time + schedule stats
    xorsched.clear_schedule_cache()
    compile_info: dict = {}
    progs: dict = {}
    for name, m in forms:
        t0 = time.perf_counter()
        prog = xorsched.get_schedule(m)
        compile_info[f"{name}_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        compile_info[f"{name}_xors"] = prog.xor_count
        compile_info[f"{name}_raw_xors"] = prog.raw_xors
        compile_info[f"{name}_temps"] = prog.n_temps
        progs[name] = prog
    out["compile"] = compile_info

    # byte-verification: interpreter AND native executor vs the gf8 golden,
    # across widths that exercise full tiles, partial tiles, and the
    # sub-8-symbol scalar tails
    match = True
    verify: dict = {}
    widths = [1, 7, 31, 512, 4097] if smoke else [4097, 65536 + 488]
    rng = np.random.default_rng(0)
    for name, m in forms:
        ok = True
        for n in widths:
            stack = rng.integers(0, 256, size=(m.shape[1], n), dtype=np.uint8)
            golden = gf8.gf_mat_vec(m, stack)
            interp = np.stack(xorsched.apply(progs[name], list(stack)))
            ok = ok and bool((interp == golden).all())
            nat = xorsched.apply_native(progs[name], list(stack))
            if nat is not None:
                ok = ok and bool((np.stack(nat) == golden).all())
        verify[name] = ok
        match = match and ok
    out["verify"] = verify
    out["match"] = match
    out["cache"] = xorsched.schedule_cache_info()
    if smoke:
        out["ok"] = match
        _emit(out)
        return

    # throughput: xorsched native executor vs the AVX2 library, same data,
    # same run, min-of-iters (GB/s counts INPUT shard bytes / wall time,
    # matching _measure_avx2's convention)
    n = 8 << 20
    have_native_lib = native.load() is not None
    for name, m in forms:
        if name == "delta":
            continue  # 1-column rank-1 update: latency path, not bandwidth
        stack = rng.integers(0, 256, size=(m.shape[1], n), dtype=np.uint8)
        sec: dict = {}
        if xorsched.native_available():
            ins = list(stack)
            t = _min_time(lambda: xorsched.apply_native(progs[name], ins), iters=5)
            sec["xorsched_gbps"] = round(m.shape[1] * n / t / 1e9, 3)
        if have_native_lib:
            bufs = [s.tobytes() for s in stack]
            t = _min_time(
                lambda: native.gf_matrix_apply_native(m, bufs, n), iters=5
            )
            sec["native_gbps"] = round(m.shape[1] * n / t / 1e9, 3)
        if "xorsched_gbps" in sec and "native_gbps" in sec:
            sec["ratio"] = round(sec["xorsched_gbps"] / sec["native_gbps"], 2)
        out[name] = sec

    # the interpreter floor, small width + one iter: it exists as the
    # byte-exact oracle and stale-.so fallback, not as a fast path
    small = rng.integers(0, 256, size=(10, 1 << 20), dtype=np.uint8)
    ins_small = list(small)
    t = _min_time(lambda: xorsched.apply(progs["encode"], ins_small), iters=1, warmup=0)
    out["encode"]["interp_gbps"] = round(10 * (1 << 20) / t / 1e9, 3)

    enc_sec = out.get("encode", {})
    dec_sec = out.get("decode", {})
    out["gate"] = {
        "encode_2x": bool(enc_sec.get("ratio", 0) >= 2.0),
        "decode_parity": bool(dec_sec.get("ratio", 0) >= 1.0),
    }
    _emit(out)


def _rebatch_storm(smoke: bool):
    """(specs, n_signatures) for the mixed-signature rebuild storm.

    Each spec is (vid, dat_bytes, missing, encoder). Three geometries:
    the fleet default 10+4 vandermonde plus the converted-volume
    geometries 12+3 and 20+4 cauchy (what `weed ec.convert` leaves
    behind), with both 2-missing and 1-missing loss classes so the
    batch crosses every axis of the signature key. Several signatures
    carry two volumes each — grouping and fusion are both exercised.
    Volume sizes sit in the tens-of-KB range: the storm the fusion
    targets is SOAK_r12's dispatch-bound regime (many small volumes,
    each formerly paying a partial-width dispatch)."""
    from seaweedfs_tpu.ops.rs_codec import Encoder

    e10 = Encoder(10, 4, backend="xorsched")
    e12 = Encoder(12, 3, backend="xorsched", matrix_kind="cauchy")
    e20 = Encoder(20, 4, backend="xorsched", matrix_kind="cauchy")
    if smoke:
        pats = [
            (e10, [10, 13]),
            (e10, [10, 13]),  # shares the signature above
            (e10, [0]),
            (e12, [0, 12]),
            (e20, [20, 23]),
            (e12, [5]),
        ]
    else:
        pats = (
            [(e10, [10, 13])] * 2 + [(e10, [11, 12])] * 2
            + [(e10, [0, 5]), (e10, [2, 7])]
            + [(e10, [0])] * 2 + [(e10, [1]), (e10, [2])]
            + [(e12, [0, 12])] * 2 + [(e12, [3, 14]), (e12, [7, 13])]
            + [(e12, [5])] * 2 + [(e12, [6])]
            + [(e20, [20, 23])] * 2 + [(e20, [1, 21]), (e20, [5, 22])]
            + [(e20, [8])] * 2 + [(e20, [9])]
        )
    specs = []
    for vid, (enc, missing) in enumerate(pats, start=1):
        specs.append((vid, 24_000 + vid * 500, list(missing), enc))
    n_sigs = len({
        (enc.data_shards, enc.total_shards, getattr(enc, "matrix_kind", ""),
         tuple(missing))
        for _, _, missing, enc in specs
    })
    return specs, n_sigs


def mode_rebuild_batch(smoke: bool = False) -> None:
    """BENCH_MODE=rebuild_batch: heterogeneous rebuild fusion — a
    mixed-signature storm rebuilt in ONE block-diagonal fused dispatch
    (WEEDTPU_REBUILD_FUSE=on) vs the PR 16 per-signature-group dispatch
    loop (fuse off), measured in the SAME run. Both paths read the same
    survivor bytes and run the same staging-ring pipeline; the delta is
    pure per-dispatch overhead, which is exactly what a storm of small
    volumes pays. Every rebuilt shard is byte-compared against the
    encode-time golden before any wall number is trusted. `--smoke` is
    the deterministic tier-1 variant: byte accounting + dispatch-count
    asserts (homogeneous batch fuses to 1 trivially; heterogeneous batch
    fuses to 1 only via the block-diagonal path), no timing, no `when`
    stamp."""
    import tempfile

    import numpy as np

    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ops import xorsched
    from seaweedfs_tpu.ops.rs_codec import _host_fingerprint
    from seaweedfs_tpu.utils import config

    specs, n_sigs = _rebatch_storm(smoke)
    out: dict = {
        "kind": "rebuild_batch",
        "host": _host_fingerprint(),
        "native_level": xorsched.native_level(),
        "tile_kb": config.env("WEEDTPU_XORSCHED_TILE_KB"),
        "protocol": (
            "same-run fused (WEEDTPU_REBUILD_FUSE=on, one block-diagonal "
            "dispatch) vs unfused (per-signature-group dispatches) wall, "
            "min-of-iters; every rebuilt shard byte-compared vs the "
            "encode-time golden"
        ),
    }
    if not smoke:
        out["when"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    td = tempfile.mkdtemp(prefix="rebatch_")
    jobs = []
    golden: list[dict[int, bytes]] = []
    rng_total = 0
    for vid, size, missing, enc in specs:
        base = os.path.join(td, f"v{vid}")
        rng = np.random.default_rng(vid)
        with open(base + ".dat", "wb") as f:
            f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
        with open(base + ".idx", "wb"):
            pass
        stripe.write_ec_files(
            base, large_block_size=16 * 1024, small_block_size=4 * 1024,
            encoder=enc,
        )
        stripe.write_sorted_file_from_idx(base)
        gold: dict[int, bytes] = {}
        for s in missing:
            with open(stripe.shard_file_name(base, s), "rb") as f:
                gold[s] = f.read()
        golden.append(gold)
        shard_n = len(next(iter(gold.values())))
        rng_total += sum(len(b) for b in gold.values())
        os.unlink(base + ".dat")
        present = [s for s in range(enc.total_shards) if s not in missing]
        jobs.append({
            "base": base,
            "sources": {
                s: stripe.LocalSlabSource(stripe.shard_file_name(base, s))
                for s in present
            },
            "shard_size": shard_n,
            "missing": missing,
            "encoder": enc,
        })
    out["storm"] = {
        "volumes": len(jobs),
        "signatures": n_sigs,
        "geometries": ["10+4 vandermonde", "12+3 cauchy", "20+4 cauchy"],
        "missing_shard_bytes": rng_total,
    }

    def run(fuse: bool) -> tuple[float, dict]:
        for (vid, size, missing, enc), job in zip(specs, jobs):
            for s in missing:
                p = stripe.shard_file_name(job["base"], s)
                if os.path.exists(p):
                    os.unlink(p)
        t0 = time.perf_counter()
        res = stripe.rebuild_ec_files_batch(
            jobs, buffer_size=64 * 1024, max_batch_bytes=32 * 1024 * 1024,
            fuse=fuse,
        )
        wall = time.perf_counter() - t0
        if res["errors"]:
            raise RuntimeError(f"rebuild errors: {res['errors']}")
        return wall, res

    def verify() -> tuple[bool, int]:
        ok, checked = True, 0
        for (vid, size, missing, enc), gold, job in zip(specs, golden, jobs):
            for s in missing:
                with open(stripe.shard_file_name(job["base"], s), "rb") as f:
                    ok = ok and f.read() == gold[s]
                checked += 1
        return ok, checked

    try:
        _, res_f = run(True)
        ok_f, n_checked = verify()
        _, res_u = run(False)
        ok_u, _ = verify()
        out["fused"] = {
            "dispatch_groups": res_f["dispatch_groups"],
            "signature_groups": res_f["signature_groups"],
            "volumes_fused": res_f["volumes_fused"],
        }
        out["unfused"] = {"dispatch_groups": res_u["dispatch_groups"]}
        out["verify"] = {
            "shards_checked": n_checked,
            "fused_bytes_match": ok_f,
            "unfused_bytes_match": ok_u,
        }
        if smoke:
            # homogeneous control: one signature repeated — both paths
            # collapse to one dispatch, so any fused-vs-unfused dispatch
            # delta seen above is the heterogeneity, not batching itself
            homo = [j for j, (_, _, m, e) in zip(jobs, specs)
                    if e is specs[0][3] and m == [10, 13]]
            for fuse in (True, False):
                for job in homo:
                    for s in job["missing"]:
                        p = stripe.shard_file_name(job["base"], s)
                        if os.path.exists(p):
                            os.unlink(p)
                res_h = stripe.rebuild_ec_files_batch(
                    homo, buffer_size=64 * 1024,
                    max_batch_bytes=32 * 1024 * 1024, fuse=fuse,
                )
                out[f"homogeneous_{'fused' if fuse else 'unfused'}"] = {
                    "dispatch_groups": res_h["dispatch_groups"],
                    "signature_groups": res_h["signature_groups"],
                }
            out["rebuilt_bytes"] = rng_total
            out["ok"] = bool(
                ok_f and ok_u
                and res_f["dispatch_groups"] == 1
                and res_u["dispatch_groups"] == n_sigs
                and res_f["signature_groups"] == n_sigs
                and out["homogeneous_fused"]["dispatch_groups"] == 1
                and out["homogeneous_unfused"]["dispatch_groups"] == 1
            )
            _emit(out)
            return

        # throughput: min-of-iters on each side, warm (run() above already
        # paid schedule compiles and staging-ring first-touch)
        iters = 8
        wall_f = min(run(True)[0] for _ in range(iters))
        wall_u = min(run(False)[0] for _ in range(iters))
        out["fused"]["wall_ms"] = round(wall_f * 1e3, 3)
        out["unfused"]["wall_ms"] = round(wall_u * 1e3, 3)
        out["fused_speedup"] = round(wall_u / wall_f, 2)

        # executor width-scaling: the widest decode program in the storm,
        # replayed through the native executor at 1 thread vs
        # WEEDTPU_XORSCHED_THREADS>1 (threads=0 = hardware concurrency)
        cores = out["host"].get("cores", 0)
        e20 = specs[-1][3]
        survivors = [s for s in range(24) if s not in (20, 23)][:20]
        m = e20.reconstruction_matrix(survivors, [20, 23])
        prog = xorsched.get_schedule(m)
        stack = np.random.default_rng(7).integers(
            0, 256, size=(m.shape[1], 8 << 20), dtype=np.uint8
        )
        ins = list(stack)
        t1 = _min_time(lambda: xorsched.apply_native(prog, ins, threads=1), iters=5)
        tn = _min_time(lambda: xorsched.apply_native(prog, ins, threads=0), iters=5)
        thread_scaling = round(t1 / tn, 2)
        out["threads"] = {
            "cores": cores,
            "single_ms": round(t1 * 1e3, 2),
            "multi_ms": round(tn * 1e3, 2),
            "scaling": thread_scaling,
        }
        gate: dict = {
            "fused_speedup_15x": bool(out["fused_speedup"] >= 1.5),
            "bytes_match": bool(ok_f and ok_u),
            "one_dispatch": bool(res_f["dispatch_groups"] == 1),
        }
        if cores > 1:
            gate["thread_scaling_15x"] = bool(thread_scaling >= 1.5)
        else:
            gate["thread_scaling_15x"] = False
            out["threads"]["note"] = (
                f"single-core host (cores={cores}): width-parallel tiles "
                "timeslice one core, so >=1.5x executor scaling is not "
                "measurable here — gate honestly unmet, rerun on a "
                "multi-core host to claim it"
            )
        out["gate"] = gate
        _emit(out)
    finally:
        for job in jobs:
            for src in job["sources"].values():
                src.close()


# ---------------------------------------------------------------------------
# stage 2c: remote degraded-read ladder (child, JAX_PLATFORMS=cpu)
# ---------------------------------------------------------------------------


def mode_remote() -> None:
    """Two-server remote ladder (SURVEY §3.2 end to end), run twice:

    raw            loopback as-is. On THIS 1-core host a 'remote fetch'
                   costs CPU, not network, so the degraded read's parallel
                   survivor fan-out cannot reduce wall time here — the
                   numbers quantify per-fetch framing cost.
    simulated RTT  5 ms server-side delay per VolumeEcShardRead (models
                   the network that dominates real clusters; sleeping
                   releases the GIL, so overlap IS measurable on 1 core).
                   Done-criterion home: reconstruct_remote p50 should sit
                   within ~2x plain-remote p50 when fetches overlap.
    """
    out: dict = dict(_remote_ladder(delay_ms=0, n_fids=200))
    out["simulated_rtt_5ms"] = _remote_ladder(delay_ms=5, n_fids=100)
    out["host_cores"] = os.cpu_count()
    _emit(out)


def _remote_ladder(delay_ms: int, n_fids: int) -> dict:
    """One ladder pass: master + in-process owner + SUBPROCESS peer;
    EC-encode a volume on the owner, hand shards 7-13 to the peer, then
    time reads through the owner's HTTP data path in three classes:
      local    — every interval on the owner's own shards
      remote   — >=1 interval fetched from the peer via pooled
                 VolumeEcShardRead
      reconstruct_remote — a shard deleted everywhere: the owner
                 reconstructs from survivors, >=4 of them remote
    This is the path r3 could not measure (uncached lookups + per-read
    dials would have dominated; both are fixed in r4); the peer became a
    subprocess in r5 so owner-side fetch concurrency is not serialized
    against the peer's serving threads by the GIL."""
    import socket
    import subprocess
    import tempfile
    import urllib.request

    import jax  # noqa: F401

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()

    import numpy as np

    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.cluster.client import MasterClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.pb import VOLUME_SERVICE
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    out: dict = {}
    large, small = 64 << 10, 4 << 10
    peer_proc = None
    with tempfile.TemporaryDirectory() as td:
        master = MasterServer(port=0, reap_interval=3600)
        master.start()
        # The OWNER runs in-process (the read path under test). The PEER is
        # a real subprocess: with both nodes in one interpreter the GIL
        # serializes the degraded read's parallel survivor fetches against
        # the peer's own serving threads, hiding exactly the concurrency
        # the ladder exists to measure.
        d0 = os.path.join(td, "srv0")
        os.makedirs(d0)
        owner_vs = VolumeServer([d0], master.address, heartbeat_interval=0.3)
        owner_vs.start()
        d1 = os.path.join(td, "srv1")
        os.makedirs(d1)

        def _free_port() -> int:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        def _start_peer():
            """Launch the peer volume server subprocess (after the upload
            phase, so the benched volume deterministically lives on the
            in-process owner) and wait for its gRPC surface."""
            import grpc as _grpc

            peer_http, peer_grpc = _free_port(), _free_port()
            env = {**os.environ, "JAX_PLATFORMS": "cpu"}
            if delay_ms:
                env["WEEDTPU_BENCH_RPC_DELAY_MS"] = str(delay_ms)
            err_path = os.path.join(td, "peer.err")
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "seaweedfs_tpu", "volume",
                    "-port", str(peer_http), "-grpcPort", str(peer_grpc),
                    "-dir", d1, "-mserver", master.address,
                ],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=open(err_path, "wb"),
            )
            addr = f"127.0.0.1:{peer_grpc}"
            try:
                deadline0 = time.monotonic() + 60
                while True:
                    if proc.poll() is not None:  # died at startup: say why
                        with open(err_path, "rb") as ef:
                            tail = ef.read()[-500:].decode(errors="replace")
                        raise RuntimeError(
                            f"peer exited rc={proc.returncode}: {tail}"
                        )
                    if time.monotonic() > deadline0:
                        raise RuntimeError("peer not serving after 60s")
                    try:
                        with rpc.RpcClient(addr) as pc:
                            pc.call(
                                VOLUME_SERVICE, "VolumeStatus",
                                {"volume_id": 999999}, timeout=5,
                            )
                        break
                    except _grpc.RpcError as e:
                        if e.code() == _grpc.StatusCode.NOT_FOUND:
                            break  # server answered: it is up
                        time.sleep(0.5)
            except Exception:
                proc.terminate()  # never leak the subprocess on a failed start
                raise
            return proc, addr
        client = MasterClient(master.address)
        try:
            rng = np.random.default_rng(11)
            first = client.submit(rng.integers(0, 256, 8192, dtype=np.uint8).tobytes())
            vid = int(first.fid.split(",")[0])
            fids = [first.fid]
            while len(fids) < n_fids:
                a = client.assign()
                if int(a.fid.split(",")[0]) != vid:
                    continue
                size = int(rng.integers(512, 6000))
                client.upload(a.fid, rng.integers(0, 256, size, dtype=np.uint8).tobytes())
                fids.append(a.fid)
            owner = owner_vs
            assert owner.store.get_volume(vid) is not None, "volume not on owner"
            peer_proc, peer_grpc_addr = _start_peer()
            with rpc.RpcClient(owner.grpc_address) as oc:
                oc.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
                oc.call(VOLUME_SERVICE, "VolumeEcShardsGenerate",
                        {"volume_id": vid, "large_block_size": large,
                         "small_block_size": small})
            with rpc.RpcClient(peer_grpc_addr) as tc:
                tc.call(VOLUME_SERVICE, "VolumeEcShardsCopy",
                        {"volume_id": vid, "shard_ids": list(range(7, 14)),
                         "source_data_node": owner.grpc_address}, timeout=120)
            base = owner._base_path_for(vid)
            with rpc.RpcClient(owner.grpc_address) as oc:
                for s in range(7, 14):
                    os.remove(stripe.shard_file_name(base, s))
                oc.call(VOLUME_SERVICE, "VolumeDelete", {"volume_id": vid})
                oc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
            with rpc.RpcClient(peer_grpc_addr) as pc:
                pc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(master.topology.lookup_ec_shards(vid)) == 14:
                    break
                time.sleep(0.05)

            ev = owner.store.get_ec_volume(vid)
            lost = 3  # will be deleted everywhere for the reconstruct class

            from seaweedfs_tpu.storage.file_id import FileId

            def shard_ids_of(fid: str) -> set:
                nid = FileId.parse(fid).key
                _, _, ivs = ev.locate_needle(nid)
                return {iv.to_shard_id_and_offset(large, small)[0] for iv in ivs}

            classes: dict[str, list[str]] = {"local": [], "remote": [], "reconstruct_remote": []}
            for fid in fids:
                try:
                    sids = shard_ids_of(fid)
                except Exception:  # noqa: BLE001
                    continue
                if lost in sids:
                    classes["reconstruct_remote"].append(fid)
                elif any(s >= 7 for s in sids):
                    classes["remote"].append(fid)
                else:
                    classes["local"].append(fid)

            def read_via_owner(fid: str) -> bytes:
                with urllib.request.urlopen(
                    f"http://{owner.url}/{fid}", timeout=30
                ) as r:
                    return r.read()

            def time_class(fids_: list[str]) -> dict | None:
                if not fids_:
                    return None
                for f in fids_[:2]:
                    read_via_owner(f)  # warm compile/caches
                ms = []
                for _ in range(3):
                    for f in fids_:
                        t0 = time.perf_counter()
                        read_via_owner(f)
                        ms.append((time.perf_counter() - t0) * 1e3)
                ms.sort()
                return {
                    "p50_ms": round(ms[len(ms) // 2], 3),
                    "p99_ms": round(ms[min(len(ms) - 1, int(0.99 * len(ms)))], 3),
                    "n_reads": len(ms),
                }
            out["local"] = time_class(classes["local"])
            out["remote"] = time_class(classes["remote"])
            # now lose shard 3 everywhere: reads touching it reconstruct.
            # Owner holds 0..6 so it keeps 6 local survivors and must
            # fan out for >=4 remote ones — the parallel-fetch path.
            p = stripe.shard_file_name(owner._base_path_for(vid), lost)
            if os.path.exists(p):
                os.remove(p)
            evv = owner.store.get_ec_volume(vid)
            if evv is not None:
                evv.drop_local_shard(lost)
            out["reconstruct_remote"] = time_class(classes["reconstruct_remote"])
            out["class_sizes"] = {k: len(v) for k, v in classes.items()}
            out["peer"] = "subprocess"  # true parallelism, no shared GIL
        finally:
            client.close()
            owner_vs.stop()
            if peer_proc is not None:
                peer_proc.terminate()
                try:
                    peer_proc.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    peer_proc.kill()
            master.stop()
    return out


# ---------------------------------------------------------------------------
# stage 2e: remote-survivor distributed rebuild (child, JAX_PLATFORMS=cpu)
# ---------------------------------------------------------------------------


def mode_rebuild_remote() -> None:
    """The distributed half of the >=10x rebuild target: survivors live on a
    PEER volume server and the rebuild target streams them through the
    network-overlapped pipeline (VolumeEcShardSlabRead + RemoteSlabSource
    prefetch) while decoding. Reports local-vs-remote GB/s, the overlap
    efficiency (remote wall / max(network wall, decode wall) — 1.0 is
    perfect overlap), and the speedup over a serial fetch-then-decode
    remote baseline (same windows, same parallel fetch, no overlap)."""
    import tempfile

    import jax  # noqa: F401

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    with tempfile.TemporaryDirectory() as td:
        _emit(_measure_rebuild_remote(td))


def _measure_rebuild_remote(
    td: str,
    dat_bytes: int = 48 << 20,
    large: int = 4 << 20,
    small: int = 1 << 20,
    buffer_size: int = 128 << 10,
    max_batch_bytes: int = 4 << 20,
    prefetch_batches: int = 4,
    delay_ms: float | None = None,
    encoder=None,
) -> dict:
    """Two in-process volume servers + master: the peer holds data shards
    0-9, parity 10-13 is lost cluster-wide, and the (initially empty)
    rebuild target regenerates it via `VolumeEcShardsRebuild {remote:true}`.

    On this 1-core loopback host a remote fetch costs CPU, not network, so
    a server-side per-RPC sleep models the RTT real clusters pay
    (WEEDTPU_BENCH_RPC_DELAY_MS, the ladder bench's trick — sleeping
    releases the GIL, so overlap IS measurable). When `delay_ms` is None
    it is auto-tuned so the modeled network wall ~= the measured decode
    wall — the regime the repair literature says dominates at scale and
    exactly where overlap pays; the chosen value is recorded."""
    import shutil

    import numpy as np

    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    vid = 7
    missing = [10, 11, 12, 13]
    out: dict = {
        "dat_mib": dat_bytes >> 20,
        "missing": missing,
        "protocol": (
            "GB/s = data footprint (10 x shard bytes) / rebuild wall; "
            "overlap_efficiency = remote wall / max(network wall, decode "
            "wall), 1.0 = perfect overlap; serial baseline = same windowed "
            "parallel fetch, decode blocking between windows (no overlap)"
        ),
    }
    prev_delay = os.environ.get("WEEDTPU_BENCH_RPC_DELAY_MS")

    def set_delay(ms: float) -> None:
        if ms > 0:
            os.environ["WEEDTPU_BENCH_RPC_DELAY_MS"] = str(ms)
        else:
            os.environ.pop("WEEDTPU_BENCH_RPC_DELAY_MS", None)

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d_target, d_peer = os.path.join(td, "target"), os.path.join(td, "peer")
    os.makedirs(d_target)
    os.makedirs(d_peer)
    set_delay(0)  # no delay during setup/copies
    target = VolumeServer(
        [d_target], master.address, heartbeat_interval=0.3, encoder=encoder
    )
    target.start()
    peer = VolumeServer([d_peer], master.address, heartbeat_interval=0.3)
    peer.start()
    try:
        # -- build the volume on the peer, lose all parity everywhere ------
        base_peer = os.path.join(d_peer, str(vid))
        rng = np.random.default_rng(13)
        with open(base_peer + ".dat", "wb") as f:
            f.write(rng.integers(0, 256, dat_bytes, dtype=np.uint8).tobytes())
        with open(base_peer + ".idx", "wb"):
            pass
        stripe.write_ec_files(
            base_peer,
            large_block_size=large,
            small_block_size=small,
            encoder=target.store.encoder,
        )
        stripe.write_sorted_file_from_idx(base_peer)
        golden = {}
        for s in missing:
            with open(stripe.shard_file_name(base_peer, s), "rb") as f:
                golden[s] = f.read()
        shard_size = os.path.getsize(stripe.shard_file_name(base_peer, 0))
        data_bytes = shard_size * DATA_SHARDS_COUNT
        for s in missing:
            os.unlink(stripe.shard_file_name(base_peer, s))
        os.unlink(base_peer + ".dat")
        with rpc.RpcClient(peer.grpc_address) as pc:
            pc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if len(master.topology.lookup_ec_shards(vid)) >= DATA_SHARDS_COUNT:
                break
            time.sleep(0.05)
        registered = len(master.topology.lookup_ec_shards(vid))
        assert registered >= DATA_SHARDS_COUNT, (
            f"only {registered} survivor shards registered at the master"
        )

        chunks_per_batch = max(1, max_batch_bytes // (DATA_SHARDS_COUNT * buffer_size))
        span = chunks_per_batch * buffer_size
        n_batches = -(-shard_size // span)
        out["n_batches"] = n_batches

        # -- decode wall: same volume, all survivors LOCAL -----------------
        base_local = os.path.join(td, "local", str(vid))
        os.makedirs(os.path.dirname(base_local))
        for s in range(DATA_SHARDS_COUNT):
            shutil.copy(stripe.shard_file_name(base_peer, s), stripe.shard_file_name(base_local, s))
        for ext in (".ecx", ".eci"):
            shutil.copy(base_peer + ext, base_local + ext)
        t0 = time.perf_counter()
        stripe.rebuild_ec_files(
            base_local,
            encoder=target.store.encoder,
            buffer_size=buffer_size,
            max_batch_bytes=max_batch_bytes,
        )
        decode_wall = time.perf_counter() - t0
        out["local_rebuild_gbps"] = round(data_bytes / decode_wall / 1e9, 3)
        out["decode_wall_s"] = round(decode_wall, 3)
        out["backend"] = target.store.encoder.backend

        # -- model the network ---------------------------------------------
        # On this 1-core loopback host a slab transfer is mostly CPU (grpc
        # serialize/deserialize + CRC) and CPU cannot overlap with decode
        # CPU — only the injected per-RPC sleep (the true network
        # component on real clusters) is overlappable. Measure the pure
        # CPU transfer wall first, then size the modeled RTT so the sleep
        # component of a window ~= its full compute cost (transfer CPU +
        # decode) — the network-comparable-to-compute regime where the
        # repair literature says rebuilds live and overlap pays.

        def fetch_windows(decode: bool) -> float:
            """Windowed survivor fetch through the real slab sources —
            parallel across shards within a window, optionally decoding
            each window BLOCKING before the next (the no-overlap serial
            baseline); without decode it is the pure network wall."""
            from concurrent.futures import ThreadPoolExecutor

            from seaweedfs_tpu.cluster.volume_server import EC_REBUILD_FETCH_WORKERS

            ex = ThreadPoolExecutor(max_workers=EC_REBUILD_FETCH_WORKERS)
            srcs = target._remote_slab_sources(vid, list(range(DATA_SHARDS_COUNT)), ex)
            staging = np.empty((DATA_SHARDS_COUNT, span), dtype=np.uint8)
            enc = target.store.encoder
            t0 = time.perf_counter()
            try:
                for off in range(0, shard_size, span):
                    valid = min(span, shard_size - off)
                    width = -(-valid // buffer_size) * buffer_size
                    for s in range(DATA_SHARDS_COUNT):
                        srcs[s].prefetch(off, width)
                    for s in range(DATA_SHARDS_COUNT):
                        srcs[s].read_into(off, staging[s, :width])
                    if decode:
                        np.asarray(
                            enc.reconstruct_lazy(
                                staging[:, :width], list(range(DATA_SHARDS_COUNT)), missing
                            )
                        )
                return time.perf_counter() - t0
            finally:
                for s in srcs.values():
                    s.close()
                ex.shutdown(wait=False, cancel_futures=True)

        set_delay(0)
        transfer_cpu_wall = fetch_windows(decode=False)
        out["transfer_cpu_wall_s"] = round(transfer_cpu_wall, 3)
        if delay_ms is None:
            # one RPC per survivor per window -> `waves` sequential sleep
            # waves per window given the fetch pool size. The 3x factor
            # puts the run in the NETWORK-DOMINATED regime ("Practical
            # Considerations in Repairing Reed-Solomon Codes": repair I/O,
            # not arithmetic, gates at scale) — and since sleeps are
            # immune to this shared vCPU's steal bursts, the ratio is set
            # by overlap arithmetic instead of CPU-noise luck
            from seaweedfs_tpu.cluster.volume_server import EC_REBUILD_FETCH_WORKERS

            waves = -(-DATA_SHARDS_COUNT // EC_REBUILD_FETCH_WORKERS)
            delay_ms = max(
                1.0,
                3e3 * (transfer_cpu_wall + decode_wall) / max(1, n_batches) / waves,
            )
        out["rpc_delay_ms"] = round(delay_ms, 2)
        set_delay(delay_ms)
        network_wall = fetch_windows(decode=False)
        out["network_wall_s"] = round(network_wall, 3)
        # best-of-2 for the gated comparison, like _measure_rebuild's
        # run(): a vCPU-steal spike during ONE phase would otherwise skew
        # the ratio either way on this shared 1-core host
        serial_wall = min(fetch_windows(decode=True) for _ in range(2))
        out["serial_fetch_then_decode_s"] = round(serial_wall, 3)
        out["serial_fetch_then_decode_gbps"] = round(data_bytes / serial_wall / 1e9, 3)

        # -- the real thing: distributed rebuild on the target -------------
        base_target = target._base_path_for(vid)
        remote_wall = float("inf")
        for _ in range(2):
            for s in missing:  # a rerun must regenerate, not no-op
                p = stripe.shard_file_name(base_target, s)
                if os.path.exists(p):
                    os.unlink(p)
            t0 = time.perf_counter()
            with rpc.RpcClient(target.grpc_address) as tc:
                resp = tc.call(
                    VOLUME_SERVICE,
                    "VolumeEcShardsRebuild",
                    {
                        "volume_id": vid,
                        "remote": True,
                        # this section measures the SLAB overlap pipeline:
                        # its baselines above model full-slab fetches, so
                        # trace projections must not silently shrink the
                        # transfer (the trace comparison is ec_rebuild_trace)
                        "trace_mode": "off",
                        # SAME window geometry as the baselines above: the
                        # comparison must count identical modeled RTTs, or
                        # "overlap" would partly measure window-size choice
                        "buffer_size": buffer_size,
                        "max_batch_bytes": max_batch_bytes,
                        "prefetch_batches": prefetch_batches,
                    },
                    timeout=600,
                )
            remote_wall = min(remote_wall, time.perf_counter() - t0)
        match = True
        for s in missing:
            with open(stripe.shard_file_name(base_target, s), "rb") as f:
                match = match and f.read() == golden[s]
        out["rebuilt_shard_ids"] = resp.get("rebuilt_shard_ids")
        out["remote_survivors"] = resp.get("remote_survivors")
        out["match"] = match
        out["remote_rebuild_wall_s"] = round(remote_wall, 3)
        out["remote_rebuild_gbps"] = round(data_bytes / remote_wall / 1e9, 3)
        out["overlap_efficiency"] = round(
            remote_wall / max(network_wall, decode_wall), 3
        )
        out["pipelined_vs_serial_fetch_then_decode"] = round(
            serial_wall / remote_wall, 2
        )
        out["ok"] = bool(match and resp.get("rebuilt_shard_ids") == missing)
    finally:
        set_delay(0)
        if prev_delay is not None:
            os.environ["WEEDTPU_BENCH_RPC_DELAY_MS"] = prev_delay
        target.stop()
        peer.stop()
        master.stop()
    return out


# ---------------------------------------------------------------------------
# stage 2f: trace-repair rebuild — wire bytes and wall vs full slabs (child)
# ---------------------------------------------------------------------------


def mode_rebuild_trace() -> None:
    """Repair-bandwidth headline: the SAME single-shard distributed rebuild
    run in trace mode (holders ship GF-projected rows for their survivor
    groups) and in slab mode (full survivor slabs), reporting the
    wire-bytes ratio — the number the repair literature prices — plus
    wall clocks under the modeled-RTT network."""
    import tempfile

    import jax  # noqa: F401

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    with tempfile.TemporaryDirectory() as td:
        _emit(_measure_rebuild_trace(td))


def _measure_rebuild_trace(
    td: str,
    dat_bytes: int = 48 << 20,
    large: int = 4 << 20,
    small: int = 1 << 20,
    buffer_size: int = 128 << 10,
    max_batch_bytes: int = 4 << 20,
    prefetch_batches: int = 4,
    lost_shard: int = 3,
    delay_ms: float | None = None,
    encoder=None,
) -> dict:
    """Master + rebuild target + TWO peer holders: peer A holds shards 0-6
    (minus the lost one), peer B holds 7-13, the target holds nothing. One
    data shard is lost cluster-wide and the target rebuilds it twice over
    the RPC path — `trace_mode=on` then `trace_mode=off` — with identical
    window geometry. Wire bytes come from BOTH the EcRebuildResponse
    accounting and the weedtpu_ec_repair_network_bytes_total counter
    (in-process servers share the registry, so the counter deltas are the
    same numbers a scrape would show); rebuilt bytes are verified against
    golden both times. Trace mode's wire cost is holder-groups x repaired
    bytes — with survivors on 2 holders that is ~0.2x the 10 full slabs
    the slab path moves, and the acceptance gate is <= 0.6."""
    import shutil

    import numpy as np

    from seaweedfs_tpu import rpc, stats
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    vid = 11
    out: dict = {
        "dat_mib": dat_bytes >> 20,
        "lost_shard": lost_shard,
        "protocol": (
            "same single-shard distributed rebuild, trace vs slab sources, "
            "identical window geometry and modeled RTT; wire_ratio = trace "
            "bytes-on-wire / slab bytes-on-wire (holder groups x repaired "
            "bytes vs 10 full survivor slabs); both runs byte-verified "
            "against golden"
        ),
    }
    prev_delay = os.environ.get("WEEDTPU_BENCH_RPC_DELAY_MS")

    def set_delay(ms: float) -> None:
        if ms > 0:
            os.environ["WEEDTPU_BENCH_RPC_DELAY_MS"] = str(ms)
        else:
            os.environ.pop("WEEDTPU_BENCH_RPC_DELAY_MS", None)

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    dirs = [os.path.join(td, n) for n in ("target", "peer_a", "peer_b")]
    for d in dirs:
        os.makedirs(d)
    set_delay(0)  # no delay during setup
    target = VolumeServer(
        [dirs[0]], master.address, heartbeat_interval=0.3, encoder=encoder
    )
    peer_a = VolumeServer([dirs[1]], master.address, heartbeat_interval=0.3)
    peer_b = VolumeServer([dirs[2]], master.address, heartbeat_interval=0.3)
    servers = [target, peer_a, peer_b]
    for vs in servers:
        vs.start()
    try:
        # -- build on peer A, spread survivors, lose one data shard --------
        base_a = os.path.join(dirs[1], str(vid))
        rng = np.random.default_rng(29)
        with open(base_a + ".dat", "wb") as f:
            f.write(rng.integers(0, 256, dat_bytes, dtype=np.uint8).tobytes())
        with open(base_a + ".idx", "wb"):
            pass
        stripe.write_ec_files(
            base_a,
            large_block_size=large,
            small_block_size=small,
            encoder=target.store.encoder,
        )
        stripe.write_sorted_file_from_idx(base_a)
        with open(stripe.shard_file_name(base_a, lost_shard), "rb") as f:
            golden = f.read()
        shard_size = os.path.getsize(stripe.shard_file_name(base_a, 0))
        os.unlink(stripe.shard_file_name(base_a, lost_shard))
        os.unlink(base_a + ".dat")
        base_b = os.path.join(dirs[2], str(vid))
        moved = [s for s in range(7, 14)]
        for s in moved:
            os.replace(
                stripe.shard_file_name(base_a, s), stripe.shard_file_name(base_b, s)
            )
        for ext in (".ecx", ".eci"):
            shutil.copy(base_a + ext, base_b + ext)
        for vs in (peer_a, peer_b):
            with rpc.RpcClient(vs.grpc_address) as c:
                c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if len(master.topology.lookup_ec_shards(vid)) >= 13:
                break
            time.sleep(0.05)
        assert len(master.topology.lookup_ec_shards(vid)) >= DATA_SHARDS_COUNT

        out["shard_mib"] = round(shard_size / (1 << 20), 3)
        out["slab_baseline_bytes"] = DATA_SHARDS_COUNT * shard_size
        if delay_ms is None:
            # the same network-comparable-to-compute regime as the
            # rebuild_remote bench, sized off the data footprint: one
            # modeled RTT per bulk window request
            delay_ms = 2.0
        out["rpc_delay_ms"] = round(delay_ms, 2)
        base_target = target._base_path_for(vid)

        def run_once(trace_mode: str) -> tuple[dict, float, bool]:
            p = stripe.shard_file_name(base_target, lost_shard)
            if os.path.exists(p):
                os.unlink(p)  # a rerun must regenerate, not no-op
            t0 = time.perf_counter()
            with rpc.RpcClient(target.grpc_address) as tc:
                resp = tc.call(
                    VOLUME_SERVICE,
                    "VolumeEcShardsRebuild",
                    {
                        "volume_id": vid,
                        "remote": True,
                        "trace_mode": trace_mode,
                        "buffer_size": buffer_size,
                        "max_batch_bytes": max_batch_bytes,
                        "prefetch_batches": prefetch_batches,
                    },
                    timeout=600,
                )
            wall = time.perf_counter() - t0
            with open(p, "rb") as f:
                match = f.read() == golden
            return resp, wall, match

        set_delay(delay_ms)
        results: dict[str, dict] = {}
        for mode_name in ("trace", "slab"):
            counter = stats.EcRepairNetworkBytes.labels(mode_name)
            before = counter.value
            wall = float("inf")
            for _ in range(2):  # best-of-2 against vCPU steal spikes
                resp, w, match = run_once("on" if mode_name == "trace" else "off")
                wall = min(wall, w)
            results[mode_name] = {
                "wall_s": round(wall, 3),
                "wire_bytes": int(resp.get("wire_bytes") or 0),
                "counter_bytes_2_runs": int(counter.value - before),
                "mode_reported": resp.get("mode"),
                "match": bool(match),
                "rebuilt_shard_ids": resp.get("rebuilt_shard_ids"),
            }
            if mode_name == "trace":
                results[mode_name]["groups"] = resp.get("trace_groups")
                results[mode_name]["fallback"] = resp.get("trace_fallback")
        out["trace"] = results["trace"]
        out["slab"] = results["slab"]
        slab_wire = results["slab"]["wire_bytes"]
        out["wire_ratio"] = (
            round(results["trace"]["wire_bytes"] / slab_wire, 4) if slab_wire else None
        )
        out["wall_ratio"] = round(
            results["trace"]["wall_s"] / results["slab"]["wall_s"], 3
        )
        out["ok"] = bool(
            results["trace"]["match"]
            and results["slab"]["match"]
            and results["trace"]["mode_reported"] == "trace"
            and results["slab"]["mode_reported"] == "slab"
            and results["trace"]["rebuilt_shard_ids"] == [lost_shard]
            and out["wire_ratio"] is not None
            and out["wire_ratio"] <= 0.6
        )
    finally:
        set_delay(0)
        if prev_delay is not None:
            os.environ["WEEDTPU_BENCH_RPC_DELAY_MS"] = prev_delay
        for vs in servers:
            vs.stop()
        master.stop()
    return out


# ---------------------------------------------------------------------------
# stage 2g: inline-EC ingest — amortized encode-on-write + delta parity
# ---------------------------------------------------------------------------


def mode_ingest() -> None:
    """Write-heavy workload headline: a volume's bytes streamed through the
    encode-on-write stripe builder (poll per append burst) vs the warm
    batch conversion, plus the small-write delta-parity accounting — the
    < 0.5x bytes gate for <=1% stripe overwrites."""
    import tempfile

    import jax  # noqa: F401

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    with tempfile.TemporaryDirectory() as td:
        _emit(_measure_ingest(td))


def mode_convert() -> None:
    """BENCH_MODE=convert: geometry conversion vs the decode->re-encode
    oracle — byte identity asserted, bytes-moved accounting gated at
    <= 0.5x the oracle's total I/O for each geometry pair."""
    import tempfile

    import jax  # noqa: F401

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    with tempfile.TemporaryDirectory() as td:
        out = _measure_convert(td)
    out = {
        "when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "kind": "bench_convert",
        **out,
    }
    _emit(out)


def _measure_convert(
    td: str,
    dat_bytes: int = 192 << 20,
    large: int = 1 << 20,
    small: int = 256 << 10,
    buffer_size: int = 256 << 10,
    families: tuple = ("cauchy_12_3", "merge_20_4"),
    encoder=None,
) -> dict:
    """`ec.convert`'s engine vs the decode->re-encode oracle on the same
    volume bytes, one run per target family.

    Conversion: `convert_ec_files` streams the source shard set through
    the staging-ring pipeline into the staged target (+ journal + on-disk
    re-verify), instrumenting `bytes_read` (source bytes consumed) and
    `bytes_written` (target bytes materialized). Oracle: write_dat_file
    (decode) + write_ec_files on the target geometry — its I/O footprint
    is MEASURED from the real files (read data shards + write .dat +
    re-read .dat + write the target set) and asserted equal to the
    deterministic `reencode_oracle_bytes` formula, so the gate cannot
    drift from what the oracle actually does. Per family: staged output
    byte-compared against the oracle's shard set, and
    `bytes_written / oracle_total <= 0.5` is the committed gate."""
    import shutil

    import numpy as np

    from seaweedfs_tpu.ec import convert as convert_mod
    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ops.rs_codec import geometry_for, new_encoder

    enc = encoder or new_encoder()
    rng = np.random.default_rng(41)
    data = rng.integers(0, 256, dat_bytes, dtype=np.uint8).tobytes()
    base = os.path.join(td, "src", "7")
    os.makedirs(os.path.dirname(base))
    with open(base + ".dat", "wb") as f:
        f.write(data)
    t0 = time.perf_counter()
    stripe.write_ec_files(
        base, large_block_size=large, small_block_size=small,
        buffer_size=buffer_size, encoder=enc,
    )
    src_encode_s = time.perf_counter() - t0
    src_total = enc.total_shards
    out: dict = {
        "section": "ec_convert",
        "dat_mib": round(dat_bytes / (1 << 20), 2),
        "large_block": large,
        "small_block": small,
        "backend": enc.backend,
        "src_family": "rs_10_4",
        "src_encode_s": round(src_encode_s, 3),
        "protocol": (
            "convert = convert_ec_files (staged target + .ecc journal + "
            "on-disk re-verify), bytes_written = target bytes "
            "materialized; oracle = write_dat_file + write_ec_files on "
            "the target geometry, oracle_total = measured read data "
            "shards + write .dat + re-read .dat + write target set "
            "(asserted == the deterministic reencode_oracle_bytes "
            "formula); gate: bytes_written / oracle_total <= 0.5 AND "
            "staged output byte-identical to the oracle's"
        ),
        "pairs": {},
    }
    ok = True
    for fam in families:
        geom = geometry_for(fam)
        oracle_acct = convert_mod.reencode_oracle_bytes(base, fam)
        t0 = time.perf_counter()
        res = convert_mod.convert_ec_files(
            base, fam, encoder=enc, buffer_size=buffer_size
        )
        convert_s = time.perf_counter() - t0
        # real oracle run, I/O measured from the files it actually touches
        ob = os.path.join(td, f"oracle_{fam}", "7")
        os.makedirs(os.path.dirname(ob))
        for s in range(src_total):
            shutil.copy(
                stripe.shard_file_name(base, s), stripe.shard_file_name(ob, s)
            )
        shutil.copy(base + ".eci", ob + ".eci")
        t0 = time.perf_counter()
        stripe.write_dat_file(ob)
        decode_s = time.perf_counter() - t0
        oracle_dat = os.path.getsize(ob + ".dat")
        for s in range(src_total):
            os.unlink(stripe.shard_file_name(ob, s))
        tgt_enc = new_encoder(family=fam, backend=enc.backend)
        t0 = time.perf_counter()
        stripe.write_ec_files(
            ob, large_block_size=large, small_block_size=small,
            buffer_size=buffer_size, encoder=tgt_enc,
        )
        encode_s = time.perf_counter() - t0
        oracle_tgt = sum(
            os.path.getsize(stripe.shard_file_name(ob, s))
            for s in range(geom.total_shards)
        )
        measured_total = 3 * oracle_dat + oracle_tgt
        staged = convert_mod.stage_base(base)
        match = all(
            open(stripe.shard_file_name(staged, s), "rb").read()
            == open(stripe.shard_file_name(ob, s), "rb").read()
            for s in range(geom.total_shards)
        )
        ratio = (
            round(res["bytes_written"] / oracle_acct["total"], 4)
            if oracle_acct["total"]
            else None
        )
        pair_ok = (
            match
            and measured_total == oracle_acct["total"]
            and ratio is not None
            and ratio <= 0.5
        )
        ok = ok and pair_ok
        out["pairs"][fam] = {
            "target_shards": geom.total_shards,
            "convert_s": round(convert_s, 3),
            "oracle_s": round(decode_s + encode_s, 3),
            "bytes_read": res["bytes_read"],
            "bytes_written": res["bytes_written"],
            "reconstructed_bytes": res["reconstructed_bytes"],
            "oracle_total_bytes": oracle_acct["total"],
            "oracle_total_measured": measured_total,
            "moved_over_reencode": ratio,
            "convert_io_over_reencode": (
                round(
                    (res["bytes_read"] + res["bytes_written"])
                    / oracle_acct["total"],
                    4,
                )
                if oracle_acct["total"]
                else None
            ),
            "match": match,
            "ok": pair_ok,
        }
        convert_mod.discard_staged(base, keep_journal=False)
    out["gate"] = "bytes_written / oracle_total <= 0.5 per pair"
    out["ok"] = ok
    return out


def _measure_ingest(
    td: str,
    dat_bytes: int = 192 << 20,
    large: int = 1 << 20,
    small: int = 256 << 10,
    buffer_size: int = 256 << 10,
    append_chunk: int = 4 << 20,
    overwrite_fraction: float = 0.01,
    overwrite_count: int = 16,
    encoder=None,
) -> dict:
    """Inline-vs-warm encode on the same volume bytes.

    Inline: the .dat is appended in `append_chunk` bursts with a builder
    poll after each (the ingest write-path shape); amortized GB/s counts
    data bytes over the SUM of encode time (polls + seal), i.e. what the
    encoder actually spent, spread across ingest. Warm: one
    `write_ec_files` over the finished .dat. Output byte-identity is
    asserted, not assumed.

    Delta: `overwrite_count` random ranges totaling `overwrite_fraction`
    of the .dat are folded into a FULLY-encoded stripe via the journaled
    delta path; the gate compares deterministic BYTE counts (not
    timings): delta bytes computed/moved (changed x (2 data + 2x parity
    RMW)) must stay under 0.5x a full re-encode's dat read + 14 shard
    writes. Shards after the deltas are verified byte-identical to a
    warm encode of the mutated .dat."""
    import numpy as np

    from seaweedfs_tpu.ec import ingest, stripe
    from seaweedfs_tpu.ec.constants import TOTAL_SHARDS_COUNT
    from seaweedfs_tpu.ops.rs_codec import new_encoder

    enc = encoder or new_encoder()
    rng = np.random.default_rng(31)
    data = rng.integers(0, 256, dat_bytes, dtype=np.uint8).tobytes()
    out: dict = {
        "dat_mib": round(dat_bytes / (1 << 20), 2),
        "large_block": large,
        "small_block": small,
        "backend": enc.backend,
        "protocol": (
            "inline = append in bursts + builder poll per burst + seal; "
            "amortized GB/s = data bytes / (sum of poll secs + seal secs); "
            "warm = one write_ec_files over the finished .dat; both outputs "
            "byte-compared. delta gate compares BYTE counts: changed x "
            "(2 + 2 x parity RMW) vs dat read + 14 shard writes of a full "
            "re-encode, for <=1% overwrites"
        ),
    }

    # -- inline: stream-append + poll ---------------------------------------
    base_i = os.path.join(td, "inline", "5")
    os.makedirs(os.path.dirname(base_i))
    builder = ingest.InlineStripeBuilder(
        base_i, enc, large, small, buffer_size=buffer_size
    )
    encode_s = 0.0
    polls = 0
    with open(base_i + ".dat", "wb") as f:
        for off in range(0, dat_bytes, append_chunk):
            f.write(data[off : off + append_chunk])
            f.flush()
            t0 = time.perf_counter()
            if builder.poll():
                polls += 1
            encode_s += time.perf_counter() - t0
    t0 = time.perf_counter()
    info = builder.seal()
    seal_s = time.perf_counter() - t0
    out["inline"] = {
        "amortized_gbps": round(dat_bytes / (encode_s + seal_s) / 1e9, 3),
        "poll_s": round(encode_s, 3),
        "seal_s": round(seal_s, 3),
        "polls_with_work": polls,
        "rows_inline": info["rows_inline"],
        "rows_total": info["rows_total"],
    }

    # -- warm reference ------------------------------------------------------
    base_w = os.path.join(td, "warm", "5")
    os.makedirs(os.path.dirname(base_w))
    with open(base_w + ".dat", "wb") as f:
        f.write(data)
    t0 = time.perf_counter()
    stripe.write_ec_files(
        base_w, large_block_size=large, small_block_size=small,
        buffer_size=buffer_size, encoder=enc,
    )
    warm_s = time.perf_counter() - t0
    out["warm"] = {"gbps": round(dat_bytes / warm_s / 1e9, 3), "wall_s": round(warm_s, 3)}
    # the ROADMAP follow-up's headline: encode-on-write efficiency relative
    # to the warm batch conversion on the same bytes, same run (shared
    # host/disk noise cancels in the ratio)
    out["amortized_over_warm"] = round(
        out["inline"]["amortized_gbps"] / out["warm"]["gbps"], 4
    ) if out["warm"]["gbps"] else None
    match = all(
        open(stripe.shard_file_name(base_i, s), "rb").read()
        == open(stripe.shard_file_name(base_w, s), "rb").read()
        for s in range(TOTAL_SHARDS_COUNT)
    ) and open(base_i + ".eci", "rb").read() == open(base_w + ".eci", "rb").read()
    out["match"] = bool(match)

    # -- delta parity updates on a fully-encoded stripe ----------------------
    base_d = os.path.join(td, "delta", "5")
    os.makedirs(os.path.dirname(base_d))
    with open(base_d + ".dat", "wb") as f:
        f.write(data)
    b2 = ingest.InlineStripeBuilder(
        base_d, enc, large, small, buffer_size=buffer_size
    )
    b2.poll()
    encoded_limit = b2.encoded_limit()
    per = max(1, int(dat_bytes * overwrite_fraction) // overwrite_count)
    mutated = bytearray(data)
    t0 = time.perf_counter()
    for i in range(overwrite_count):
        off = int(rng.integers(0, max(1, encoded_limit - per)))
        new_seg = rng.integers(0, 256, per, dtype=np.uint8).tobytes()
        old_seg = bytes(mutated[off : off + per])

        def mutate(off=off, new_seg=new_seg):
            with open(base_d + ".dat", "r+b") as f:
                f.seek(off)
                f.write(new_seg)

        b2.overwrite(off, old_seg, new_seg, mutate=mutate)
        mutated[off : off + per] = new_seg
    delta_wall = time.perf_counter() - t0
    changed = b2.delta_stats["changed_bytes"]
    delta_bytes = b2.delta_stats["accounted_bytes"]
    b2.seal()
    shard_size = os.path.getsize(stripe.shard_file_name(base_d, 0))
    reencode_bytes = dat_bytes + TOTAL_SHARDS_COUNT * shard_size
    base_m = os.path.join(td, "mut", "5")
    os.makedirs(os.path.dirname(base_m))
    with open(base_m + ".dat", "wb") as f:
        f.write(bytes(mutated))
    t0 = time.perf_counter()
    stripe.write_ec_files(
        base_m, large_block_size=large, small_block_size=small,
        buffer_size=buffer_size, encoder=enc,
    )
    reencode_wall = time.perf_counter() - t0
    delta_match = all(
        open(stripe.shard_file_name(base_d, s), "rb").read()
        == open(stripe.shard_file_name(base_m, s), "rb").read()
        for s in range(TOTAL_SHARDS_COUNT)
    )
    out["delta"] = {
        "overwrites": overwrite_count,
        "overwrite_fraction": round(changed / dat_bytes, 5),
        "changed_bytes": int(changed),
        "delta_bytes": int(delta_bytes),
        "reencode_bytes": int(reencode_bytes),
        "bytes_ratio": round(delta_bytes / reencode_bytes, 5),
        "wall_s": round(delta_wall, 3),
        "reencode_wall_s": round(reencode_wall, 3),
        "wall_ratio": round(delta_wall / reencode_wall, 4) if reencode_wall else None,
        "match": bool(delta_match),
    }
    out["ok"] = bool(
        match and delta_match and out["delta"]["bytes_ratio"] < 0.5
    )
    return out


# ---------------------------------------------------------------------------
# stage 2h: mesh backend — pod-scale encode/rebuild per mesh shape
# ---------------------------------------------------------------------------


def mode_mesh() -> None:
    """Per-mesh-shape encode + ring-vs-all_to_all rebuild GB/s through the
    REAL file pipelines (write_ec_files / rebuild_ec_files with the mesh
    backend), byte-verified against the single-device oracle — emitted in
    the MULTICHIP_r*.json artifact format the `auto` promotion reads."""
    import tempfile

    import jax  # noqa: F401

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    with tempfile.TemporaryDirectory() as td:
        _emit(_measure_mesh(td))


def _measure_mesh(
    td: str,
    dat_bytes: int = 96 << 20,
    large: int = 1 << 20,
    small: int = 256 << 10,
    buffer_size: int = 256 << 10,
    max_batch_bytes: int = 32 << 20,
    shapes=None,
    lost=(0, 5, 11, 13),
) -> dict:
    """MULTICHIP_r06-format body: for each dp x sp shape, encode the same
    volume through the mesh streaming pipeline and rebuild the worst
    allowed loss through BOTH distributed formulations; every output is
    byte-compared against the single-device oracle files. Encode GB/s
    counts data bytes in; rebuild GB/s counts rebuilt shard bytes out
    (the repaired-bytes rate the >=10x target is stated against)."""
    import jax
    import numpy as np

    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder

    n_dev = len(jax.devices())
    d0 = jax.devices()[0]
    if shapes is None:
        shapes = [
            s
            for s in ((n_dev, 1), (n_dev // 2, 2), (n_dev // 4, 4))
            if s[0] >= 1 and s[0] * s[1] == n_dev
        ]
    out: dict = {
        "when": time.strftime("%FT%TZ", time.gmtime()),
        "kind": "multichip",
        "round": 6,
        "n_devices": n_dev,
        "platform": f"{d0.platform} ({getattr(d0, 'device_kind', '?')})",
        "protocol": (
            "per-shape encode/rebuild through the real ec/stripe file "
            "pipelines with the mesh backend; encode GB/s = data bytes / "
            "wall, rebuild GB/s = rebuilt shard bytes / wall; every shard "
            "file byte-compared vs the single-device oracle (match=false "
            "disqualifies the shape as promotion evidence)"
        ),
        "dat_mib": round(dat_bytes / (1 << 20), 2),
        "lost_shards": list(lost),
        "shapes": {},
    }
    rng = np.random.default_rng(23)
    data = rng.integers(0, 256, dat_bytes, dtype=np.uint8).tobytes()

    # single-device oracle: the auto encoder — UNLESS auto already
    # promoted to mesh (a prior on-chip evidence round landed), in which
    # case the oracle must be forced back to the per-chip path or the
    # artifact's single_device baseline would itself be the pod number
    # and no shape could ever beat it on re-measurement
    oracle_enc = new_encoder()
    if oracle_enc.backend == "mesh":
        from seaweedfs_tpu.ops.rs_codec import _cpu_backend

        single = "jax" if d0.platform != "cpu" else _cpu_backend()
        oracle_enc = Encoder(10, 4, backend=single)
    base_o = os.path.join(td, "oracle", "7")
    os.makedirs(os.path.dirname(base_o))
    with open(base_o + ".dat", "wb") as f:
        f.write(data)
    t0 = time.perf_counter()
    stripe.write_ec_files(
        base_o, large_block_size=large, small_block_size=small,
        buffer_size=buffer_size, encoder=oracle_enc,
        max_batch_bytes=max_batch_bytes,
    )
    enc_wall = time.perf_counter() - t0
    oracle = {
        s: open(stripe.shard_file_name(base_o, s), "rb").read() for s in range(14)
    }
    shard_size = len(oracle[0])
    rebuilt_bytes = len(lost) * shard_size
    for s in lost:
        os.unlink(stripe.shard_file_name(base_o, s))
    t0 = time.perf_counter()
    stripe.rebuild_ec_files(
        base_o, encoder=oracle_enc, buffer_size=buffer_size,
        max_batch_bytes=max_batch_bytes,
    )
    reb_wall = time.perf_counter() - t0
    out["single_device"] = {
        "backend": oracle_enc.backend,
        "encode_gbps": round(dat_bytes / enc_wall / 1e9, 3),
        "rebuild_gbps": round(rebuilt_bytes / reb_wall / 1e9, 3),
    }

    all_match = True
    for dp, sp in shapes:
        label = f"{dp}x{sp}"
        base_m = os.path.join(td, label, "7")
        os.makedirs(os.path.dirname(base_m))
        with open(base_m + ".dat", "wb") as f:
            f.write(data)
        rec: dict = {}
        try:
            enc = Encoder(10, 4, backend="mesh", mesh_shape=(dp, sp))
            t0 = time.perf_counter()
            stripe.write_ec_files(
                base_m, large_block_size=large, small_block_size=small,
                buffer_size=buffer_size, encoder=enc,
                max_batch_bytes=max_batch_bytes,
            )
            rec["encode_gbps"] = round(dat_bytes / (time.perf_counter() - t0) / 1e9, 3)
            match = all(
                open(stripe.shard_file_name(base_m, s), "rb").read() == oracle[s]
                for s in range(14)
            )
            for variant, key in (("ring", "rebuild_ring_gbps"),
                                 ("alltoall", "rebuild_alltoall_gbps")):
                for s in lost:
                    os.unlink(stripe.shard_file_name(base_m, s))
                enc_v = Encoder(
                    10, 4, backend="mesh", mesh_shape=(dp, sp), mesh_rebuild=variant
                )
                t0 = time.perf_counter()
                stripe.rebuild_ec_files(
                    base_m, encoder=enc_v, buffer_size=buffer_size,
                    max_batch_bytes=max_batch_bytes,
                )
                rec[key] = round(rebuilt_bytes / (time.perf_counter() - t0) / 1e9, 3)
                match = match and all(
                    open(stripe.shard_file_name(base_m, s), "rb").read() == oracle[s]
                    for s in lost
                )
            rec["match"] = bool(match)
            all_match = all_match and match
        except Exception as e:  # noqa: BLE001 — one shape must not kill the sweep
            rec["error"] = str(e)[:200]
            all_match = False
        out["shapes"][label] = rec
    out["ok"] = bool(all_match and out["shapes"])
    return out


# ---------------------------------------------------------------------------
# stage 2d: dp-scaling sweep (child, 8 virtual CPU devices)
# ---------------------------------------------------------------------------


def mode_dp() -> None:
    """Encode throughput across dp=1/2/4/8 meshes (SURVEY §2.5, VERDICT r3
    #5). On this single-core host the virtual CPU devices share one core,
    so the curve quantifies the sharding machinery's overhead (flat =
    free), not chip speedup — the real-speedup axis needs real chips."""
    import jax

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    import numpy as np

    from seaweedfs_tpu.ops import gf8
    from seaweedfs_tpu.parallel import mesh as mesh_mod
    from seaweedfs_tpu.parallel import sharded

    out: dict = {
        "devices": len(jax.devices()),
        "host_cores": os.cpu_count(),
        "note": (
            "virtual CPU mesh on one host core: the curve measures "
            "sharding-machinery overhead at fixed global problem size, "
            "not parallel speedup"
        ),
    }
    b, n = 8, 1 << 20  # fixed global problem: 80 MiB of data
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(b, 10, n), dtype=np.uint8)
    pm = gf8.parity_matrix(10, 4)
    sweep: dict = {}
    for dp in (1, 2, 4, 8):
        if dp > len(jax.devices()):
            break
        try:
            mesh = mesh_mod.device_mesh(("dp", "sp"), shape=(dp, 1))
            enc = sharded.make_encode_fn(mesh, pm)
            x = sharded.shard_batch(mesh, data)
            t = _median_time(lambda: jax.block_until_ready(enc(x)), iters=3, warmup=1)
            sweep[str(dp)] = round(b * 10 * n / t / 1e9, 3)
        except Exception as e:  # noqa: BLE001 — one dp point must not kill the sweep
            sweep[str(dp)] = f"error: {str(e)[:120]}"
    out["encode_gbps_by_dp"] = sweep
    base = sweep.get("1")
    if isinstance(base, float) and base > 0:
        out["efficiency_vs_dp1"] = {
            k: round(v / base, 3) for k, v in sweep.items() if isinstance(v, float)
        }
    _emit(out)


# ---------------------------------------------------------------------------
# stage 3: device suite (child, default/axon platform)
# ---------------------------------------------------------------------------


def mode_device() -> None:
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops import gf8, rs_jax

    out: dict = {"platform": jax.devices()[0].platform}
    parity_bits = rs_jax.lifted_matrix(gf8.parity_matrix(10, 4))

    # compile check at a tiny shape first: if the toolchain rejects the
    # kernel we still report that fact instead of dying in the sweep
    t0 = time.perf_counter()
    try:
        tiny = jnp.zeros((1, 10, 16384), dtype=jnp.uint8)
        jax.block_until_ready(rs_jax.gf_apply(parity_bits, tiny))
        out["compile_check_secs"] = round(time.perf_counter() - t0, 2)
    except Exception as e:  # noqa: BLE001 — still sweep: Pallas may lower fine
        out["compile_check_error"] = str(e)[:500]

    b, n = 8, 4 << 20
    key = jax.random.PRNGKey(0)
    data = jax.block_until_ready(
        jax.random.randint(key, (b, 10, n), 0, 256, dtype=jnp.uint8)
    )
    data_bytes = b * 10 * n

    @jax.jit
    def encode_xla(d):
        return rs_jax.gf_apply(parity_bits, d)

    def encode_pallas(d):
        from seaweedfs_tpu.ops import rs_pallas

        return rs_pallas.gf_apply_fused(parity_bits, d)

    # Two numbers per backend (measured 2026-07-29 on the TPU v5 chip):
    #   per-call      — one dispatch per encode. Through the axon tunnel this
    #                   is FLOORED at ~65 ms/dispatch (a tiny x+1 op costs the
    #                   same), so it reflects the tunnel, not the chip.
    #   steady-state  — slope method: time lax.scan chains of K1 and K2
    #                   encodes in ONE dispatch; (t2-t1)/(K2-K1) is the true
    #                   per-encode device time. This matches production use
    #                   (a storage node streams encodes) and BASELINE.md's
    #                   device-side protocol.
    def steady_gbps(encode_fn, out_rows: int = 4):
        from seaweedfs_tpu.ops.measure import scan_chain_gbps

        return scan_chain_gbps(encode_fn, data, data_bytes, out_rows=out_rows)

    best_gbps, best_name, best_fn = 0.0, "none", None
    for name, fn in (("xla", encode_xla), ("pallas", encode_pallas)):
        try:
            t = _median_time(lambda: jax.block_until_ready(fn(data)), iters=10, warmup=3)
            gbps = data_bytes / t / 1e9
            out[f"{name}_gbps"] = round(gbps, 3)
        except Exception as e:  # noqa: BLE001 — a kernel failure must not zero the run
            out[f"{name}_error"] = str(e)[:500]
            continue
        if gbps > best_gbps:
            best_gbps, best_name, best_fn = gbps, name, fn
    # slope-measure only the per-call winner: each chain is two more XLA
    # compiles, and the device child must fit the watchdog budget even on a
    # cold compile cache (measured 2026-07-29: xla 31.1, pallas 18.7 GB/s
    # steady-state, so the per-call winner is also the steady-state winner)
    if best_fn is not None:
        try:
            steady = steady_gbps(best_fn)
            out[f"{best_name}_steady_gbps"] = round(steady, 3)
            if steady > best_gbps:
                best_gbps = steady
        except Exception as e:  # noqa: BLE001
            out["steady_error"] = str(e)[:300]
    # rebuild decode path on-device: ONE fused survivors->missing matrix
    # (2 data + 2 parity lost — the worst allowed loss count) applied to a
    # survivor stack, the exact shape the pipelined rebuild_ec_files
    # dispatches per batch. Counts toward the >=10x-rebuild north star.
    try:
        from seaweedfs_tpu.ops.rs_codec import _reconstruction_matrix

        lost = (0, 5, 11, 13)
        surv = tuple(s for s in range(14) if s not in lost)[:10]
        dm_bits = rs_jax.lifted_matrix(
            _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
        )

        @jax.jit
        def decode_xla(d):
            return rs_jax.gf_apply(dm_bits, d)

        t = _median_time(
            lambda: jax.block_until_ready(decode_xla(data)), iters=10, warmup=3
        )
        out["rebuild_xla_gbps"] = round(data_bytes / t / 1e9, 3)
        out["rebuild_xla_steady_gbps"] = round(
            steady_gbps(decode_xla, out_rows=len(lost)), 3
        )
    except Exception as e:  # noqa: BLE001 — rebuild numbers must not zero encode's
        out["rebuild_error"] = str(e)[:300]
    out["best_gbps"] = round(best_gbps, 3)
    out["best_backend"] = best_name
    try:
        from seaweedfs_tpu.ops.rs_codec import new_encoder

        # what production would ACTUALLY select on this device right now —
        # the evidence-based factory decision, next to the live numbers it
        # should eventually reflect (flips only via a committed artifact)
        out["auto_backend"] = new_encoder().selection
    except Exception as e:  # noqa: BLE001
        out["auto_backend_error"] = str(e)[:200]
    out["dispatch_floor_note"] = (
        "per-call numbers are floored by the axon tunnel's ~65 ms dispatch "
        "RTT; steady-state (scan-chain slope) is the device-side throughput"
    )

    # jax.profiler capture of the winning kernel (SURVEY §5 tracing row):
    # only meaningful with a real device; the trace directory is committed
    # as a round artifact for offline analysis
    trace_dir = os.environ.get("BENCH_TRACE_DIR", "")
    if trace_dir and best_fn is not None and out["platform"] != "cpu":
        try:
            with jax.profiler.trace(trace_dir):
                for _ in range(3):
                    jax.block_until_ready(best_fn(data))
            out["trace_dir"] = trace_dir
        except Exception as e:  # noqa: BLE001 — tracing must not zero the run
            out["trace_error"] = str(e)[:200]
    _emit(out)


# ---------------------------------------------------------------------------
# parent orchestrator
# ---------------------------------------------------------------------------


def _last_ditch_numpy() -> float | None:
    """Inline numpy measurement in the parent — no jax import, cannot hang."""
    try:
        return round(_measure_numpy_gbps(), 3)
    except Exception:  # noqa: BLE001
        return None


def main() -> None:
    deadline = time.monotonic() + WATCHDOG_SECS - 30  # emit margin
    forced_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"

    result: dict = {
        "metric": "ec_encode_gbps_10p4",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
    }

    # stage 1: device probe (skipped when the operator pinned cpu)
    probe, probe_err = (None, "JAX_PLATFORMS=cpu pinned by operator") if forced_cpu else _run_child(
        "probe", timeout=min(PROBE_SECS, max(10, int(deadline - time.monotonic())))
    )
    device_ok = bool(probe and probe.get("ok") and probe.get("platform") != "cpu")

    # stage 2: CPU suite — always, so the JSON always carries measurements
    cpu, cpu_err = _run_child(
        "cpu",
        timeout=min(CPU_SUITE_SECS, max(30, int(deadline - time.monotonic()))),
        extra_env={"JAX_PLATFORMS": "cpu"},
    )
    if cpu:
        result["fallback"] = cpu
        if "ec_rebuild" in cpu:  # the second north-star target, surfaced
            result["ec_rebuild"] = cpu["ec_rebuild"]  # beside the encode headline
    else:
        result["fallback_error"] = cpu_err
        gbps = _last_ditch_numpy()
        if gbps is not None:
            result["fallback"] = {"numpy_gbps": gbps, "note": "parent inline"}

    # stage 2c: remote degraded-read ladder (two in-process servers)
    remote, remote_err = _run_child(
        "remote",
        timeout=min(300, max(30, int(deadline - time.monotonic()))),
        extra_env={"JAX_PLATFORMS": "cpu"},
    )
    if remote:
        result["remote_ladder"] = remote
    else:
        result["remote_ladder_error"] = remote_err

    # stage 2e: distributed remote-survivor rebuild (two in-process servers)
    rr, rr_err = _run_child(
        "rebuild_remote",
        timeout=min(300, max(30, int(deadline - time.monotonic()))),
        extra_env={"JAX_PLATFORMS": "cpu"},
    )
    if rr:
        result["ec_rebuild_remote"] = rr
    else:
        result["ec_rebuild_remote_error"] = rr_err

    # stage 2f: trace-repair rebuild — wire-bytes ratio vs full slabs
    rt, rt_err = _run_child(
        "rebuild_trace",
        timeout=min(300, max(30, int(deadline - time.monotonic()))),
        extra_env={"JAX_PLATFORMS": "cpu"},
    )
    if rt:
        result["ec_rebuild_trace"] = rt
    else:
        result["ec_rebuild_trace_error"] = rt_err

    # stage 2g: inline-EC ingest — amortized encode-on-write + delta gate
    ing, ing_err = _run_child(
        "ingest",
        timeout=min(300, max(30, int(deadline - time.monotonic()))),
        extra_env={"JAX_PLATFORMS": "cpu"},
    )
    if ing:
        result["ec_ingest"] = ing
    else:
        result["ec_ingest_error"] = ing_err

    # stage 2i: compiled XOR-schedule backend vs the native library (the
    # committed section rs_codec.pick_cpu_backend promotes on: same-run
    # xorsched/native ratio, host fingerprint, byte-verification)
    xor, xor_err = _run_child(
        "xor",
        timeout=min(300, max(30, int(deadline - time.monotonic()))),
        extra_env={"JAX_PLATFORMS": "cpu"},
    )
    if xor:
        result["xor"] = xor
    else:
        result["xor_error"] = xor_err

    # stage 2d: dp-scaling sweep over the virtual 8-device CPU mesh
    if deadline - time.monotonic() > 30:
        dp, dp_err = _run_child(
            "dp",
            timeout=min(300, int(deadline - time.monotonic())),
            extra_env={
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            },
        )
        if dp:
            result["dp_scaling"] = dp
        else:
            result["dp_scaling_error"] = dp_err
    else:
        result["dp_scaling_error"] = "skipped: bench deadline exhausted"

    # stage 2h: mesh backend — per-mesh-shape encode/rebuild through the
    # real file pipelines on the forced 8-device CPU mesh (the off-chip
    # half of the MULTICHIP evidence; on-chip numbers come from
    # device_window's mesh stage)
    if deadline - time.monotonic() > 60:
        mesh, mesh_err = _run_child(
            "mesh",
            timeout=min(300, int(deadline - time.monotonic())),
            extra_env={
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            },
        )
        if mesh:
            result["ec_mesh"] = mesh
        else:
            result["ec_mesh_error"] = mesh_err
    else:
        result["ec_mesh_error"] = "skipped: bench deadline exhausted"

    # stage 2b: TPU-lowering proof — device-free Mosaic validation of the
    # Pallas kernel (cheap; proves the kernel compiles for the real target
    # even when the tunnel is wedged)
    try:
        from seaweedfs_tpu.ops import tpu_lowering

        proof = tpu_lowering.run_lowering_proof(
            timeout=min(300, max(30, int(deadline - time.monotonic())))
        )
        result["tpu_lowering"] = {
            "ok": bool(proof) and all(r.get("ok") for r in proof),
            "shapes": {r["name"]: r.get("ok", False) for r in proof},
        }
    except Exception as e:  # noqa: BLE001
        result["tpu_lowering"] = {"ok": False, "error": str(e)[:200]}

    # stage 1b: retry the probe — the tunnel may have unwedged mid-run
    if not device_ok and not forced_cpu and deadline - time.monotonic() > 120:
        probe2, probe2_err = _run_child("probe", timeout=60)
        if probe2 and probe2.get("ok") and probe2.get("platform") != "cpu":
            probe, probe_err, device_ok = probe2, None, True
        elif probe_err is None:
            probe_err = probe2_err

    # stage 3: device suite (with a jax.profiler capture directory)
    device = None
    if device_ok and deadline - time.monotonic() > 60:
        device, dev_err = _run_child(
            "device",
            timeout=max(60, int(deadline - time.monotonic())),
            extra_env={
                "BENCH_TRACE_DIR": os.environ.get(
                    "BENCH_TRACE_DIR",
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "artifacts", "jax_trace"),
                )
            },
        )
        if device:
            result["device"] = device
        else:
            result["device_error"] = dev_err

    # headline value: real chip if reachable, else best CPU-side measurement
    if device and device.get("best_gbps", 0) > 0:
        result["value"] = device["best_gbps"]
        result["platform"] = device.get("platform", "device")
        result["backend"] = device.get("best_backend")
    else:
        fb = result.get("fallback", {})
        native_name = "native-avx2" if fb.get("native_avx2") else "native"
        candidates = {
            "xla-cpu": fb.get("xla_cpu_gbps"),
            native_name: fb.get("native_gbps"),
            native_name + "-mt": fb.get("native_mt_gbps"),
            "numpy": fb.get("numpy_gbps"),
        }
        best = max(
            ((v, k) for k, v in candidates.items() if v), default=(0.0, "none")
        )
        result["value"] = best[0]
        result["platform"] = "cpu-fallback"
        result["backend"] = best[1]
        if probe_err:
            result["device_probe_error"] = probe_err
        # When the tunnel is wedged at bench time but a device measurement
        # was taken during an unwedged window, the DEVICE number is the
        # headline (it is what the chip does; the CPU number is what this
        # host does) — promoted verbatim from the committed artifact with
        # explicit provenance, never hardcoded values that could drift
        # from what they cite. The live CPU measurement moves to a
        # clearly-labeled sub-block. An operator-pinned CPU run is asking
        # for THIS host's number — no promotion there.
        try:
            if forced_cpu:
                raise OSError("operator pinned cpu: no device promotion")
            art_dir = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "artifacts"
            )
            latest = sorted(
                f for f in os.listdir(art_dir)
                if f.startswith("DEVICE_MEASUREMENT_") and f.endswith(".json")
            )
            if latest:
                with open(os.path.join(art_dir, latest[-1]), encoding="utf-8") as f:
                    prior = json.load(f)
                result["prior_device_measurement"] = prior
                cands = {
                    "xla": prior.get("xla_steady_gbps"),
                    "pallas": prior.get("pallas_steady_gbps"),
                }
                rm = prior.get("remeasured") or {}
                if rm.get("xla_steady_gbps"):
                    cands["xla"] = max(
                        cands.get("xla") or 0, rm["xla_steady_gbps"]
                    )
                dev_best = max(
                    ((v, k) for k, v in cands.items() if v), default=None
                )
                if dev_best:
                    result["live_cpu_fallback"] = {
                        "value": result["value"],
                        "backend": result["backend"],
                    }
                    result["value"] = dev_best[0]
                    result["backend"] = dev_best[1]
                    result["platform"] = "tpu-prior-window"
                    result["headline_provenance"] = (
                        f"artifacts/{latest[-1]} (device-measured in a prior "
                        "tunnel-alive window; tunnel wedged at bench time)"
                    )
        except (OSError, ValueError):
            pass
    if probe:
        result["device_probe"] = {k: probe[k] for k in ("secs", "platform") if k in probe}
    # the evidence-based auto-backend decision for a TPU deployment, from
    # committed artifacts alone (no jax import in the parent: reading a
    # JSON file cannot wedge the tunnel) — what new_encoder("auto") will
    # select on-chip, and why
    try:
        from seaweedfs_tpu.ops.rs_codec import pick_device_backend

        result["auto_backend_on_tpu"] = pick_device_backend()[1]
    except Exception as e:  # noqa: BLE001
        result["auto_backend_on_tpu_error"] = str(e)[:200]
    # the CPU-side twin: what new_encoder("auto") will select on a plain
    # CPU host from committed BENCH xor evidence, and why
    try:
        from seaweedfs_tpu.ops.rs_codec import pick_cpu_backend

        result["auto_backend_on_cpu"] = pick_cpu_backend()[1]
    except Exception as e:  # noqa: BLE001
        result["auto_backend_on_cpu_error"] = str(e)[:200]
    result["vs_baseline"] = round(result["value"] / TARGET_GBPS, 4)
    _emit(result)


if __name__ == "__main__":
    mode = os.environ.get("BENCH_MODE", "")
    if mode == "probe":
        mode_probe()
    elif mode == "cpu":
        mode_cpu()
    elif mode == "remote":
        mode_remote()
    elif mode == "rebuild_remote":
        mode_rebuild_remote()
    elif mode == "rebuild_trace":
        mode_rebuild_trace()
    elif mode == "ingest":
        mode_ingest()
    elif mode == "convert":
        mode_convert()
    elif mode == "xor":
        mode_xor(smoke="--smoke" in sys.argv)
    elif mode == "rebuild_batch":
        mode_rebuild_batch(smoke="--smoke" in sys.argv)
    elif mode == "dp":
        mode_dp()
    elif mode == "mesh":
        mode_mesh()
    elif mode == "device":
        mode_device()
    else:
        main()
