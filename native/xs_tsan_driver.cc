// Standalone sanitizer driver for weedtpu_xor_schedule_apply_blocks.
//
// The width-parallel executor is the one threads-and-atomics surface in
// libweedtpu.so (a pool draining a flat (block, tile) task list off one
// atomic counter). Loading a TSan-instrumented .so into an uninstrumented
// Python would need the sanitizer runtime preloaded into the interpreter,
// so race coverage runs as this standalone binary instead: build with
// `make tsan` / `make asan` and run with the thread counts to exercise
// (default 1 2 4 8). Exit 0 = clean; the sanitizer runtime exits nonzero
// on any report, and the driver itself exits nonzero when the parallel
// result drifts from the byte-level XOR oracle or from the single-thread
// run.
//
// Two blocks with different non-tile-aligned lengths exercise the
// block-diagonal task walk; lengths are sized so total bytes clear the
// executor's ~256 KiB-per-worker clamp at 8 threads (smaller inputs would
// silently collapse every run to one worker and race-check nothing).
//
// Schedule geometry (shared by both blocks): 4 input shards -> planes
// [0,32), one temp shard -> planes [32,40), 2 output shards at
// out_base=40. Per bit i: temp = in0 ^ in2, out0 = in0^in1^in2^in3,
// out1 = temp ^ in1. Uniform shard-level ops make the bit-plane program
// equal a plain byte-wise XOR, which is the oracle below.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" int weedtpu_xor_schedule_apply_blocks(
    const int32_t* sched, const uint64_t* sched_off, const uint64_t* sched_words,
    const uint32_t* n_slots, const uint32_t* in_planes, const uint32_t* out_base,
    const uint32_t* out_planes, const uint8_t* const* ins,
    const uint64_t* ins_off, uint8_t* const* outs, const uint64_t* outs_off,
    const uint64_t* lens, uint32_t n_blocks, uint64_t tile_sym,
    uint32_t threads);

static const int K = 4;  // input shards per block
static const int R = 2;  // output shards per block

struct Block {
  std::vector<std::vector<uint8_t>> ins, outs, want;
  uint64_t len;
};

static Block make_block(uint64_t len, uint32_t seed) {
  Block b;
  b.len = len;
  b.ins.assign(K, std::vector<uint8_t>(len));
  b.outs.assign(R, std::vector<uint8_t>(len));
  b.want.assign(R, std::vector<uint8_t>(len));
  uint32_t s = seed;
  for (int c = 0; c < K; c++)
    for (uint64_t i = 0; i < len; i++) {
      s = s * 1664525u + 1013904223u;  // LCG: deterministic, no libc rand
      b.ins[c][i] = (uint8_t)(s >> 24);
    }
  for (uint64_t i = 0; i < len; i++) {
    b.want[0][i] = b.ins[0][i] ^ b.ins[1][i] ^ b.ins[2][i] ^ b.ins[3][i];
    b.want[1][i] = b.ins[0][i] ^ b.ins[1][i] ^ b.ins[2][i];
  }
  return b;
}

int main(int argc, char** argv) {
  std::vector<int32_t> sched;
  for (int i = 0; i < 8; i++) {  // temp = in0 ^ in2
    sched.push_back(32 + i);
    sched.push_back(2);
    sched.push_back(i);
    sched.push_back(16 + i);
  }
  for (int i = 0; i < 8; i++) {  // out0 = in0 ^ in1 ^ in2 ^ in3
    sched.push_back(40 + i);
    sched.push_back(4);
    for (int c = 0; c < K; c++) sched.push_back(c * 8 + i);
  }
  for (int i = 0; i < 8; i++) {  // out1 = temp ^ in1
    sched.push_back(48 + i);
    sched.push_back(2);
    sched.push_back(32 + i);
    sched.push_back(8 + i);
  }

  Block blocks[2] = {
      make_block(400 * 512 + 137, 1u),  // odd tail tile
      make_block(700 * 512 + 1, 2u),
  };

  uint64_t sched_words[2] = {sched.size(), sched.size()};
  uint64_t sched_off[2] = {0, 0};  // both blocks share one program
  uint32_t n_slots[2] = {56, 56}, in_planes[2] = {32, 32};
  uint32_t out_base[2] = {40, 40}, out_planes[2] = {16, 16};
  uint64_t lens[2] = {blocks[0].len, blocks[1].len};
  const uint8_t* ins[2 * K];
  uint8_t* outs[2 * R];
  uint64_t ins_off[2] = {0, K}, outs_off[2] = {0, R};
  for (int g = 0; g < 2; g++) {
    for (int c = 0; c < K; c++) ins[g * K + c] = blocks[g].ins[c].data();
    for (int r = 0; r < R; r++) outs[g * R + r] = blocks[g].outs[r].data();
  }

  std::vector<uint32_t> counts;
  for (int a = 1; a < argc; a++) counts.push_back((uint32_t)atoi(argv[a]));
  if (counts.empty()) counts = {1, 2, 4, 8};

  for (uint32_t t : counts) {
    for (int iter = 0; iter < 3; iter++) {
      for (int g = 0; g < 2; g++)
        for (int r = 0; r < R; r++)
          memset(blocks[g].outs[r].data(), 0xAA, blocks[g].len);
      int rc = weedtpu_xor_schedule_apply_blocks(
          sched.data(), sched_off, sched_words, n_slots, in_planes, out_base,
          out_planes, ins, ins_off, outs, outs_off, lens, 2, 512, t);
      if (!rc) {
        fprintf(stderr, "apply_blocks rejected args (threads=%u)\n", t);
        return 4;
      }
      for (int g = 0; g < 2; g++)
        for (int r = 0; r < R; r++)
          if (memcmp(blocks[g].outs[r].data(), blocks[g].want[r].data(),
                     blocks[g].len) != 0) {
            fprintf(stderr,
                    "block %d out %d drifts from XOR oracle (threads=%u)\n",
                    g, r, t);
            return 2;
          }
    }
    printf("threads=%u ok\n", t);
  }
  puts("xs sanitizer driver: all clean");
  return 0;
}
