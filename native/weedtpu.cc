// libweedtpu — native runtime kernels for seaweedfs_tpu.
//
// The reference's only native-perf code is the SIMD galois kernels inside its
// RS codec dependency (klauspost/reedsolomon galois_*.s [VERIFY: mount empty,
// SURVEY.md §2.2]) plus CRC helpers. This library provides the host-side
// equivalents for the TPU-native framework:
//   * crc32c        — Castagnoli CRC (needle checksums), slice-by-8
//   * gf_mul_slice  — GF(2^8) multiply-accumulate over byte slices using the
//                     PSHUFB nibble-table trick (AVX2 when available, scalar
//                     fallback) — the honest "AVX2 baseline" for BASELINE.md
//   * gf_matrix_apply — (R x C) GF matrix over C input slices -> R outputs
//
// Exposed with a plain C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, poly 0x82F63B78 reflected) — slice-by-8
// ---------------------------------------------------------------------------

static uint32_t crc32c_table[8][256];

// Table init runs as a static constructor during dlopen (single-threaded),
// so concurrent first calls from GIL-released ctypes threads see a fully
// published table — no lazy-init data race.
static const int crc32c_initialized = [] {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ (0x82F63B78u & (0u - (crc & 1)));
    crc32c_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 8; s++)
      crc32c_table[s][i] =
          (crc32c_table[s - 1][i] >> 8) ^ crc32c_table[0][crc32c_table[s - 1][i] & 0xFF];
  return 1;
}();

uint32_t weedtpu_crc32c(uint32_t crc, const uint8_t* buf, uint64_t len) {
  crc = ~crc;
  while (len >= 8) {
    uint64_t word;
    memcpy(&word, buf, 8);
    word ^= crc;  // little-endian hosts only (x86/arm64)
    crc = crc32c_table[7][word & 0xFF] ^ crc32c_table[6][(word >> 8) & 0xFF] ^
          crc32c_table[5][(word >> 16) & 0xFF] ^ crc32c_table[4][(word >> 24) & 0xFF] ^
          crc32c_table[3][(word >> 32) & 0xFF] ^ crc32c_table[2][(word >> 40) & 0xFF] ^
          crc32c_table[1][(word >> 48) & 0xFF] ^ crc32c_table[0][(word >> 56) & 0xFF];
    buf += 8;
    len -= 8;
  }
  while (len--) crc = (crc >> 8) ^ crc32c_table[0][(crc ^ *buf++) & 0xFF];
  return ~crc;
}

// ---------------------------------------------------------------------------
// GF(2^8) multiply-accumulate, poly 0x11D
// ---------------------------------------------------------------------------

static uint8_t gf_mul_table[256][256];

static const int gf_initialized = [] {
  for (int a = 0; a < 256; a++) {
    for (int b = 0; b < 256; b++) {
      uint16_t x = (uint16_t)a, r = 0, y = (uint16_t)b;
      while (y) {
        if (y & 1) r ^= x;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
        y >>= 1;
      }
      gf_mul_table[a][b] = (uint8_t)r;
    }
  }
  return 1;
}();

#if defined(__x86_64__)
// AVX2 body compiled with a per-function target attribute and selected at
// runtime via __builtin_cpu_supports, so one binary runs on any x86-64 host
// (no -mavx2 global flag, no SIGILL on pre-AVX2 machines).
__attribute__((target("avx2"))) static void gf_mul_xor_slice_avx2(
    const uint8_t* row, const uint8_t* src, uint8_t* dst, uint64_t len) {
  // PSHUFB nibble tables: y = lo_tbl[x & 0xF] ^ hi_tbl[x >> 4]
  uint8_t lo[16], hi[16];
  for (int i = 0; i < 16; i++) {
    lo[i] = row[i];
    hi[i] = row[i << 4];
  }
  const __m256i vlo = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)lo));
  const __m256i vhi = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)hi));
  const __m256i mask = _mm256_set1_epi8(0x0F);
  uint64_t i = 0;
  for (; i + 32 <= len; i += 32) {
    __m256i x = _mm256_loadu_si256((const __m256i*)(src + i));
    __m256i xl = _mm256_and_si256(x, mask);
    __m256i xh = _mm256_and_si256(_mm256_srli_epi64(x, 4), mask);
    __m256i y = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, xl),
                                 _mm256_shuffle_epi8(vhi, xh));
    __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
    _mm256_storeu_si256((__m256i*)(dst + i), _mm256_xor_si256(d, y));
  }
  for (; i < len; i++) dst[i] ^= row[src[i]];
}
#endif

// dst[i] ^= gmul(c, src[i]) for i in [0, len)
void weedtpu_gf_mul_xor_slice(uint8_t c, const uint8_t* src, uint8_t* dst,
                              uint64_t len) {
  if (c == 0) return;
  const uint8_t* row = gf_mul_table[c];
#if defined(__x86_64__)
  if (__builtin_cpu_supports("avx2")) {
    gf_mul_xor_slice_avx2(row, src, dst, len);
    return;
  }
#endif
  for (uint64_t i = 0; i < len; i++) dst[i] ^= row[src[i]];
}

// One contiguous byte range of the apply: for each output row, zero the
// range then XOR-accumulate every input slice through its coefficient.
// Iterating (row, col) inside a bounded range keeps src/dst resident in
// cache across the inner passes — the same blocking the reference codec
// gets from its per-goroutine split (WithAutoGoroutines).
static void gf_matrix_apply_range(const uint8_t* matrix, uint32_t rows,
                                  uint32_t cols, const uint8_t* const* inputs,
                                  uint8_t* const* outputs, uint64_t off,
                                  uint64_t n) {
  for (uint32_t r = 0; r < rows; r++) {
    memset(outputs[r] + off, 0, n);
    for (uint32_t c0 = 0; c0 < cols; c0++) {
      uint8_t coef = matrix[r * cols + c0];
      if (coef)
        weedtpu_gf_mul_xor_slice(coef, inputs[c0] + off, outputs[r] + off, n);
    }
  }
}

// outputs[r] = XOR_c gmul(matrix[r*cols+c], inputs[c]), each slice `len` bytes
void weedtpu_gf_matrix_apply(const uint8_t* matrix, uint32_t rows, uint32_t cols,
                             const uint8_t* const* inputs, uint8_t* const* outputs,
                             uint64_t len) {
  gf_matrix_apply_range(matrix, rows, cols, inputs, outputs, 0, len);
}

// Multithreaded variant: the byte range splits across `threads` workers
// (0 = hardware concurrency), each running the blocked single-thread body
// on a disjoint 64B-aligned chunk. Mirrors klauspost/reedsolomon's
// WithAutoGoroutines data split; output rows are disjoint per range, so
// no synchronization beyond join is needed.
void weedtpu_gf_matrix_apply_mt(const uint8_t* matrix, uint32_t rows,
                                uint32_t cols, const uint8_t* const* inputs,
                                uint8_t* const* outputs, uint64_t len,
                                uint32_t threads) {
  if (threads == 0) {
    unsigned hw = std::thread::hardware_concurrency();
    threads = hw ? hw : 1;
  }
  // below ~256 KiB per worker, spawn overhead beats the parallel win
  uint64_t max_useful = len / (256 * 1024);
  if (max_useful < threads) threads = (uint32_t)std::max<uint64_t>(1, max_useful);
  if (threads <= 1) {
    gf_matrix_apply_range(matrix, rows, cols, inputs, outputs, 0, len);
    return;
  }
  uint64_t chunk = (len / threads + 63) & ~63ull;  // 64B-aligned split
  std::vector<std::thread> pool;
  pool.reserve(threads);
  uint64_t off = 0;
  for (uint32_t t = 0; t < threads && off < len; t++) {
    uint64_t n = std::min(chunk, len - off);
    pool.emplace_back(gf_matrix_apply_range, matrix, rows, cols, inputs,
                      outputs, off, n);
    off += n;
  }
  if (off < len)  // remainder from alignment rounding
    gf_matrix_apply_range(matrix, rows, cols, inputs, outputs, off, len - off);
  for (auto& th : pool) th.join();
}

// Batched apply: `batch` independent stacks sharing one matrix.
// inputs holds batch*cols slice pointers, outputs batch*rows; workers split
// over batch elements — one pool for the whole flush instead of one per
// element, and no host-side repacking (each slice pointer is used as-is).
void weedtpu_gf_matrix_apply_batch(const uint8_t* matrix, uint32_t rows,
                                   uint32_t cols,
                                   const uint8_t* const* inputs,
                                   uint8_t* const* outputs, uint64_t len,
                                   uint32_t batch, uint32_t threads) {
  if (threads == 0) {
    unsigned hw = std::thread::hardware_concurrency();
    threads = hw ? hw : 1;
  }
  if (threads > batch) {
    // fewer elements than workers: the per-element byte-range split keeps
    // the whole machine busy (a batch of 2 large stacks on 16 cores would
    // otherwise run on 2 threads)
    for (uint32_t b = 0; b < batch; b++)
      weedtpu_gf_matrix_apply_mt(matrix, rows, cols, inputs + (uint64_t)b * cols,
                                 outputs + (uint64_t)b * rows, len, threads);
    return;
  }
  uint64_t max_useful = (uint64_t)batch * cols * len / (256 * 1024);
  if (max_useful < threads) threads = (uint32_t)std::max<uint64_t>(1, max_useful);
  auto run_span = [&](uint32_t b0, uint32_t b1) {
    for (uint32_t b = b0; b < b1; b++)
      gf_matrix_apply_range(matrix, rows, cols, inputs + (uint64_t)b * cols,
                            outputs + (uint64_t)b * rows, 0, len);
  };
  if (threads <= 1) {
    run_span(0, batch);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  uint32_t per = (batch + threads - 1) / threads;
  for (uint32_t t = 0; t < threads; t++) {
    uint32_t b0 = t * per, b1 = std::min(batch, b0 + per);
    if (b0 >= b1) break;
    pool.emplace_back(run_span, b0, b1);
  }
  for (auto& th : pool) th.join();
}

int weedtpu_has_avx2() {
#if defined(__x86_64__)
  return __builtin_cpu_supports("avx2") ? 1 : 0;
#else
  return 0;
#endif
}

}  // extern "C"
