// libweedtpu — native runtime kernels for seaweedfs_tpu.
//
// The reference's only native-perf code is the SIMD galois kernels inside its
// RS codec dependency (klauspost/reedsolomon galois_*.s [VERIFY: mount empty,
// SURVEY.md §2.2]) plus CRC helpers. This library provides the host-side
// equivalents for the TPU-native framework:
//   * crc32c        — Castagnoli CRC (needle checksums), slice-by-8
//   * gf_mul_slice  — GF(2^8) multiply-accumulate over byte slices using the
//                     PSHUFB nibble-table trick (AVX2 when available, scalar
//                     fallback) — the honest "AVX2 baseline" for BASELINE.md
//   * gf_matrix_apply — (R x C) GF matrix over C input slices -> R outputs
//
// Exposed with a plain C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, poly 0x82F63B78 reflected) — slice-by-8
// ---------------------------------------------------------------------------

static uint32_t crc32c_table[8][256];

// Table init runs as a static constructor during dlopen (single-threaded),
// so concurrent first calls from GIL-released ctypes threads see a fully
// published table — no lazy-init data race.
static const int crc32c_initialized = [] {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ (0x82F63B78u & (0u - (crc & 1)));
    crc32c_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 8; s++)
      crc32c_table[s][i] =
          (crc32c_table[s - 1][i] >> 8) ^ crc32c_table[0][crc32c_table[s - 1][i] & 0xFF];
  return 1;
}();

uint32_t weedtpu_crc32c(uint32_t crc, const uint8_t* buf, uint64_t len) {
  crc = ~crc;
  while (len >= 8) {
    uint64_t word;
    memcpy(&word, buf, 8);
    word ^= crc;  // little-endian hosts only (x86/arm64)
    crc = crc32c_table[7][word & 0xFF] ^ crc32c_table[6][(word >> 8) & 0xFF] ^
          crc32c_table[5][(word >> 16) & 0xFF] ^ crc32c_table[4][(word >> 24) & 0xFF] ^
          crc32c_table[3][(word >> 32) & 0xFF] ^ crc32c_table[2][(word >> 40) & 0xFF] ^
          crc32c_table[1][(word >> 48) & 0xFF] ^ crc32c_table[0][(word >> 56) & 0xFF];
    buf += 8;
    len -= 8;
  }
  while (len--) crc = (crc >> 8) ^ crc32c_table[0][(crc ^ *buf++) & 0xFF];
  return ~crc;
}

// ---------------------------------------------------------------------------
// GF(2^8) multiply-accumulate, poly 0x11D
// ---------------------------------------------------------------------------

static uint8_t gf_mul_table[256][256];

static const int gf_initialized = [] {
  for (int a = 0; a < 256; a++) {
    for (int b = 0; b < 256; b++) {
      uint16_t x = (uint16_t)a, r = 0, y = (uint16_t)b;
      while (y) {
        if (y & 1) r ^= x;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
        y >>= 1;
      }
      gf_mul_table[a][b] = (uint8_t)r;
    }
  }
  return 1;
}();

#if defined(__x86_64__)
// AVX2 body compiled with a per-function target attribute and selected at
// runtime via __builtin_cpu_supports, so one binary runs on any x86-64 host
// (no -mavx2 global flag, no SIGILL on pre-AVX2 machines).
__attribute__((target("avx2"))) static void gf_mul_xor_slice_avx2(
    const uint8_t* row, const uint8_t* src, uint8_t* dst, uint64_t len) {
  // PSHUFB nibble tables: y = lo_tbl[x & 0xF] ^ hi_tbl[x >> 4]
  uint8_t lo[16], hi[16];
  for (int i = 0; i < 16; i++) {
    lo[i] = row[i];
    hi[i] = row[i << 4];
  }
  const __m256i vlo = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)lo));
  const __m256i vhi = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)hi));
  const __m256i mask = _mm256_set1_epi8(0x0F);
  uint64_t i = 0;
  for (; i + 32 <= len; i += 32) {
    __m256i x = _mm256_loadu_si256((const __m256i*)(src + i));
    __m256i xl = _mm256_and_si256(x, mask);
    __m256i xh = _mm256_and_si256(_mm256_srli_epi64(x, 4), mask);
    __m256i y = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, xl),
                                 _mm256_shuffle_epi8(vhi, xh));
    __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
    _mm256_storeu_si256((__m256i*)(dst + i), _mm256_xor_si256(d, y));
  }
  for (; i < len; i++) dst[i] ^= row[src[i]];
}
#endif

// dst[i] ^= gmul(c, src[i]) for i in [0, len)
void weedtpu_gf_mul_xor_slice(uint8_t c, const uint8_t* src, uint8_t* dst,
                              uint64_t len) {
  if (c == 0) return;
  const uint8_t* row = gf_mul_table[c];
#if defined(__x86_64__)
  if (__builtin_cpu_supports("avx2")) {
    gf_mul_xor_slice_avx2(row, src, dst, len);
    return;
  }
#endif
  for (uint64_t i = 0; i < len; i++) dst[i] ^= row[src[i]];
}

// One contiguous byte range of the apply: for each output row, zero the
// range then XOR-accumulate every input slice through its coefficient.
// Iterating (row, col) inside a bounded range keeps src/dst resident in
// cache across the inner passes — the same blocking the reference codec
// gets from its per-goroutine split (WithAutoGoroutines).
static void gf_matrix_apply_range(const uint8_t* matrix, uint32_t rows,
                                  uint32_t cols, const uint8_t* const* inputs,
                                  uint8_t* const* outputs, uint64_t off,
                                  uint64_t n) {
  for (uint32_t r = 0; r < rows; r++) {
    memset(outputs[r] + off, 0, n);
    for (uint32_t c0 = 0; c0 < cols; c0++) {
      uint8_t coef = matrix[r * cols + c0];
      if (coef)
        weedtpu_gf_mul_xor_slice(coef, inputs[c0] + off, outputs[r] + off, n);
    }
  }
}

// outputs[r] = XOR_c gmul(matrix[r*cols+c], inputs[c]), each slice `len` bytes
void weedtpu_gf_matrix_apply(const uint8_t* matrix, uint32_t rows, uint32_t cols,
                             const uint8_t* const* inputs, uint8_t* const* outputs,
                             uint64_t len) {
  gf_matrix_apply_range(matrix, rows, cols, inputs, outputs, 0, len);
}

// Multithreaded variant: the byte range splits across `threads` workers
// (0 = hardware concurrency), each running the blocked single-thread body
// on a disjoint 64B-aligned chunk. Mirrors klauspost/reedsolomon's
// WithAutoGoroutines data split; output rows are disjoint per range, so
// no synchronization beyond join is needed.
void weedtpu_gf_matrix_apply_mt(const uint8_t* matrix, uint32_t rows,
                                uint32_t cols, const uint8_t* const* inputs,
                                uint8_t* const* outputs, uint64_t len,
                                uint32_t threads) {
  if (threads == 0) {
    unsigned hw = std::thread::hardware_concurrency();
    threads = hw ? hw : 1;
  }
  // below ~256 KiB per worker, spawn overhead beats the parallel win
  uint64_t max_useful = len / (256 * 1024);
  if (max_useful < threads) threads = (uint32_t)std::max<uint64_t>(1, max_useful);
  if (threads <= 1) {
    gf_matrix_apply_range(matrix, rows, cols, inputs, outputs, 0, len);
    return;
  }
  uint64_t chunk = (len / threads + 63) & ~63ull;  // 64B-aligned split
  std::vector<std::thread> pool;
  pool.reserve(threads);
  uint64_t off = 0;
  for (uint32_t t = 0; t < threads && off < len; t++) {
    uint64_t n = std::min(chunk, len - off);
    pool.emplace_back(gf_matrix_apply_range, matrix, rows, cols, inputs,
                      outputs, off, n);
    off += n;
  }
  if (off < len)  // remainder from alignment rounding
    gf_matrix_apply_range(matrix, rows, cols, inputs, outputs, off, len - off);
  for (auto& th : pool) th.join();
}

// Batched apply: `batch` independent stacks sharing one matrix.
// inputs holds batch*cols slice pointers, outputs batch*rows; workers split
// over batch elements — one pool for the whole flush instead of one per
// element, and no host-side repacking (each slice pointer is used as-is).
void weedtpu_gf_matrix_apply_batch(const uint8_t* matrix, uint32_t rows,
                                   uint32_t cols,
                                   const uint8_t* const* inputs,
                                   uint8_t* const* outputs, uint64_t len,
                                   uint32_t batch, uint32_t threads) {
  if (threads == 0) {
    unsigned hw = std::thread::hardware_concurrency();
    threads = hw ? hw : 1;
  }
  if (threads > batch) {
    // fewer elements than workers: the per-element byte-range split keeps
    // the whole machine busy (a batch of 2 large stacks on 16 cores would
    // otherwise run on 2 threads)
    for (uint32_t b = 0; b < batch; b++)
      weedtpu_gf_matrix_apply_mt(matrix, rows, cols, inputs + (uint64_t)b * cols,
                                 outputs + (uint64_t)b * rows, len, threads);
    return;
  }
  uint64_t max_useful = (uint64_t)batch * cols * len / (256 * 1024);
  if (max_useful < threads) threads = (uint32_t)std::max<uint64_t>(1, max_useful);
  auto run_span = [&](uint32_t b0, uint32_t b1) {
    for (uint32_t b = b0; b < b1; b++)
      gf_matrix_apply_range(matrix, rows, cols, inputs + (uint64_t)b * cols,
                            outputs + (uint64_t)b * rows, 0, len);
  };
  if (threads <= 1) {
    run_span(0, batch);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  uint32_t per = (batch + threads - 1) / threads;
  for (uint32_t t = 0; t < threads; t++) {
    uint32_t b0 = t * per, b1 = std::min(batch, b0 + per);
    if (b0 >= b1) break;
    pool.emplace_back(run_span, b0, b1);
  }
  for (auto& th : pool) th.join();
}

int weedtpu_has_avx2() {
#if defined(__x86_64__)
  return __builtin_cpu_supports("avx2") ? 1 : 0;
#else
  return 0;
#endif
}

// ---------------------------------------------------------------------------
// xorsched — compiled XOR-schedule executor (ops/xorsched.py)
//
// The schedule arrives as a flat int32 op list ([dest, nsrc, srcs...]
// records) over a slot space of packed bit-planes: slots [0, in_planes) are
// the transposed input shards (plane 8c+i = bit i of shard c), temps follow,
// and [out_base, out_base + out_planes) are the output bit-planes.  The
// executor tiles the width axis (tile_sym symbols per shard per tile), and
// per tile: byte->bit-plane transposes the inputs into a scratch frame,
// replays the XOR program with wide vector XORs, and transposes the output
// planes back to bytes.  Three SIMD levels, dispatched at runtime like the
// PSHUFB kernel above: GFNI+AVX-512 (one vgf2p8affineqb per 8x8 bit
// transpose), AVX2 (movemask / shuffle+cmpeq), scalar (Hacker's Delight).
// ---------------------------------------------------------------------------

// 8x8 bit-matrix transpose of a little-endian qword: result byte i bit j =
// input byte j bit i — i.e. 8 symbols in, their 8 packed plane bytes out
// (an involution, so it is also the plane->symbol direction).
static inline uint64_t xs_t8(uint64_t x) {
  uint64_t t;
  t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AAull; x ^= t ^ (t << 7);
  t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCCull; x ^= t ^ (t << 14);
  t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0ull; x ^= t ^ (t << 28);
  return x;
}

static void xs_xor_op_scalar(uint8_t* dst, const uint8_t* const* srcs, int nsrc,
                             uint64_t nb) {
  uint64_t i = 0;
  for (; i + 8 <= nb; i += 8) {
    uint64_t v;
    memcpy(&v, srcs[0] + i, 8);
    for (int s = 1; s < nsrc; s++) {
      uint64_t w;
      memcpy(&w, srcs[s] + i, 8);
      v ^= w;
    }
    memcpy(dst + i, &v, 8);
  }
  for (; i < nb; i++) {
    uint8_t v = srcs[0][i];
    for (int s = 1; s < nsrc; s++) v ^= srcs[s][i];
    dst[i] = v;
  }
}

#if defined(__x86_64__)

// ---- AVX2 level ----

// 32 symbols -> one uint32 per plane: movemask peels the MSB plane, then
// paddb shifts the next bit into MSB position.
__attribute__((target("avx2"))) static void xs_fwd32_avx2(
    const uint8_t* src, uint8_t* const pl[8], uint64_t word_off) {
  __m256i v = _mm256_loadu_si256((const __m256i*)src);
  for (int bit = 7; bit >= 0; bit--) {
    uint32_t w = (uint32_t)_mm256_movemask_epi8(v);
    memcpy(pl[bit] + word_off * 4, &w, 4);
    v = _mm256_add_epi8(v, v);
  }
}

// one uint32 per plane -> 32 symbols: broadcast each plane word, spread its
// bytes across lanes with shuffle, test each lane's bit with cmpeq.
__attribute__((target("avx2"))) static void xs_bwd32_avx2(
    uint8_t* const pl[8], uint64_t word_off, uint8_t* dst) {
  const __m256i sel = _mm256_setr_epi8(
      0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 1, 1, 1, 1,
      2, 2, 2, 2, 2, 2, 2, 2, 3, 3, 3, 3, 3, 3, 3, 3);
  const __m256i bits = _mm256_setr_epi8(
      1, 2, 4, 8, 16, 32, 64, (char)128, 1, 2, 4, 8, 16, 32, 64, (char)128,
      1, 2, 4, 8, 16, 32, 64, (char)128, 1, 2, 4, 8, 16, 32, 64, (char)128);
  __m256i acc = _mm256_setzero_si256();
  for (int bit = 0; bit < 8; bit++) {
    uint32_t w;
    memcpy(&w, pl[bit] + word_off * 4, 4);
    __m256i x = _mm256_broadcastd_epi32(_mm_cvtsi32_si128((int)w));
    __m256i sh = _mm256_shuffle_epi8(x, sel);
    __m256i isset = _mm256_cmpeq_epi8(_mm256_and_si256(sh, bits), bits);
    acc = _mm256_or_si256(acc,
                          _mm256_and_si256(isset, _mm256_set1_epi8((char)(1 << bit))));
  }
  _mm256_storeu_si256((__m256i*)dst, acc);
}

__attribute__((target("avx2"))) static void xs_xor_op_avx2(
    uint8_t* dst, const uint8_t* const* srcs, int nsrc, uint64_t nb) {
  uint64_t i = 0;
  for (; i + 64 <= nb; i += 64) {
    __m256i a0 = _mm256_loadu_si256((const __m256i*)(srcs[0] + i));
    __m256i a1 = _mm256_loadu_si256((const __m256i*)(srcs[0] + i + 32));
    for (int s = 1; s < nsrc; s++) {
      a0 = _mm256_xor_si256(a0, _mm256_loadu_si256((const __m256i*)(srcs[s] + i)));
      a1 = _mm256_xor_si256(a1, _mm256_loadu_si256((const __m256i*)(srcs[s] + i + 32)));
    }
    _mm256_storeu_si256((__m256i*)(dst + i), a0);
    _mm256_storeu_si256((__m256i*)(dst + i + 32), a1);
  }
  for (; i < nb; i++) {
    uint8_t v = srcs[0][i];
    for (int s = 1; s < nsrc; s++) v ^= srcs[s][i];
    dst[i] = v;
  }
}

// ---- GFNI + AVX-512 level ----

#define XS_REV8_BYTES                                                      \
  56, 57, 58, 59, 60, 61, 62, 63, 48, 49, 50, 51, 52, 53, 54, 55, 40, 41, \
      42, 43, 44, 45, 46, 47, 32, 33, 34, 35, 36, 37, 38, 39, 24, 25, 26, \
      27, 28, 29, 30, 31, 16, 17, 18, 19, 20, 21, 22, 23, 8, 9, 10, 11,   \
      12, 13, 14, 15, 0, 1, 2, 3, 4, 5, 6, 7
#define XS_GATHER_BYTES                                                    \
  63, 55, 47, 39, 31, 23, 15, 7, 62, 54, 46, 38, 30, 22, 14, 6, 61, 53,   \
      45, 37, 29, 21, 13, 5, 60, 52, 44, 36, 28, 20, 12, 4, 59, 51, 43,   \
      35, 27, 19, 11, 3, 58, 50, 42, 34, 26, 18, 10, 2, 57, 49, 41, 33,   \
      25, 17, 9, 1, 56, 48, 40, 32, 24, 16, 8, 0

// 512 symbols -> 64 contiguous bytes in each of 8 planes.  Per qword,
// vgf2p8affineqb(IDENT, rev8(x)) is an 8x8 bit transpose (the data rides in
// the matrix operand; IDENT byte i = 1<<i); vpermb then groups each plane's
// 8 bytes, and a 3-stage unpack/shuffle network transposes the 8x8 qword
// block across registers into whole-plane 64-byte stores.
__attribute__((target("gfni,avx512f,avx512bw,avx512vbmi"))) static void
xs_fwd512_gfni(const uint8_t* src, uint8_t* const pl[8], uint64_t boff) {
  const __m512i ident = _mm512_set1_epi64((long long)0x8040201008040201ull);
  const __m512i rev8 = _mm512_set_epi8(XS_REV8_BYTES);
  const __m512i gather = _mm512_set_epi8(XS_GATHER_BYTES);
  __m512i w[8];
  for (int g = 0; g < 8; g++) {
    _mm_prefetch((const char*)(src + 64 * g + 1024), _MM_HINT_T0);
    __m512i v = _mm512_loadu_si512(src + 64 * g);
    v = _mm512_gf2p8affine_epi64_epi8(ident, _mm512_shuffle_epi8(v, rev8), 0);
    w[g] = _mm512_permutexvar_epi8(gather, v);
  }
  __m512i a0 = _mm512_unpacklo_epi64(w[0], w[1]);
  __m512i a1 = _mm512_unpackhi_epi64(w[0], w[1]);
  __m512i a2 = _mm512_unpacklo_epi64(w[2], w[3]);
  __m512i a3 = _mm512_unpackhi_epi64(w[2], w[3]);
  __m512i a4 = _mm512_unpacklo_epi64(w[4], w[5]);
  __m512i a5 = _mm512_unpackhi_epi64(w[4], w[5]);
  __m512i a6 = _mm512_unpacklo_epi64(w[6], w[7]);
  __m512i a7 = _mm512_unpackhi_epi64(w[6], w[7]);
  __m512i b0 = _mm512_shuffle_i64x2(a0, a2, 0x88);
  __m512i b1 = _mm512_shuffle_i64x2(a0, a2, 0xDD);
  __m512i b2 = _mm512_shuffle_i64x2(a1, a3, 0x88);
  __m512i b3 = _mm512_shuffle_i64x2(a1, a3, 0xDD);
  __m512i b4 = _mm512_shuffle_i64x2(a4, a6, 0x88);
  __m512i b5 = _mm512_shuffle_i64x2(a4, a6, 0xDD);
  __m512i b6 = _mm512_shuffle_i64x2(a5, a7, 0x88);
  __m512i b7 = _mm512_shuffle_i64x2(a5, a7, 0xDD);
  _mm512_storeu_si512(pl[0] + boff, _mm512_shuffle_i64x2(b0, b4, 0x88));
  _mm512_storeu_si512(pl[4] + boff, _mm512_shuffle_i64x2(b0, b4, 0xDD));
  _mm512_storeu_si512(pl[1] + boff, _mm512_shuffle_i64x2(b2, b6, 0x88));
  _mm512_storeu_si512(pl[5] + boff, _mm512_shuffle_i64x2(b2, b6, 0xDD));
  _mm512_storeu_si512(pl[2] + boff, _mm512_shuffle_i64x2(b1, b5, 0x88));
  _mm512_storeu_si512(pl[6] + boff, _mm512_shuffle_i64x2(b1, b5, 0xDD));
  _mm512_storeu_si512(pl[3] + boff, _mm512_shuffle_i64x2(b3, b7, 0x88));
  _mm512_storeu_si512(pl[7] + boff, _mm512_shuffle_i64x2(b3, b7, 0xDD));
}

// exact inverse of xs_fwd512_gfni (every stage is an involution)
__attribute__((target("gfni,avx512f,avx512bw,avx512vbmi"))) static void
xs_bwd512_gfni(uint8_t* const pl[8], uint64_t boff, uint8_t* dst) {
  const __m512i ident = _mm512_set1_epi64((long long)0x8040201008040201ull);
  const __m512i rev8 = _mm512_set_epi8(XS_REV8_BYTES);
  const __m512i gather = _mm512_set_epi8(XS_GATHER_BYTES);
  __m512i p[8];
  for (int i = 0; i < 8; i++) p[i] = _mm512_loadu_si512(pl[i] + boff);
  __m512i a0 = _mm512_unpacklo_epi64(p[0], p[1]);
  __m512i a1 = _mm512_unpackhi_epi64(p[0], p[1]);
  __m512i a2 = _mm512_unpacklo_epi64(p[2], p[3]);
  __m512i a3 = _mm512_unpackhi_epi64(p[2], p[3]);
  __m512i a4 = _mm512_unpacklo_epi64(p[4], p[5]);
  __m512i a5 = _mm512_unpackhi_epi64(p[4], p[5]);
  __m512i a6 = _mm512_unpacklo_epi64(p[6], p[7]);
  __m512i a7 = _mm512_unpackhi_epi64(p[6], p[7]);
  __m512i b0 = _mm512_shuffle_i64x2(a0, a2, 0x88);
  __m512i b1 = _mm512_shuffle_i64x2(a0, a2, 0xDD);
  __m512i b2 = _mm512_shuffle_i64x2(a1, a3, 0x88);
  __m512i b3 = _mm512_shuffle_i64x2(a1, a3, 0xDD);
  __m512i b4 = _mm512_shuffle_i64x2(a4, a6, 0x88);
  __m512i b5 = _mm512_shuffle_i64x2(a4, a6, 0xDD);
  __m512i b6 = _mm512_shuffle_i64x2(a5, a7, 0x88);
  __m512i b7 = _mm512_shuffle_i64x2(a5, a7, 0xDD);
  __m512i w[8];
  w[0] = _mm512_shuffle_i64x2(b0, b4, 0x88);
  w[4] = _mm512_shuffle_i64x2(b0, b4, 0xDD);
  w[1] = _mm512_shuffle_i64x2(b2, b6, 0x88);
  w[5] = _mm512_shuffle_i64x2(b2, b6, 0xDD);
  w[2] = _mm512_shuffle_i64x2(b1, b5, 0x88);
  w[6] = _mm512_shuffle_i64x2(b1, b5, 0xDD);
  w[3] = _mm512_shuffle_i64x2(b3, b7, 0x88);
  w[7] = _mm512_shuffle_i64x2(b3, b7, 0xDD);
  for (int g = 0; g < 8; g++) {
    __m512i v = _mm512_permutexvar_epi8(gather, w[g]);
    v = _mm512_gf2p8affine_epi64_epi8(ident, _mm512_shuffle_epi8(v, rev8), 0);
    _mm512_storeu_si512(dst + 64 * g, v);
  }
}

__attribute__((target("avx512f"))) static void xs_xor_op_avx512(
    uint8_t* dst, const uint8_t* const* srcs, int nsrc, uint64_t nb) {
  uint64_t i = 0;
  for (; i + 128 <= nb; i += 128) {
    __m512i a0 = _mm512_loadu_si512(srcs[0] + i);
    __m512i a1 = _mm512_loadu_si512(srcs[0] + i + 64);
    for (int s = 1; s < nsrc; s++) {
      a0 = _mm512_xor_si512(a0, _mm512_loadu_si512(srcs[s] + i));
      a1 = _mm512_xor_si512(a1, _mm512_loadu_si512(srcs[s] + i + 64));
    }
    _mm512_storeu_si512(dst + i, a0);
    _mm512_storeu_si512(dst + i + 64, a1);
  }
  for (; i + 64 <= nb; i += 64) {
    __m512i a0 = _mm512_loadu_si512(srcs[0] + i);
    for (int s = 1; s < nsrc; s++)
      a0 = _mm512_xor_si512(a0, _mm512_loadu_si512(srcs[s] + i));
    _mm512_storeu_si512(dst + i, a0);
  }
  for (; i < nb; i++) {
    uint8_t v = srcs[0][i];
    for (int s = 1; s < nsrc; s++) v ^= srcs[s][i];
    dst[i] = v;
  }
}

#endif  // __x86_64__

// 0 = scalar, 1 = AVX2, 2 = GFNI+AVX-512 (what the executor will use here)
int weedtpu_xorsched_level() {
#if defined(__x86_64__)
  if (__builtin_cpu_supports("gfni") && __builtin_cpu_supports("avx512f") &&
      __builtin_cpu_supports("avx512bw") && __builtin_cpu_supports("avx512vbmi"))
    return 2;
  if (__builtin_cpu_supports("avx2")) return 1;
#endif
  return 0;
}

// One compiled schedule bound to its shard set — the unit the tile runner
// executes.  A single-matrix apply is one block; a block-diagonal fused
// decode is many, each owning a disjoint output range.
struct XsBlock {
  const int32_t* sched;
  uint64_t sched_words;
  uint32_t in_shards;
  uint32_t out_base;
  uint32_t out_shards;
  const uint8_t* const* ins;
  uint8_t* const* outs;
  uint64_t len;
};

// Validate one op stream so a malformed schedule cannot scribble outside
// the slot frame.  Returns the stream's max nsrc, or -1 on a bad stream.
static int32_t xs_validate(const int32_t* sched, uint64_t sched_words,
                           uint32_t n_slots) {
  int32_t max_nsrc = 1;
  for (uint64_t k = 0; k < sched_words;) {
    if (k + 2 > sched_words) return -1;
    int32_t dest = sched[k], nsrc = sched[k + 1];
    if (dest < 0 || (uint32_t)dest >= n_slots || nsrc < 0) return -1;
    if (nsrc > max_nsrc) max_nsrc = nsrc;
    k += 2;
    if (k + (uint64_t)nsrc > sched_words) return -1;
    for (int32_t s = 0; s < nsrc; s++)
      if (sched[k + s] < 0 || (uint32_t)sched[k + s] >= n_slots) return -1;
    k += nsrc;
  }
  return max_nsrc;
}

// Run width tiles [t0, t1) of one block: forward transpose -> XOR replay ->
// backward transpose, all inside the caller's scratch slot frame.  Tiles
// are independent (each covers a disjoint byte range of every shard), so
// disjoint tile ranges of the same block may run on different threads.
static void xs_run_tiles(const XsBlock& b, uint64_t tile_sym, uint64_t plane_b,
                         int level, uint8_t* scratch, const uint8_t** srcs,
                         uint64_t t0, uint64_t t1) {
  for (uint64_t ti = t0; ti < t1; ti++) {
    const uint64_t off = ti * tile_sym;
    const uint64_t w = std::min(tile_sym, b.len - off);
    const uint64_t pw = (w + 7) / 8;
    // forward transpose: shard bytes -> packed bit-planes
    for (uint32_t c = 0; c < b.in_shards; c++) {
      const uint8_t* src = b.ins[c] + off;
      uint8_t* pl[8];
      for (int i = 0; i < 8; i++) pl[i] = scratch + ((uint64_t)c * 8 + i) * plane_b;
      uint64_t s = 0;
#if defined(__x86_64__)
      if (level == 2) {
        const uint64_t w512 = w / 512 * 512;
        for (; s < w512; s += 512) xs_fwd512_gfni(src + s, pl, s / 8);
      } else if (level == 1) {
        const uint64_t w32 = w / 32 * 32;
        for (; s < w32; s += 32) xs_fwd32_avx2(src + s, pl, s / 32);
      }
#endif
      for (; s < w; s += 8) {
        uint64_t x = 0;
        const uint64_t n = std::min<uint64_t>(8, w - s);
        memcpy(&x, src + s, n);
        const uint64_t y = xs_t8(x);
        for (int i = 0; i < 8; i++) pl[i][s / 8] = (uint8_t)(y >> (8 * i));
      }
    }
    // replay the XOR program over this tile's planes
    for (uint64_t k = 0; k < b.sched_words;) {
      const int32_t dest = b.sched[k], nsrc = b.sched[k + 1];
      k += 2;
      uint8_t* d = scratch + (uint64_t)dest * plane_b;
      if (nsrc == 0) {
        memset(d, 0, pw);
        continue;
      }
      for (int32_t j = 0; j < nsrc; j++)
        srcs[(size_t)j] = scratch + (uint64_t)b.sched[k + j] * plane_b;
      k += nsrc;
#if defined(__x86_64__)
      if (level == 2) xs_xor_op_avx512(d, srcs, nsrc, pw);
      else if (level == 1) xs_xor_op_avx2(d, srcs, nsrc, pw);
      else xs_xor_op_scalar(d, srcs, nsrc, pw);
#else
      xs_xor_op_scalar(d, srcs, nsrc, pw);
#endif
    }
    // backward transpose: output planes -> shard bytes
    for (uint32_t r = 0; r < b.out_shards; r++) {
      uint8_t* dst = b.outs[r] + off;
      uint8_t* pl[8];
      for (int i = 0; i < 8; i++)
        pl[i] = scratch + ((uint64_t)b.out_base + (uint64_t)r * 8 + i) * plane_b;
      uint64_t s = 0;
#if defined(__x86_64__)
      if (level == 2) {
        const uint64_t w512 = w / 512 * 512;
        for (; s < w512; s += 512) xs_bwd512_gfni(pl, s / 8, dst + s);
      } else if (level == 1) {
        const uint64_t w32 = w / 32 * 32;
        for (; s < w32; s += 32) xs_bwd32_avx2(pl, s / 32, dst + s);
      }
#endif
      for (; s < w; s += 8) {
        uint64_t y = 0;
        for (int i = 0; i < 8; i++) y |= (uint64_t)pl[i][s / 8] << (8 * i);
        const uint64_t x = xs_t8(y);
        const uint64_t n = std::min<uint64_t>(8, w - s);
        memcpy(dst + s, &x, n);
      }
    }
  }
}

// Replay a compiled XOR schedule.  sched: flat [dest, nsrc, srcs...] int32
// records (sched_words total); slots [0, in_planes) are input planes,
// [out_base, out_base+out_planes) output planes; ins/outs hold in_planes/8
// and out_planes/8 shard pointers of `len` bytes; tile_sym is the per-shard
// tile width (multiple of 512).  Returns 1 on success, 0 on invalid args.
int weedtpu_xor_schedule_apply(const int32_t* sched, uint64_t sched_words,
                               uint32_t n_slots, uint32_t in_planes,
                               uint32_t out_base, uint32_t out_planes,
                               const uint8_t* const* ins, uint8_t* const* outs,
                               uint64_t len, uint64_t tile_sym) {
  if (!sched || !ins || !outs || n_slots == 0 || (in_planes % 8) ||
      (out_planes % 8) || tile_sym < 512 || (tile_sym % 512) ||
      out_base + out_planes > n_slots || in_planes > n_slots)
    return 0;
  const int32_t max_nsrc = xs_validate(sched, sched_words, n_slots);
  if (max_nsrc < 0) return 0;
  const uint64_t plane_b = tile_sym / 8;
  uint8_t* scratch = (uint8_t*)aligned_alloc(64, (size_t)n_slots * plane_b);
  if (!scratch) return 0;
  std::vector<const uint8_t*> srcs((size_t)max_nsrc);
  const XsBlock b = {sched, sched_words, in_planes / 8, out_base,
                     out_planes / 8, ins, outs, len};
  xs_run_tiles(b, tile_sym, plane_b, weedtpu_xorsched_level(), scratch,
               srcs.data(), 0, (len + tile_sym - 1) / tile_sym);
  free(scratch);
  return 1;
}

// Block-diagonal, width-parallel schedule replay: `n_blocks` compiled
// schedules, each bound to its own shard pointers and byte length, run as
// ONE flat (block, tile) task list across a thread pool.  Parallel arrays
// describe the blocks; sched_off/ins_off/outs_off index into the
// concatenated op-word / input-pointer / output-pointer arrays.  All
// blocks share `tile_sym` (one slot-frame geometry, one scratch size).
// threads = 0 means hardware concurrency; the pool is clamped to the
// task count and to a ~256 KiB-per-worker usefulness floor, like
// weedtpu_gf_matrix_apply_mt.  Tiles never share output bytes, so no
// synchronization beyond the final join is needed.  Returns 1 on
// success, 0 on invalid args.
int weedtpu_xor_schedule_apply_blocks(
    const int32_t* sched, const uint64_t* sched_off, const uint64_t* sched_words,
    const uint32_t* n_slots, const uint32_t* in_planes, const uint32_t* out_base,
    const uint32_t* out_planes, const uint8_t* const* ins,
    const uint64_t* ins_off, uint8_t* const* outs, const uint64_t* outs_off,
    const uint64_t* lens, uint32_t n_blocks, uint64_t tile_sym,
    uint32_t threads) {
  if (!sched || !sched_off || !sched_words || !n_slots || !in_planes ||
      !out_base || !out_planes || !ins || !ins_off || !outs || !outs_off ||
      !lens || n_blocks == 0 || tile_sym < 512 || (tile_sym % 512))
    return 0;
  std::vector<XsBlock> blocks((size_t)n_blocks);
  uint32_t max_slots = 0;
  int32_t max_nsrc = 1;
  uint64_t total_bytes = 0;
  // (block, first tile) prefix so tasks flatten to one atomic counter
  std::vector<uint64_t> tile_base((size_t)n_blocks + 1, 0);
  for (uint32_t g = 0; g < n_blocks; g++) {
    if (n_slots[g] == 0 || (in_planes[g] % 8) || (out_planes[g] % 8) ||
        out_base[g] + out_planes[g] > n_slots[g] || in_planes[g] > n_slots[g])
      return 0;
    const int32_t mn = xs_validate(sched + sched_off[g], sched_words[g],
                                   n_slots[g]);
    if (mn < 0) return 0;
    if (mn > max_nsrc) max_nsrc = mn;
    if (n_slots[g] > max_slots) max_slots = n_slots[g];
    blocks[g] = {sched + sched_off[g], sched_words[g], in_planes[g] / 8,
                 out_base[g], out_planes[g] / 8, ins + ins_off[g],
                 outs + outs_off[g], lens[g]};
    tile_base[g + 1] = tile_base[g] + (lens[g] + tile_sym - 1) / tile_sym;
    total_bytes += (uint64_t)(in_planes[g] / 8) * lens[g];
  }
  const uint64_t n_tasks = tile_base[n_blocks];
  if (n_tasks == 0) return 1;  // every block empty: vacuous success
  if (threads == 0) {
    unsigned hw = std::thread::hardware_concurrency();
    threads = hw ? hw : 1;
  }
  // below ~256 KiB per worker, spawn overhead beats the parallel win
  uint64_t max_useful = total_bytes / (256 * 1024);
  if (max_useful < threads) threads = (uint32_t)std::max<uint64_t>(1, max_useful);
  if (threads > n_tasks) threads = (uint32_t)n_tasks;
  const uint64_t plane_b = tile_sym / 8;
  const int level = weedtpu_xorsched_level();
  std::atomic<uint64_t> next{0};
  std::atomic<int> oom{0};
  auto worker = [&]() {
    uint8_t* scratch = (uint8_t*)aligned_alloc(64, (size_t)max_slots * plane_b);
    if (!scratch) {
      oom.store(1);
      return;
    }
    std::vector<const uint8_t*> srcs((size_t)max_nsrc);
    uint32_t g = 0;
    for (;;) {
      const uint64_t t = next.fetch_add(1);
      if (t >= n_tasks) break;
      while (t >= tile_base[g + 1]) g++;  // task ids ascend per worker
      while (t < tile_base[g]) g--;       // (other workers may skip g ahead)
      xs_run_tiles(blocks[g], tile_sym, plane_b, level, scratch, srcs.data(),
                   t - tile_base[g], t - tile_base[g] + 1);
    }
    free(scratch);
  };
  if (threads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(threads);
    for (uint32_t t = 0; t < threads; t++) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return oom.load() ? 0 : 1;
}

}  // extern "C"
