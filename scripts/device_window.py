"""One-shot TPU window worker: when the axon tunnel is alive, harvest
everything VERDICT r4 asks for in priority order, self-budgeted, in ONE
process (never externally killed — SIGTERM mid-dispatch wedges the
tunnel, the r4 lesson):

  1. fresh scan-chain measurement of the XLA path, the retuned fused
     kernel (auto-tile + bf16 MXU variants), AND the rebuild decode path
     (`rebuild_xla_steady_gbps` — the ROADMAP's missing number)
     -> artifacts/DEVICE_MEASUREMENT_r06.json
  2. kernel sweep (tiles x staged variants, byte-exact gated) with
     INCREMENTAL persistence: kernel_sweep.py --out appends one JSON
     line per config as it lands and resumes past configs a previous
     window (or the device_watch.sh-fired sweep) already harvested
     -> artifacts/SWEEP_r06.jsonl, assembled into the committed
     DEVICE_MEASUREMENT_r06.json (the auto-backend evidence file)
  3. config-2-shaped END-TO-END encode through ec/stripe's real file
     path (disk -> device -> .ecNN writes) — device-side AND e2e GB/s;
     e2e here crosses the ~20-25 MB/s axon tunnel, so it is labeled
     tunnel-bound (BASELINE.md's protocol wants both numbers; on real
     hardware host<->device is PCIe/ICI, not a tunnel)
     -> artifacts/E2E_DEVICE_r05.json
  4. remote-survivor distributed rebuild (bench.py's ec_rebuild_remote
     harness: two in-process volume servers, survivors streamed over
     VolumeEcShardSlabRead while the decode runs on-device) — the
     network-overlapped half of the >=10x rebuild target
     -> artifacts/REMOTE_REBUILD_r07.json

Usage: PYTHONPATH=/root/repo:/root/.axon_site python scripts/device_window.py
Writes artifacts/ as it goes; safe to re-run.

`--assemble [SWEEP_PATH]` skips the stages and only re-assembles the
committed DEVICE_MEASUREMENT artifact from the existing stage-1 numbers
plus the (possibly still-growing) sweep harvest — the parse seam the
device_watch.sh -> kernel_sweep --out -> assembler round-trip test
exercises.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "artifacts")
BUDGET_S = float(os.environ.get("WINDOW_BUDGET_S", "1500"))
T0 = time.monotonic()


def left() -> float:
    return BUDGET_S - (time.monotonic() - T0)


def log(msg: str) -> None:
    line = f"{time.strftime('%FT%TZ', time.gmtime())} {msg}"
    print(line, flush=True)
    with open(os.path.join(ART, "device_window.log"), "a", encoding="utf-8") as f:
        f.write(line + "\n")


SWEEP_PATH = os.path.join(ART, "SWEEP_r06.jsonl")
MEASUREMENT_PATH = os.path.join(ART, "DEVICE_MEASUREMENT_r06.json")
# MULTICHIP artifacts live at the repo root beside r01-r05; r06 is the
# first round in the per-mesh-shape evidence format pick_mesh_backend reads
MULTICHIP_PATH = os.path.join(os.path.dirname(ART), "MULTICHIP_r06.json")


def parse_sweep_jsonl(path: str) -> dict:
    """Parse a kernel_sweep.py --out harvest into evidence tables:
    {"encode": {variant: steady_gbps}, "rebuild": {...}, "failed": [...],
    "records": N}. Tolerant of a torn tail line (a sweep crashed
    mid-write) and of cpu-platform sanity records (excluded — only
    on-chip numbers may become auto-backend evidence)."""
    out: dict = {
        "encode": {}, "rebuild": {}, "failed": [], "records": 0,
        "platform": None, "when": None,
    }
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return out
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail from a crash mid-write
        name = rec.get("variant")
        if not name:
            continue
        out["records"] += 1
        if rec.get("platform") == "cpu" or rec.get("tiny"):
            continue  # sanity run — neither evidence NOR an on-chip failure
        if rec.get("error"):
            out["failed"].append(name)
            continue
        gbps = rec.get("steady_gbps")
        if isinstance(gbps, (int, float)):
            table = "rebuild" if name.startswith("rebuild-") else "encode"
            out[table][name] = gbps
            out["platform"] = out["platform"] or rec.get("platform")
            if rec.get("when"):
                out["when"] = max(out["when"] or "", rec["when"])
    return out


def assemble_measurement(meas: dict, sweep_path: str = SWEEP_PATH) -> dict:
    """Fold the incremental sweep harvest into the measurement dict the
    auto-backend factory reads (rs_codec.pick_device_backend): adds the
    `sweep` tables plus `sweep_best_encode` / `sweep_best_rebuild`
    summaries. Safe to call while the sweep is still appending — it
    assembles whatever has landed so far."""
    meas = dict(meas)
    sweep = parse_sweep_jsonl(sweep_path)
    if sweep["records"]:
        # a sweep-only assembly (watch fired the sweep, no stage-1 pass
        # yet) still needs platform/when for the evidence gates
        if sweep["platform"] and not meas.get("platform"):
            meas["platform"] = sweep["platform"]
        if sweep["when"]:
            meas["when"] = max(str(meas.get("when", "")), sweep["when"])
        meas["sweep"] = {"encode": sweep["encode"], "rebuild": sweep["rebuild"]}
        if sweep["failed"]:
            meas["sweep"]["failed"] = sweep["failed"]
        for key, table in (("sweep_best_encode", sweep["encode"]),
                           ("sweep_best_rebuild", sweep["rebuild"])):
            if table:
                best = max(table, key=table.get)
                meas[key] = {"variant": best, "steady_gbps": table[best]}
    return meas


def write_measurement(meas: dict) -> None:
    with open(MEASUREMENT_PATH, "w", encoding="utf-8") as f:
        json.dump(meas, f, indent=1)


def assemble_multichip(mesh_result: dict) -> dict:
    """Normalize a bench `_measure_mesh` result into the committed
    MULTICHIP_r06 evidence artifact: round/when/platform stamped, shapes
    table required (the per-mesh-shape promotion input
    rs_codec.pick_mesh_backend reads), reader-side tags stripped."""
    meas = dict(mesh_result)
    meas.pop("_file", None)
    meas.setdefault("when", time.strftime("%FT%TZ", time.gmtime()))
    meas.setdefault("kind", "multichip")
    meas.setdefault("round", 6)
    if not isinstance(meas.get("shapes"), dict) or not meas["shapes"]:
        raise ValueError("mesh result carries no per-mesh-shape table")
    return meas


def write_multichip(meas: dict) -> None:
    with open(MULTICHIP_PATH, "w", encoding="utf-8") as f:
        json.dump(meas, f, indent=1)


def assemble_only(sweep_path: str = SWEEP_PATH) -> int:
    """--assemble: merge the harvest into the committed artifact without
    touching the device (works even while the watch-fired sweep runs)."""
    try:
        with open(MEASUREMENT_PATH, encoding="utf-8") as f:
            meas = json.load(f)
    except (OSError, ValueError):
        meas = {
            "when": time.strftime("%FT%TZ", time.gmtime()),
            "round": 6,
            "note": "assembled from sweep harvest only; stage-1 scan-chain "
            "numbers pending a device window",
        }
    meas.pop("_file", None)  # reader-side provenance tag, never committed
    assembled = assemble_measurement(meas, sweep_path)
    write_measurement(assembled)
    print(json.dumps(assembled, indent=1))
    return 0


def main() -> int:
    os.makedirs(ART, exist_ok=True)
    import jax

    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()  # JAX_PLATFORMS=cpu sanity runs must not touch the tunnel
    import jax.numpy as jnp
    import numpy as np

    d = jax.devices()[0]
    log(f"window open: platform={d.platform} kind={getattr(d, 'device_kind', '?')}")
    if d.platform == "cpu":
        log("cpu only — aborting window")
        return 1

    from seaweedfs_tpu.ops import gf8, rs_jax, rs_pallas

    pm = gf8.parity_matrix(10, 4)
    b_bits = rs_jax.lifted_matrix(pm)
    B, N = 8, 4 << 20  # 320 MiB of data per encode, bench stage-3 shape
    data_bytes = B * 10 * N
    key = jax.random.PRNGKey(0)
    data = jax.block_until_ready(
        jax.random.randint(key, (B, 10, N), 0, 256, dtype=jnp.uint8)
    )

    from seaweedfs_tpu.ops.measure import scan_chain_gbps

    def steady(encode_fn, out_rows: int = 4) -> float:
        # raises ValueError on a non-measurable slope — the stage wrappers
        # record *_error instead of a bogus 0.0 measurement
        return scan_chain_gbps(encode_fn, data, data_bytes, out_rows=out_rows)

    # -- 1: fresh measurement ------------------------------------------------
    meas = {
        "when": time.strftime("%FT%TZ", time.gmtime()),
        "round": 6,
        "platform": f"{d.platform} ({getattr(d, 'device_kind', '?')})",
        "method": "scan-chain slope, 320 MiB/apply, device-resident, block_until_ready",
    }

    def stage(key: str, fn) -> None:
        try:
            meas[key] = round(fn(), 3)
            log(f"{key}: {meas[key]} GB/s")
        except Exception as e:  # noqa: BLE001
            meas[key + "_error"] = str(e)[:300]
            log(f"{key} stage failed: {e}")

    stage("xla_steady_gbps", lambda: steady(lambda x: rs_jax.gf_apply(b_bits, x)))
    # the r6 retuned defaults: auto_tile (VMEM-budget tiles) and the bf16
    # MXU variant — the two hypotheses for the 19-vs-31 GB/s Pallas gap
    stage(
        "pallas_auto_steady_gbps",
        lambda: steady(lambda x: rs_pallas.gf_apply_fused(b_bits, x)),
    )
    stage(
        "pallas_bf16_steady_gbps",
        lambda: steady(lambda x: rs_pallas.gf_apply_fused(b_bits, x, mxu="bf16")),
    )
    # the r6 staged variants (ROOFLINE verification plan): shift-free
    # unpack, multi-plane accumulation, manual double-buffered DMA — the
    # full tile grid belongs to the sweep; these are the headline configs
    stage(
        "pallas_u8_steady_gbps",
        lambda: steady(lambda x: rs_pallas.gf_apply_fused(b_bits, x, mxu="u8")),
    )
    stage(
        "pallas_mplane_steady_gbps",
        lambda: steady(lambda x: rs_pallas.gf_apply_fused(b_bits, x, mxu="mplane")),
    )
    stage(
        "pallas_dma_steady_gbps",
        lambda: steady(lambda x: rs_pallas.gf_apply_fused(b_bits, x, mxu="dma")),
    )
    stage(
        "pallas_tile8192_steady_gbps",
        lambda: steady(lambda x: rs_pallas.gf_apply_fused(b_bits, x, tile=8192)),
    )
    # rebuild decode path — the ROADMAP's missing rebuild_xla_steady_gbps:
    # ONE fused survivors->missing matrix (worst allowed loss, 2 data +
    # 2 parity) applied to the survivor stack exactly as the pipelined
    # rebuild_ec_files dispatches it
    from seaweedfs_tpu.ops.rs_codec import _reconstruction_matrix

    lost = (0, 5, 11, 13)
    surv = tuple(s for s in range(14) if s not in lost)[:10]
    dm_bits = rs_jax.lifted_matrix(
        _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    )
    stage(
        "rebuild_xla_steady_gbps",
        lambda: steady(lambda x: rs_jax.gf_apply(dm_bits, x), out_rows=len(lost)),
    )
    stage(
        "rebuild_pallas_auto_steady_gbps",
        lambda: steady(
            lambda x: rs_pallas.gf_apply_fused(dm_bits, x), out_rows=len(lost)
        ),
    )
    write_measurement(meas)

    # -- 2: sweep ------------------------------------------------------------
    # budget is checked BEFORE starting and the sweep runs UNBOUNDED: a
    # subprocess timeout would SIGTERM a device dispatch mid-flight — the
    # exact tunnel-wedging action this worker exists to avoid (r4 lesson).
    # --out makes the sweep RESUMABLE: one JSON line persists per config
    # as it lands, and configs the device_watch.sh-fired sweep (or a prior
    # aborted window) already harvested are skipped, so every alive minute
    # extends the harvest instead of restarting it.
    if left() > 600:
        log("running kernel sweep (incremental, resumes prior harvest)")
        import subprocess

        with open(os.path.join(ART, "SWEEP_r06.log"), "a") as out, open(
            os.path.join(ART, "SWEEP_r06.err"), "a"
        ) as err:
            subprocess.run(
                [sys.executable, "scripts/kernel_sweep.py", "--out", SWEEP_PATH],
                cwd=os.path.dirname(ART),
                stdout=out,  # stderr kept separate: warnings must not
                stderr=err,  # corrupt the record stream
            )
        log("sweep done")
    else:
        log("skipping sweep: budget (assembling whatever already landed)")

    # assemble the committed evidence artifact: stage-1 scan-chain numbers
    # + every sweep config that has landed so far. new_encoder("auto")
    # reads exactly this file (rs_codec.pick_device_backend).
    meas = assemble_measurement(meas)
    write_measurement(meas)
    log(
        "assembled %s: sweep_best_encode=%s"
        % (os.path.basename(MEASUREMENT_PATH), meas.get("sweep_best_encode"))
    )

    # -- 3: e2e encode through the real file path ----------------------------
    if left() > 180:
        import tempfile

        from seaweedfs_tpu.ec import stripe
        from seaweedfs_tpu.ops.rs_codec import Encoder

        size = 128 << 20
        with tempfile.TemporaryDirectory() as td:
            base = os.path.join(td, "9")
            rng = np.random.default_rng(5)
            with open(base + ".dat", "wb") as f:
                f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
            with open(base + ".idx", "wb") as f:
                f.write(b"")
            enc = Encoder(10, 4, backend="jax")
            t0 = time.perf_counter()
            stripe.write_ec_files(base, encoder=enc)
            e2e_s = time.perf_counter() - t0
            rec = {
                "when": time.strftime("%FT%TZ", time.gmtime()),
                "dat_bytes": size,
                "e2e_seconds": round(e2e_s, 3),
                "e2e_gbps": round(size / e2e_s / 1e9, 4),
                "device_steady_gbps": meas.get("xla_steady_gbps"),
                "note": "e2e crosses the ~20-25 MB/s axon tunnel (host<->device); "
                "on real hardware this hop is PCIe/ICI — device_steady_gbps is "
                "the chip-side number, e2e_gbps is tunnel-bound here",
            }
            # e2e REBUILD through the depth-N pipelined path: lose the worst
            # allowed pattern, rebuild on-device, depth sweep 1 vs default
            if left() > 120:
                try:
                    for s in (0, 5, 11, 13):
                        os.unlink(stripe.shard_file_name(base, s))
                    t0 = time.perf_counter()
                    stripe.rebuild_ec_files(base, encoder=enc)
                    dt = time.perf_counter() - t0
                    rec["rebuild_e2e_seconds"] = round(dt, 3)
                    rec["rebuild_e2e_gbps"] = round(size / dt / 1e9, 4)
                    for s in (0, 5, 11, 13):
                        os.unlink(stripe.shard_file_name(base, s))
                    t0 = time.perf_counter()
                    stripe.rebuild_ec_files(base, encoder=enc, pipeline_depth=1)
                    rec["rebuild_e2e_depth1_seconds"] = round(
                        time.perf_counter() - t0, 3
                    )
                except Exception as e:  # noqa: BLE001 — rebuild must not zero encode e2e
                    rec["rebuild_e2e_error"] = str(e)[:300]
        with open(os.path.join(ART, "E2E_DEVICE_r06.json"), "w", encoding="utf-8") as f:
            json.dump(rec, f, indent=1)
        log(f"e2e: {rec['e2e_gbps']} GB/s ({rec['e2e_seconds']}s for 128 MiB)")
    else:
        log("skipping e2e: budget")

    # -- 3b: mesh backend — per-mesh-shape encode/rebuild ON-CHIP ------------
    # the pod-promotion evidence: an on-chip MULTICHIP_r06.json whose best
    # achievable shape beats the single-device number flips
    # new_encoder("auto") to the mesh backend (rs_codec.pick_mesh_backend)
    if left() > 300 and jax.device_count() > 1:
        import tempfile

        import bench as bench_mod

        try:
            with tempfile.TemporaryDirectory() as td3:
                mesh_res = bench_mod._measure_mesh(td3)
            write_multichip(assemble_multichip(mesh_res))
            best = max(
                (
                    (rec.get("encode_gbps") or 0, lbl)
                    for lbl, rec in mesh_res["shapes"].items()
                    if isinstance(rec, dict) and rec.get("match")
                ),
                default=(0, None),
            )
            log(
                f"mesh stage: {os.path.basename(MULTICHIP_PATH)} assembled, "
                f"best shape {best[1]}={best[0]} GB/s encode "
                f"(single-device {mesh_res['single_device']['encode_gbps']}), "
                f"ok={mesh_res.get('ok')}"
            )
        except Exception as e:  # noqa: BLE001 — must not zero the harvest
            log(f"mesh stage failed: {e}")
    elif jax.device_count() > 1:
        log("skipping mesh stage: budget")
    else:
        log("skipping mesh stage: single device")

    # -- 4: remote-survivor distributed rebuild, decode on-device ------------
    if left() > 240:
        import tempfile

        import bench as bench_mod
        from seaweedfs_tpu.ops.rs_codec import Encoder as _Enc

        try:
            with tempfile.TemporaryDirectory() as td2:
                rr = bench_mod._measure_rebuild_remote(
                    td2, encoder=_Enc(10, 4, backend="jax")
                )
            with open(
                os.path.join(ART, "REMOTE_REBUILD_r07.json"), "w", encoding="utf-8"
            ) as f:
                json.dump(rr, f, indent=1)
            log(
                f"remote rebuild: {rr.get('remote_rebuild_gbps')} GB/s remote, "
                f"overlap_efficiency={rr.get('overlap_efficiency')}, "
                f"ok={rr.get('ok')}"
            )
        except Exception as e:  # noqa: BLE001 — must not zero the harvest
            log(f"remote rebuild stage failed: {e}")
    else:
        log("skipping remote rebuild: budget")
    log("window complete")
    return 0


if __name__ == "__main__":
    if "--assemble" in sys.argv:
        i = sys.argv.index("--assemble")
        path = sys.argv[i + 1] if i + 1 < len(sys.argv) else SWEEP_PATH
        raise SystemExit(assemble_only(path))
    raise SystemExit(main())
