"""weedload: open-loop SLO load harness for hot-set and degraded EC reads.

Grown out of chaos_soak.py's real-cluster driver: a live master + volume
servers, zipfian keys over the master HTTP front (or the S3 gateway with
--front s3), a CONFIGURABLE degraded fraction (data shards of the EC'd
volume dropped cluster-wide, so their needles reconstruct on every read),
and mid-run chaos (SIGKILL restarts and SIGSTOP wedges of shard holders).
Unlike the soak, the generator is OPEN-LOOP: arrivals fire on a Poisson
schedule at the target rate whether or not earlier requests returned, and
each latency is measured from the request's SCHEDULED arrival — a stalled
server shows up as queueing delay in the tail, exactly like it would for
real users, instead of silently throttling the offered load (the
closed-loop "coordinated omission" failure mode).

Kilo-rps scale comes from --procs N: the driver preloads and classifies,
then spawns N GENERATOR WORKER subprocesses (each its own Python process
and client connection pool, each offering rps/N on its own Poisson clock,
all phase-aligned to one absolute start instant) while the driver runs
chaos; workers ship their latency recorders back as JSON and the driver
merges them bucket-exactly. One GIL never caps the offered load.

Every preloaded needle is classified up front by the stripe math
(.ecx index + interval locate): a read is `degraded` when any of its
intervals lands on a dropped shard (it MUST reconstruct), `ec_intact`
when it lives on the EC volume's surviving shards, `healthy` when it
lives on a plain replicated volume. At serving time the volume server's
X-Weedtpu-Read-Class response header refines that: a statically-degraded
read answered from the decoded-interval cache records as `cached`, so
the artifact separates cache hits from real decodes — the hot-set
serving comparison (cached p99 vs decoded p99) this harness exists for.
The decoded-interval cache runs with a short TTL (the "epoch") so the
decoded class keeps earning fresh samples after warmup instead of
starving behind a fully-warm cache.

Chaos runs start the master with WEEDTPU_REPAIR=on: the fleet-repair
scheduler is part of the serving story under kills, not a separate mode.
A guard thread re-drops the DELIBERATELY dropped shards whenever the
scheduler dutifully rebuilds them (counted as repairs_reverted) so the
degraded class keeps existing.

Shards 5-9 are spread to TWO extra holders so degraded fan-outs cross
the network and hedged fetches have a second holder to race.

Usage (real run; writes artifacts/SLO_r02.json):
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo:/root/.axon_site \
      python scripts/weedload.py --seconds 30 --rps 1000 --procs 4 --chaos
Smoke (tier-1; in-process servers, <=20 s, schema + cache-hit +
zero-loss gate):
  python scripts/weedload.py --smoke --out /tmp/SLO_smoke.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")

#: serving classes the volume server's read-class header may answer; a
#: header value outside this set (or a front that strips it) falls back
#: to the static stripe-math classification
OBSERVED_CLASSES = ("healthy", "ec_intact", "cached", "degraded")

#: S3-front credentials (loopback bench identity, not a secret)
S3_AK, S3_SK = "weedloadAccessKey", "weedloadSecretKey"

#: counters scraped from every node's /metrics at run end — the server-side
#: evidence that hedging/coalescing/admission/caching actually engaged
SCRAPED_COUNTERS = (
    "weedtpu_hedge_fired_total",
    "weedtpu_hedge_won_total",
    "weedtpu_coalesced_reads_total",
    "weedtpu_rebuild_admission_waits_total",
    "weedtpu_degraded_read_seconds_count",
    "weedtpu_degraded_read_errors_total",
    "weedtpu_ec_repair_network_bytes_total",
    "weedtpu_inline_ec_rows_total",
    "weedtpu_inline_ec_bytes_total",
    "weedtpu_inline_ec_delta_updates_total",
    "weedtpu_inline_ec_seals_total",
    "weedtpu_scrub_bytes_scanned_total",
    "weedtpu_scrub_corruptions_found_total",
    "weedtpu_scrub_repairs_total",
    "weedtpu_scrub_cycles_total",
    "weedtpu_ec_convert_bytes_total",
    "weedtpu_ec_convert_seconds_count",
    # fleet repair scheduler (master-side: the master's /metrics is
    # scraped too on subprocess runs) + inline parity spreading
    "weedtpu_repair_dispatch_total",
    "weedtpu_repair_backoff_total",
    "weedtpu_inline_ec_spread_bytes_total",
    "weedtpu_inline_ec_spread_commits_total",
    # decoded-interval cache (read planner)
    "weedtpu_read_cache_hits_total",
    "weedtpu_read_cache_misses_total",
    "weedtpu_read_cache_evictions_total",
    "weedtpu_read_cache_invalidations_total",
)


def parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seconds", type=float, default=120.0,
                   help="measured load time (split steady/chaos)")
    p.add_argument("--rps", type=float, default=40.0, help="offered arrival rate")
    p.add_argument("--procs", type=int, default=1,
                   help="generator worker processes; >1 spawns that many "
                        "subprocess open-loop generators each offering "
                        "rps/N (kilo-rps needs more than one GIL), phase-"
                        "aligned to one absolute start time while the "
                        "driver runs chaos and merges their recorders")
    p.add_argument("--front", choices=("master", "s3"), default="master",
                   help="serving front the load goes through: the master "
                        "HTTP redirect front (direct fid reads, per-read "
                        "class header), or the S3 gateway (signed V4 "
                        "requests through filer+s3 in-process; classes "
                        "come from the objects' chunk fids)")
    p.add_argument("--objects", type=int, default=160, help="preloaded objects")
    p.add_argument("--zipf", type=float, default=1.1, help="zipf skew s")
    p.add_argument("--concurrency", type=int, default=64,
                   help="client worker threads (open-loop: queueing counts)")
    p.add_argument("--client-timeout", type=float, default=2.0,
                   help="per-location HTTP timeout: a wedged replica costs "
                        "this much before the client fails over, for healthy "
                        "and degraded traffic alike (30 s would let one "
                        "SIGSTOP dominate every class's tail)")
    p.add_argument("--put-fraction", type=float, default=0.0,
                   help="fraction of arrivals that are PUTs (assign + upload "
                        "over the master HTTP front). Any value > 0 also "
                        "starts the servers with WEEDTPU_INLINE_EC=on so "
                        "every PUT streams through the encode-on-write "
                        "stripe builders — the write-heavy workload. PUT "
                        "latency lands in the artifact under class `put`. "
                        "Requires --procs 1 and --front master")
    p.add_argument("--dropped-shards", type=int, nargs="*", default=[0, 1],
                   help="data shards deleted cluster-wide (degraded fraction)")
    p.add_argument("--ec-large-block", type=int, default=1 << 20,
                   help="EC large-block size for the converted volume: "
                        "small relative to the volume so needles stripe "
                        "across shards (the production 1 GB default would "
                        "put a bench-sized volume entirely on shard 0)")
    p.add_argument("--ec-small-block", type=int, default=16 << 10)
    p.add_argument("--chaos", action="store_true",
                   help="second phase with kills + SIGSTOP wedges; the "
                        "master runs the fleet-repair scheduler "
                        "(WEEDTPU_REPAIR=on) for the whole run")
    p.add_argument("--rebuild-storm", action="store_true",
                   help="launch concurrent remote rebuilds mid-chaos so "
                        "bulk slab streams contend with foreground reads "
                        "through the admission gate (servers start with "
                        "WEEDTPU_REBUILD_MAX_INFLIGHT=4 unless overridden)")
    p.add_argument("--corrupt", action="store_true",
                   help="inject silent corruption on live servers mid-run "
                        "(bit-flips, truncations, deletions of EC shard "
                        "files, cycling) with the background scrubber ON — "
                        "measures detect -> quarantine -> auto-repair under "
                        "load, and the SLO with scrub + repair active; "
                        "every injection is verified healed (bytes match "
                        "the .eci record again) in the artifact")
    p.add_argument("--wedge-seconds", type=float, default=12.0,
                   help="SIGSTOP duration (must outlast the 10 s per-holder "
                        "transport timeout for the suspicion path to fire)")
    p.add_argument("--slo-factor", type=float, default=5.0)
    p.add_argument("--out", default=None,
                   help="artifact path; defaults to artifacts/SLO_r02.json "
                        "for real runs and a /tmp path for --smoke (a "
                        "casual smoke must never overwrite the committed "
                        "real-run evidence)")
    p.add_argument("--trace-out", default=None,
                   help="tail-attribution artifact path (per-stage p50/p99 "
                        "per class + the slowest full span trees, scraped "
                        "from every node's /debug/traces); defaults to "
                        "artifacts/TRACE_ATTRIB_r02.json for real runs and "
                        "a /tmp path for --smoke")
    p.add_argument("--smoke", action="store_true",
                   help="tiny in-process cluster, <=20 s, schema + "
                        "cache-hit-rate gate")
    p.add_argument("--require-slo", action="store_true",
                   help="exit 2 when the SLO verdict is not ok")
    p.add_argument("--seed", type=int, default=7)
    # -- generator-worker mode (internal; the driver spawns these) ----------
    p.add_argument("--gen-worker", default=None, help=argparse.SUPPRESS)
    p.add_argument("--worker-out", default=None, help=argparse.SUPPRESS)
    p.add_argument("--worker-index", type=int, default=0, help=argparse.SUPPRESS)
    return p.parse_args(argv)


def classify_needles(base: str, dropped: set[int]) -> tuple[set[int], set[int]]:
    """(degraded_ids, all_ids) for the EC volume at `base`: a needle is
    degraded when ANY of its record intervals maps to a dropped shard —
    the same locate math the serving path runs, executed offline on the
    committed .ecx/.eci, so the classification is exact, not sampled."""
    from seaweedfs_tpu.ec import locate as locate_mod
    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage import types

    info = stripe.read_ec_info(base)
    assert info is not None, f"{base}.eci missing — cannot classify"
    large, small = int(info["large_block_size"]), int(info["small_block_size"])
    dat_size = int(info["dat_size"])
    with open(base + ".ecx", "rb") as f:
        entries = idx_mod.index_entries_array(f.read())
    degraded, everyone = set(), set()
    for i in range(len(entries)):
        key = int(entries[i]["key"])
        size = int(entries[i]["size"])
        if types.is_deleted(size):
            continue
        everyone.add(key)
        off = types.offset_to_actual(int(entries[i]["offset"]))
        whole = types.actual_size(size, 3)
        ivs = locate_mod.locate_data(large, small, dat_size, off, whole)
        if any(iv.to_shard_id_and_offset(large, small)[0] in dropped for iv in ivs):
            degraded.add(key)
    return degraded, everyone


def zipf_cdf(n: int, s: float) -> list[float]:
    weights = [1.0 / (rank ** s) for rank in range(1, n + 1)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    return cdf


def pick_zipf(rng: random.Random, keys: list, cdf: list[float]):
    import bisect

    return keys[min(bisect.bisect_left(cdf, rng.random()), len(keys) - 1)]


def measure_trace_overhead(
    client, fids: list, rounds: int = 8, batch: int = 40,
    attempts: int = 3, tol: float = 0.05, abs_floor_us: float = 100.0,
) -> dict:
    """The tracing-on overhead gate: healthy reads against the SAME live
    cluster with `WEEDTPU_TRACE` toggled per batch, interleaved ABBA
    (which mode goes first alternates per round) so clock drift, page
    cache, and GC land evenly on both sides — the only honest way to
    resolve a 5% bound on a shared machine. A real regression fails all
    `attempts` measurements; a scheduler artifact fails at most one, so
    the gate passes if ANY attempt holds both bounds (p99 within `tol`,
    throughput within `tol`). Each bound also accepts an absolute floor:
    loopback reads run in the hundreds of microseconds, where tracing's
    fixed few-dozen-µs cost is a large *fraction* yet invisible against
    any real (ms-scale, network + decode) read — so a delta at or under
    `abs_floor_us` per read passes even when the ratio does not.
    Smoke-only: the in-process cluster shares this process's
    environment, which is what makes the per-batch toggle land on the
    servers."""
    import itertools

    prev = os.environ.get("WEEDTPU_TRACE")

    def pct(xs: list, q: float) -> float:
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    def one_attempt() -> dict:
        lat = {"on": [], "off": []}
        busy = {"on": 0.0, "off": 0.0}
        it = itertools.cycle(fids)
        for r in range(rounds):
            for mode in ("on", "off") if r % 2 == 0 else ("off", "on"):
                os.environ["WEEDTPU_TRACE"] = mode
                t0 = time.monotonic()
                for _ in range(batch):
                    fid = next(it)
                    s0 = time.monotonic()
                    client.read(fid)
                    lat[mode].append(time.monotonic() - s0)
                busy[mode] += time.monotonic() - t0
        n = rounds * batch
        p99_on, p99_off = pct(lat["on"], 0.99), pct(lat["off"], 0.99)
        rps_on, rps_off = n / busy["on"], n / busy["off"]
        mean_delta_us = (busy["on"] - busy["off"]) / n * 1e6
        p99_delta_us = (p99_on - p99_off) * 1e6
        return {
            "samples_per_mode": n,
            "p50_ms": {
                "on": round(pct(lat["on"], 0.5) * 1e3, 3),
                "off": round(pct(lat["off"], 0.5) * 1e3, 3),
            },
            "p99_ms": {
                "on": round(p99_on * 1e3, 3),
                "off": round(p99_off * 1e3, 3),
            },
            "rps": {"on": round(rps_on, 1), "off": round(rps_off, 1)},
            "p99_ratio": round(p99_on / p99_off, 4) if p99_off else None,
            "throughput_ratio": round(rps_on / rps_off, 4) if rps_off else None,
            "mean_delta_us_per_read": round(mean_delta_us, 1),
            "p99_delta_us": round(p99_delta_us, 1),
            "ok": (
                p99_off > 0
                and (p99_on / p99_off <= 1.0 + tol or p99_delta_us <= abs_floor_us)
                and (rps_on / rps_off >= 1.0 - tol or mean_delta_us <= abs_floor_us)
            ),
        }

    out = {
        "method": "interleaved-ABBA",
        "tolerance": tol,
        "abs_floor_us": abs_floor_us,
        "attempts": [],
    }
    try:
        for fid in fids[: min(len(fids), 20)]:
            client.read(fid)  # warmup: page cache + connection reuse
        for _ in range(attempts):
            a = one_attempt()
            out["attempts"].append(a)
            if a["ok"]:
                break
    finally:
        if prev is None:
            os.environ.pop("WEEDTPU_TRACE", None)
        else:
            os.environ["WEEDTPU_TRACE"] = prev
    out["ok"] = any(a["ok"] for a in out["attempts"])
    return out


class TraceScraper:
    """Accumulates every node's retained `/debug/traces` span trees
    across process generations (same discipline as CounterScraper: a
    victim is scraped right before its kill, everyone at run end).
    Dedup is by RECORD identity — (node, trace id, kind, start,
    duration) — so scraping the same generation twice cannot double a
    record in the attribution quantiles, while one propagated id's
    DISTINCT records (the serving http.read root, EACH holder's
    rpc.server continuation, even two continuations inside one holder)
    all survive: any coarser key lets whichever record scrapes first
    shadow the rest."""

    def __init__(self) -> None:
        self._traces: dict[tuple, dict] = {}

    @property
    def traces(self) -> dict:
        return self._traces

    def scrape(self, http_port: int) -> None:
        url = f"http://127.0.0.1:{http_port}/debug/traces?limit=1000000"
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                payload = json.loads(r.read().decode())
        except Exception:  # noqa: BLE001 — a dead node scrapes as nothing
            return
        for t in payload.get("traces", ()):
            key = (
                http_port, t["trace_id"], t["kind"],
                t.get("start"), t.get("duration_s"),
            )
            self._traces.setdefault(key, t)


class CounterScraper:
    """Accumulates the servers' /metrics counters ACROSS process
    generations: a killed-and-restarted node comes back with zeroed
    counters, so the chaos loop scrapes each victim right before the
    kill and the run end scrapes everyone — every generation is counted
    exactly once and a restart can no longer erase the evidence that
    hedging/coalescing/admission engaged."""

    def __init__(self) -> None:
        self.totals: dict[str, float] = {name: 0.0 for name in SCRAPED_COUNTERS}

    def scrape(self, http_port: int) -> None:
        url = f"http://127.0.0.1:{http_port}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                text = r.read().decode()
        except Exception:  # noqa: BLE001 — a dead node scrapes as zero
            return
        for line in text.splitlines():
            if line.startswith("#") or " " not in line:
                continue
            name_part, _, value = line.rpartition(" ")
            bare = name_part.split("{", 1)[0]
            if bare in self.totals:
                try:
                    self.totals[bare] += float(value)
                except ValueError:
                    continue


def ec_encode_and_spread(
    rpc_mod, VOLUME_SERVICE, nodes, vid: int, dropped: list[int],
    large_block: int, small_block: int, collection: str = "",
) -> str:
    """EC-encode `vid` on its owner, spread shards 5-9 to two other
    holders (hedging needs a second holder to race), drop `dropped`
    cluster-wide, and return the owner's base path (for classification).
    `collection` must match the volume's collection (s3-front objects
    land in their bucket's collection, so the on-disk base is
    `<collection>_<vid>`, and every shard RPC resolves paths from it).
    `nodes` entries expose .grpc (port) and .dir — true for both the
    subprocess Node and the in-process shim."""
    owner = None
    for n in nodes:
        try:
            with rpc_mod.RpcClient(f"127.0.0.1:{n.grpc}") as c:
                st = c.call(VOLUME_SERVICE, "VolumeStatus", {"volume_id": vid})
            if st.get("kind") == "normal":
                owner = n
                break
        except Exception:  # noqa: BLE001 — not the owner
            continue
    assert owner is not None, f"no node owns volume {vid}"
    with rpc_mod.RpcClient(f"127.0.0.1:{owner.grpc}") as c:
        c.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
        c.call(
            VOLUME_SERVICE, "VolumeEcShardsGenerate",
            {
                "volume_id": vid,
                "collection": collection,
                "large_block_size": large_block,
                "small_block_size": small_block,
            },
            timeout=300,
        )
        c.call(
            VOLUME_SERVICE, "VolumeEcShardsMount",
            {"volume_id": vid, "collection": collection},
        )
    # the normal volume must vanish from EVERY holder, replicas included:
    # with replication 001 a surviving replica would keep serving these
    # needles as a plain volume and the "degraded" class would silently
    # measure replica reads whenever the master lists the replica first
    for n in nodes:
        try:
            with rpc_mod.RpcClient(f"127.0.0.1:{n.grpc}") as c:
                c.call(VOLUME_SERVICE, "VolumeDelete", {"volume_id": vid})
        except Exception:  # noqa: BLE001 — node never held a replica
            continue
    # survivable 2-resident placement: owner keeps the non-spread shards,
    # both peers take 5-9, the second peer additionally mirrors the rest —
    # every surviving shard then has TWO holders, so one killed/wedged
    # node never makes the stripe unreadable (and every hedged fetch has
    # a second holder to race)
    spread = [s for s in (5, 6, 7, 8, 9) if s not in dropped]
    rest = [s for s in range(14) if s not in dropped and s not in spread]
    others = [n for n in nodes if n is not owner][:2]
    for peer, shard_sets in ((others[0], [spread]), (others[1], [spread, rest])):
        with rpc_mod.RpcClient(f"127.0.0.1:{peer.grpc}") as c:
            for shard_ids in shard_sets:
                c.call(
                    VOLUME_SERVICE, "VolumeEcShardsCopy",
                    {
                        "volume_id": vid,
                        "collection": collection,
                        "shard_ids": shard_ids,
                        "source_data_node": f"127.0.0.1:{owner.grpc}",
                        "copy_ecx_file": True,
                    },
                    timeout=120,
                )
            c.call(
                VOLUME_SERVICE, "VolumeEcShardsMount",
                {"volume_id": vid, "collection": collection},
            )
    with rpc_mod.RpcClient(f"127.0.0.1:{owner.grpc}") as c:
        c.call(
            VOLUME_SERVICE, "VolumeEcShardsDelete",
            {
                "volume_id": vid,
                "collection": collection,
                "shard_ids": sorted(set(spread) | set(dropped)),
            },
        )
    base_name = f"{collection}_{vid}" if collection else str(vid)
    return os.path.join(owner.dir, base_name)


class _InprocNode:
    """chaos_soak.Node-shaped shim around an in-process VolumeServer so
    the smoke path reuses the exact encode/spread/load machinery (no
    subprocess spawn in tier-1's 20 s budget). Wedges/kills are no-ops:
    you cannot SIGSTOP your own test process."""

    def __init__(self, i: int, dirpath: str, master_addr: str):
        from seaweedfs_tpu.cluster.volume_server import VolumeServer

        self.i = i
        self.dir = dirpath
        self.vs = VolumeServer(
            [dirpath], master_addr, heartbeat_interval=0.5, max_volume_count=30
        )
        self.vs.start()
        self.grpc = self.vs.grpc_port
        self.http = self.vs.port
        self.wedged = False

    @property
    def alive(self) -> bool:
        return True

    def stop(self) -> None:
        self.vs.stop()


def run_load(
    args, read_fn, rec, lost, keys, cdf, klass_of, phases: list[tuple[str, float]],
    chaos_fn=None, put_fn=None,
):
    """Open-loop Poisson arrivals over `phases` ([(name, seconds), ...]):
    latency is measured from each request's SCHEDULED time, so server
    stalls surface as tail latency instead of reduced offered load.
    `read_fn(key) -> (bytes, served_class|None)` is the front adapter;
    the served class (the volume server's read-class header) overrides
    the static stripe-math class when present, so a cache hit on a
    statically-degraded key records as `cached`. `put_fn(sched, phase)`
    (when given) serves the --put-fraction share of arrivals — write
    traffic interleaved with the read mix, same open-loop accounting."""
    rng = random.Random(args.seed + 1)
    pool = ThreadPoolExecutor(max_workers=args.concurrency)
    issued = 0

    def one(fid: str, want: bytes, sched: float, phase: str) -> None:
        static_klass = klass_of(fid)
        try:
            got, served = read_fn(fid)
        except Exception:  # noqa: BLE001 — open loop records, never retries
            rec.error(phase, static_klass)
            return
        lat = time.monotonic() - sched
        if got != want:
            lost.append({"fid": fid, "why": "BYTES DIFFER (live read)"})
            rec.error(phase, static_klass)
        else:
            klass = served if served in OBSERVED_CLASSES else static_klass
            rec.observe(phase, klass, lat)

    try:
        for phase, seconds in phases:
            stop_chaos = threading.Event()
            chaos_thread = None
            if chaos_fn is not None and phase == "chaos":
                chaos_thread = threading.Thread(
                    target=chaos_fn, args=(stop_chaos,), daemon=True
                )
                chaos_thread.start()
            t_end = time.monotonic() + seconds
            next_t = time.monotonic()
            while True:
                now = time.monotonic()
                if now >= t_end:
                    break
                if now < next_t:
                    time.sleep(min(next_t - now, 0.02))
                    continue
                if put_fn is not None and rng.random() < args.put_fraction:
                    pool.submit(put_fn, next_t, phase)
                else:
                    fid = pick_zipf(rng, keys, cdf)
                    pool.submit(one, fid, client_blobs[fid], next_t, phase)
                issued += 1
                next_t += rng.expovariate(args.rps)
            stop_chaos.set()
            if chaos_thread is not None:
                chaos_thread.join(timeout=args.wedge_seconds + 10)
    finally:
        pool.shutdown(wait=True)
    return issued


def run_worker(args) -> int:
    """One generator worker subprocess (--gen-worker): an independent
    open-loop Poisson generator at spec rps, phase-aligned to the spec's
    absolute start instant shared by every worker and the driver's chaos
    clock. Blob bytes stay in the driver; the spec carries each fid's
    sha256 + static class, and each read verifies content by digest.
    Results (bucketed latency cells, issued count, losses) are written
    as JSON for the driver to merge."""
    with open(args.gen_worker, encoding="utf-8") as f:
        spec = json.load(f)
    from seaweedfs_tpu.cluster.client import MasterClient
    from seaweedfs_tpu.ec import slo

    rec = slo.LatencyRecorder()
    lost: list[dict] = []
    fids: dict[str, dict] = spec["fids"]
    keys = sorted(fids)
    # the SAME shuffle in every worker: the zipf hot set must be shared
    # across generators or the aggregate offered load has no hot set and
    # the cache has nothing to serve
    random.Random(spec["seed"]).shuffle(keys)
    cdf = zipf_cdf(len(keys), spec["zipf"])
    # arrivals are per-worker independent Poisson clocks (superposition
    # of N Poisson streams at rps/N is one Poisson stream at rps)
    rng = random.Random(spec["seed"] * 7919 + args.worker_index)
    client = MasterClient(spec["master"], http_timeout=spec["client_timeout"])
    pool = ThreadPoolExecutor(max_workers=spec["concurrency"])
    issued = 0

    def one(fid: str, sched: float, phase: str) -> None:
        info = fids[fid]
        try:
            got, served = client.read_ex(fid)
        except Exception:  # noqa: BLE001 — open loop records, never retries
            rec.error(phase, info["klass"])
            return
        lat = time.monotonic() - sched
        if hashlib.sha256(got).hexdigest() != info["sha256"]:
            lost.append({
                "fid": fid,
                "why": "BYTES DIFFER (live read)",
                "worker": args.worker_index,
            })
            rec.error(phase, info["klass"])
        else:
            klass = served if served in OBSERVED_CLASSES else info["klass"]
            rec.observe(phase, klass, lat)

    delay = spec["start_at"] - time.time()
    if delay > 0:
        time.sleep(delay)
    try:
        next_t = time.monotonic()
        for phase, seconds in spec["phases"]:
            t_end = time.monotonic() + seconds
            while True:
                now = time.monotonic()
                if now >= t_end:
                    break
                if now < next_t:
                    time.sleep(min(next_t - now, 0.02))
                    continue
                fid = pick_zipf(rng, keys, cdf)
                pool.submit(one, fid, next_t, phase)
                issued += 1
                next_t += rng.expovariate(spec["rps"])
    finally:
        pool.shutdown(wait=True)
        client.close()
    with open(args.worker_out, "w", encoding="utf-8") as f:
        json.dump({"issued": issued, "cells": rec.to_dict(), "lost": lost}, f)
    return 0


client_blobs: dict[str, bytes] = {}  # fid -> expected bytes (module-level
# so the worker closure in run_load stays picklable-simple)


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if args.gen_worker:
        return run_worker(args)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rng = random.Random(args.seed)

    from seaweedfs_tpu import rpc as rpc_mod
    from seaweedfs_tpu.cluster.client import MasterClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.ec import slo
    from seaweedfs_tpu.pb import VOLUME_SERVICE
    from seaweedfs_tpu.storage.file_id import FileId
    from seaweedfs_tpu.utils import config

    if args.smoke:
        args.seconds = min(args.seconds, 4.0)
        args.objects = min(args.objects, 30)
        args.rps = min(args.rps, 30.0)
        args.chaos = False
        args.procs = 1
    if args.put_fraction > 0:
        assert args.procs == 1, "--put-fraction requires --procs 1"
        assert args.front == "master", "--put-fraction requires --front master"
    if args.front == "s3":
        assert args.procs == 1, "--front s3 requires --procs 1"
        assert not args.corrupt, "--corrupt requires --front master"
        assert not args.rebuild_storm, "--rebuild-storm requires --front master"
    if args.out is None:
        if args.smoke:
            args.out = os.path.join(tempfile.gettempdir(), "SLO_smoke.json")
        elif args.corrupt:
            # corruption-soak artifacts join the SOAK_r* family: this is
            # failure-injection evidence, not a plain latency run
            args.out = os.path.join(ART, "SOAK_r10.json")
        else:
            args.out = os.path.join(ART, "SLO_r02.json")

    if args.trace_out is None:
        if args.smoke:
            args.trace_out = os.path.join(
                tempfile.gettempdir(), "TRACE_ATTRIB_smoke.json"
            )
        else:
            args.trace_out = os.path.join(ART, "TRACE_ATTRIB_r02.json")
    # tracing rides along by default (WEEDTPU_TRACE=on): widen the
    # sampled ring so the per-stage quantiles aggregate over ~the whole
    # run's traces, not a tail-biased subset (retention bias would
    # flatter exactly the stages the attribution is about). Subprocess
    # servers pick the env up at exec; the in-process smoke cluster's
    # module-global RING was already constructed at import (possibly by
    # the hosting test process, long before this env write), so its
    # capacity is widened directly.
    os.environ.setdefault("WEEDTPU_TRACE_RING", "65536")
    from seaweedfs_tpu.obs import trace as trace_obs

    trace_obs.RING.capacity = max(trace_obs.RING.capacity, 65536)

    # hot-set serving is the point of this harness: force the decoded-
    # interval cache ON even when the hosting environment zeroed the
    # budget (the test suite's autouse fixture runs the cache default-off
    # to protect decode-count assertions elsewhere). The TTL ("epoch")
    # stays SHORT so warm entries keep expiring and the decoded class
    # keeps earning real reconstruction samples alongside cache hits.
    try:
        _cache_mb = float(os.environ.get("WEEDTPU_READ_CACHE_MB", "0") or 0.0)
    except ValueError:
        _cache_mb = 0.0
    if _cache_mb <= 0:
        os.environ["WEEDTPU_READ_CACHE_MB"] = "64"
    os.environ.setdefault(
        "WEEDTPU_READ_CACHE_TTL_S", "2.0" if args.smoke else "5.0"
    )

    if args.chaos:
        # the fleet-repair scheduler is part of the serving story under
        # kills: killed holders' shards draw mass-rebuild dispatches
        # while the load runs. Must land BEFORE MasterServer() — the
        # master reads it once at construction.
        os.environ.setdefault("WEEDTPU_REPAIR", "on")
    if args.rebuild_storm:
        # must land BEFORE the server processes start (they read it once
        # at init); a tight gate makes the storm actually queue
        os.environ.setdefault("WEEDTPU_REBUILD_MAX_INFLIGHT", "4")
    if args.put_fraction > 0:
        # write traffic exercises the encode-on-write path: servers start
        # with inline EC on and a bench-scale stripe geometry so PUT-fed
        # volumes actually complete large rows within the run (the
        # production 1 GiB rows would never fill here)
        os.environ.setdefault("WEEDTPU_INLINE_EC", "on")
        os.environ.setdefault("WEEDTPU_INLINE_EC_LARGE_BLOCK", str(256 << 10))
        os.environ.setdefault("WEEDTPU_INLINE_EC_SMALL_BLOCK", str(16 << 10))
    if args.corrupt:
        # corruption mode runs the scrubber hot (short cycle, no rate cap,
        # prompt repair retries) so detection latency is scan-bound, not
        # idle-bound; must land before the server processes start
        os.environ.setdefault("WEEDTPU_SCRUB", "on")
        os.environ.setdefault("WEEDTPU_SCRUB_INTERVAL", "0.5")
        os.environ.setdefault("WEEDTPU_SCRUB_RATE_MB", "0")
        os.environ.setdefault("WEEDTPU_SCRUB_REPAIR_BACKOFF", "1.0")

    rec = slo.LatencyRecorder()
    lost: list[dict] = []
    trace_overhead = None
    chaos_report = {"mode": "kill+wedge" if args.chaos else "none",
                    "kills": 0, "wedges": 0}
    if args.chaos:
        chaos_report["repair_scheduler"] = "on"
        chaos_report["repairs_reverted"] = 0

    with tempfile.TemporaryDirectory() as td:
        master = MasterServer(port=0, reap_interval=3600)
        master.start()
        nodes = []
        client = None
        filer_srv = s3_srv = filer_client = None
        try:
            if args.smoke:
                for i in range(3):
                    d = os.path.join(td, f"n{i}")
                    os.makedirs(d)
                    nodes.append(_InprocNode(i, d, master.address))
            else:
                from chaos_soak import Node

                for i in range(3):
                    d = os.path.join(td, f"n{i}")
                    os.makedirs(d)
                    n = Node(i, d, master.address)
                    n.start()
                    nodes.append(n)
            client = MasterClient(master.address, http_timeout=args.client_timeout)
            deadline0 = time.monotonic() + 60
            while time.monotonic() < deadline0 and len(master.topology.nodes) < 3:
                time.sleep(0.3)
            assert len(master.topology.nodes) == 3, "cluster did not form"

            # -- front adapters: how objects get written, read back, and
            # mapped to the needle fids the stripe math classifies ----------
            if args.front == "s3":
                from seaweedfs_tpu.filer import FilerServer
                from seaweedfs_tpu.filer.client import FilerClient
                from seaweedfs_tpu.s3api import (
                    Iam, Identity, S3ApiServer, sign_request,
                )

                filer_srv = FilerServer(master.address, chunk_size=1 << 20)
                filer_srv.start()
                s3_srv = S3ApiServer(
                    filer_srv.url,
                    filer_srv.grpc_address,
                    iam=Iam([Identity("weedload", S3_AK, S3_SK)]),
                )
                s3_srv.start()
                filer_client = FilerClient(filer_srv.grpc_address)

                def _s3_req(method, key, body=b""):
                    url = f"http://{s3_srv.url}{key}"
                    h = sign_request(S3_AK, S3_SK, method, url, body)
                    req = urllib.request.Request(
                        url, data=body if body else None, method=method,
                        headers=h,
                    )
                    with urllib.request.urlopen(
                        req, timeout=args.client_timeout + 10
                    ) as r:
                        return r.read(), r.headers

                _s3_req("PUT", "/load")
                s3_seq = [0]
                _chunk_cache: dict[str, list[str]] = {}

                def fids_of(key: str) -> list[str]:
                    chunks = _chunk_cache.get(key)
                    if chunks is None:
                        ent = filer_client.lookup(f"/buckets{key}")
                        chunks = [c.fid for c in (ent.chunks or [])] if ent else []
                        _chunk_cache[key] = chunks
                    return chunks

                def write_one_blob(payload: bytes) -> str:
                    key = f"/load/o{s3_seq[0]:06d}"
                    s3_seq[0] += 1
                    _s3_req("PUT", key, payload)
                    return key

                def read_fn(key: str):
                    # the s3 gateway reads needles filer-side, so the
                    # read-class header does not reach this client:
                    # classification stays the static chunk-fid class
                    body, _headers = _s3_req("GET", key)
                    return body, None
            else:

                def fids_of(key: str) -> list[str]:
                    return [key]

                def write_one_blob(payload: bytes) -> str:
                    a = client.assign(replication="001")
                    client.upload(a.fid, payload)
                    return a.fid

                def read_fn(key: str):
                    return client.read_ex(key)

            # -- preload batch 1: the objects that will live on the EC'd
            # volume (written first so they share one volume) --------------
            client_blobs.clear()

            def write_some(count: int) -> None:
                for _ in range(count):
                    size = rng.randrange(500, 40_000)
                    payload = rng.getrandbits(8 * size).to_bytes(size, "little")
                    key = write_one_blob(payload)
                    client_blobs[key] = payload

            n_ec = max(10, args.objects // 2)
            write_some(n_ec)

            # -- EC the busiest volume, spread + drop shards --------------
            by_vid: dict[int, int] = {}
            for key in client_blobs:
                for fid in fids_of(key):
                    vid = int(fid.split(",", 1)[0])
                    by_vid[vid] = by_vid.get(vid, 0) + 1
            ec_vid = max(by_vid, key=lambda v: by_vid[v])
            dropped = set(args.dropped_shards)
            # s3-front objects live in their bucket's collection, which
            # prefixes the on-disk base (`load_<vid>`); master-front
            # assigns land in the default (empty) collection
            ec_collection = "load" if args.front == "s3" else ""
            base = ec_encode_and_spread(
                rpc_mod, VOLUME_SERVICE, nodes, ec_vid, sorted(dropped),
                args.ec_large_block, args.ec_small_block,
                collection=ec_collection,
            )
            degraded_ids, _ = classify_needles(base, dropped)

            # -- preload batch 2: the EC'd volume left the writable set, so
            # these land on freshly-grown replicated volumes = the healthy
            # comparison class ---------------------------------------------
            write_some(args.objects - n_ec)

            def klass_of(key: str) -> str:
                best = "healthy"
                for fid in fids_of(key):
                    f = FileId.parse(fid)
                    if f.volume_id != ec_vid:
                        continue
                    if f.key in degraded_ids:
                        return "degraded"
                    best = "ec_intact"
                return best

            by_klass = {"healthy": 0, "degraded": 0, "ec_intact": 0}
            for key in client_blobs:
                by_klass[klass_of(key)] += 1

            # -- warmup: one unrecorded pass over the EC volume's needles
            # so the steady phase measures steady state, not the first
            # read's decode-matrix build + XLA bucket compilation. This
            # also populates the decoded-interval cache: the measured
            # phases then serve the hot set from it until each entry's
            # TTL epoch lapses and a real decode refreshes it -------------
            for key in client_blobs:
                if klass_of(key) != "healthy":
                    try:
                        read_fn(key)
                    except Exception:  # noqa: BLE001 — warmup best-effort
                        pass

            # -- open-loop load -------------------------------------------
            keys = sorted(client_blobs)
            rng.shuffle(keys)
            cdf = zipf_cdf(len(keys), args.zipf)
            if args.chaos:
                phases = [("steady", args.seconds / 2), ("chaos", args.seconds / 2)]
            else:
                phases = [("steady", args.seconds)]

            scraper = CounterScraper()
            tracer = TraceScraper()

            put_rng = random.Random(args.seed + 3)
            put_lock = threading.Lock()
            puts_done = [0]

            def put_one(sched: float, phase: str) -> None:
                """One open-loop PUT: assign + upload over the master front.
                New blobs join client_blobs so the final zero-loss pass
                verifies them; a read-only race (a volume sealing under
                the writer) retries once with a fresh assign before it
                counts as an error — exactly what a real client does.
                Payload construction stays OUTSIDE the lock (os.urandom,
                not the shared RNG): latency is measured from scheduled
                time, so serialized generation would read as server tail."""
                with put_lock:
                    size = put_rng.randrange(500, 40_000)
                payload = os.urandom(size)
                for _ in range(2):
                    try:
                        a = client.assign(replication="001")
                        client.upload(a.fid, payload)
                        client_blobs[a.fid] = payload
                        with put_lock:
                            puts_done[0] += 1
                        rec.observe(phase, "put", time.monotonic() - sched)
                        return
                    except Exception:  # noqa: BLE001 — re-assign once
                        continue
                rec.error(phase, "put")

            storm_threads: list[threading.Thread] = []
            if args.rebuild_storm:
                # concurrent remote rebuilds of the dropped shards at the
                # two non-owner holders, launched INTO the steady phase:
                # their survivor slab pulls ride the token-gated rebuild
                # lane while foreground reads keep flowing (the rebuilt
                # files stay unmounted, so the degraded classification is
                # untouched; launching them under kills would just race
                # the sole holder of the unspread shards)
                chaos_report["rebuilds"] = []

                def one_rebuild(node) -> None:
                    try:
                        with rpc_mod.RpcClient(f"127.0.0.1:{node.grpc}") as c:
                            # trace auto: projections when every holder
                            # speaks them, full slabs otherwise — the storm
                            # now also measures the repair-bandwidth path
                            # under load, and records which mode served
                            resp = c.call(
                                VOLUME_SERVICE, "VolumeEcShardsRebuild",
                                {
                                    "volume_id": ec_vid,
                                    "remote": True,
                                    "trace_mode": "auto",
                                },
                                timeout=240,
                            )
                            # the storm measures the rebuild LANE, not the
                            # repair result: scrub the rebuilt files so a
                            # later chaos restart cannot rescan them into
                            # service and quietly un-degrade the volume
                            c.call(
                                VOLUME_SERVICE, "VolumeEcShardsDelete",
                                {
                                    "volume_id": ec_vid,
                                    "shard_ids": resp.get("rebuilt_shard_ids", []),
                                },
                            )
                        chaos_report["rebuilds"].append({
                            "target": node.i,
                            "rebuilt": resp.get("rebuilt_shard_ids", []),
                            "mode": resp.get("mode"),
                            "wire_bytes": resp.get("wire_bytes"),
                            "trace_fallback": resp.get("trace_fallback") or None,
                        })
                    except Exception as e:  # noqa: BLE001 — recorded, not fatal
                        chaos_report["rebuilds"].append(
                            {"target": node.i, "error": str(e)[:160]}
                        )

                for n in nodes:
                    if not base.startswith(n.dir):
                        t = threading.Thread(
                            target=one_rebuild, args=(n,), daemon=True
                        )
                        t.start()
                        storm_threads.append(t)

            corrupt_stop = threading.Event()
            corrupt_thread = None
            corruption_report = None
            if args.corrupt:
                from seaweedfs_tpu.ec import stripe as stripe_mod

                # injection/healed primitives are SHARED with chaos_soak
                # so the two harnesses cannot drift on their semantics
                from chaos_soak import (
                    ec_shard_clean,
                    ec_shard_path,
                    inject_shard_fault,
                )

                eci = stripe_mod.read_ec_info(base)
                assert eci and eci.get("shard_crc32"), "corrupt mode needs .eci CRCs"
                golden_crcs = eci["shard_crc32"]
                corruption_report = {"injected": [], "all_healed": False}

                def shard_path(node, s: int) -> str:
                    return ec_shard_path(node.dir, ec_vid, s)

                def shard_clean(node, s: int) -> bool:
                    return ec_shard_clean(node.dir, ec_vid, s, golden_crcs)

                def corrupt_fn() -> None:
                    """One corruption at a time, cycling bit-flip ->
                    truncate -> delete across live holders' shard files,
                    each verified SELF-HEALED (bytes match the .eci
                    record again) before the next lands — so the stripe
                    never carries two concurrent injections and every
                    entry gets an exact healed-or-not verdict."""
                    crng = random.Random(args.seed + 9)
                    kinds = ("bitflip", "truncate", "delete")
                    k = 0
                    while not corrupt_stop.is_set():
                        cands = [
                            (n, s)
                            for n in nodes
                            for s in range(2, 10)
                            if n.alive and not n.wedged
                            and os.path.exists(shard_path(n, s))
                        ]
                        if not cands:
                            corrupt_stop.wait(1.0)
                            continue
                        node, s = crng.choice(cands)
                        kind = kinds[k % len(kinds)]
                        k += 1
                        if not inject_shard_fault(shard_path(node, s), kind, crng):
                            continue  # racing repair/kill: pick again
                        ent = {"node": node.i, "shard": s, "kind": kind}
                        corruption_report["injected"].append(ent)
                        t0 = time.monotonic()
                        deadline = t0 + 60
                        while (
                            time.monotonic() < deadline
                            and not corrupt_stop.is_set()
                            and not shard_clean(node, s)
                        ):
                            corrupt_stop.wait(0.5)
                        ent["healed"] = shard_clean(node, s)
                        ent["healed_after_s"] = (
                            round(time.monotonic() - t0, 2) if ent["healed"] else None
                        )
                        corrupt_stop.wait(2.0)

                corrupt_thread = threading.Thread(target=corrupt_fn, daemon=True)
                corrupt_thread.start()

            def chaos_fn(stop: threading.Event) -> None:
                crng = random.Random(args.seed + 2)
                while not stop.is_set():
                    victims = [n for n in nodes if n.alive and not n.wedged]
                    if len(victims) > 1:
                        victim = crng.choice(victims)
                        # both failure modes must actually land in every
                        # chaos window (a short window + an unlucky rng
                        # would otherwise produce a kills-only or
                        # wedges-only artifact): first a wedge, then a
                        # kill, then the 60/40 mix
                        if chaos_report["wedges"] == 0 or (
                            chaos_report["kills"] > 0 and crng.random() < 0.6
                        ):
                            victim.wedge()
                            chaos_report["wedges"] += 1
                            stop.wait(args.wedge_seconds)
                            victim.unwedge()
                        else:
                            # harvest the dying generation's counters +
                            # trace ring first (both die with the process)
                            scraper.scrape(victim.http)
                            tracer.scrape(victim.http)
                            victim.kill(hard=True)
                            chaos_report["kills"] += 1
                            stop.wait(3.0)
                            victim.start()
                            stop.wait(2.0)
                    stop.wait(crng.uniform(1.0, 3.0))

            # -- repair-revert guard: with WEEDTPU_REPAIR=on the fleet
            # scheduler sees the DELIBERATELY dropped shards as damage and
            # rebuilds them, silently un-degrading the measured class. The
            # guard watches every holder and re-drops them the moment they
            # come back, keeping score — the scheduler staying busy is part
            # of the chaos, the degraded class surviving it is the point.
            guard_stop = threading.Event()
            guard_thread = None
            if args.chaos:

                def repair_guard() -> None:
                    while not guard_stop.is_set():
                        for n in nodes:
                            if not n.alive or n.wedged:
                                continue
                            try:
                                with rpc_mod.RpcClient(
                                    f"127.0.0.1:{n.grpc}"
                                ) as c:
                                    st = c.call(
                                        VOLUME_SERVICE, "VolumeStatus",
                                        {"volume_id": ec_vid}, timeout=5,
                                    )
                                    back = sorted(
                                        set(st.get("shard_ids", ())) & dropped
                                    )
                                    if back:
                                        c.call(
                                            VOLUME_SERVICE,
                                            "VolumeEcShardsDelete",
                                            {
                                                "volume_id": ec_vid,
                                                "collection": ec_collection,
                                                "shard_ids": back,
                                            },
                                            timeout=10,
                                        )
                                        chaos_report["repairs_reverted"] += len(
                                            back
                                        )
                            except Exception:  # noqa: BLE001 — racing a kill
                                continue
                        guard_stop.wait(2.0)

                guard_thread = threading.Thread(target=repair_guard, daemon=True)
                guard_thread.start()

            if args.procs > 1:
                # -- multi-process generators: spec out, spawn, drive chaos
                # on the shared absolute clock, merge recorders ------------
                spec = {
                    "master": master.address,
                    "client_timeout": args.client_timeout,
                    "rps": args.rps / args.procs,
                    "zipf": args.zipf,
                    "concurrency": max(16, args.concurrency // args.procs),
                    "seed": args.seed,
                    "phases": [[name, secs] for name, secs in phases],
                    # absolute start instant: late enough for every worker
                    # to finish interpreter startup + imports, shared so
                    # worker phase boundaries align with the driver's
                    # chaos window
                    "start_at": time.time() + max(6.0, 1.5 * args.procs),
                    "fids": {
                        fid: {
                            "klass": klass_of(fid),
                            "sha256": hashlib.sha256(data).hexdigest(),
                        }
                        for fid, data in client_blobs.items()
                    },
                }
                spec_path = os.path.join(td, "genspec.json")
                with open(spec_path, "w", encoding="utf-8") as f:
                    json.dump(spec, f)
                wenv = {**os.environ, "JAX_PLATFORMS": "cpu"}
                wenv["PYTHONPATH"] = (
                    REPO + os.pathsep + wenv.get("PYTHONPATH", "")
                ).rstrip(os.pathsep)
                workers = []
                for i in range(args.procs):
                    out_i = os.path.join(td, f"gen{i}.json")
                    log_i = open(  # weedlint: ignore[open-no-ctx]
                        os.path.join(td, f"gen{i}.log"), "ab"
                    )
                    proc = subprocess.Popen(
                        [
                            sys.executable, os.path.abspath(__file__),
                            "--gen-worker", spec_path,
                            "--worker-out", out_i,
                            "--worker-index", str(i),
                        ],
                        env=wenv, stdout=log_i, stderr=log_i,
                    )
                    workers.append((proc, out_i, log_i))

                # the driver mirrors the workers' phase clock and owns
                # chaos: kills/wedges land inside the chaos window every
                # worker is measuring
                delay = spec["start_at"] - time.time()
                if delay > 0:
                    time.sleep(delay)
                for phase, seconds in phases:
                    stop_chaos = threading.Event()
                    chaos_thread = None
                    if args.chaos and phase == "chaos":
                        chaos_thread = threading.Thread(
                            target=chaos_fn, args=(stop_chaos,), daemon=True
                        )
                        chaos_thread.start()
                    time.sleep(seconds)
                    stop_chaos.set()
                    if chaos_thread is not None:
                        chaos_thread.join(timeout=args.wedge_seconds + 10)

                issued = 0
                drain_deadline = time.time() + 120
                for proc, out_i, log_i in workers:
                    try:
                        rc_w = proc.wait(
                            timeout=max(5.0, drain_deadline - time.time())
                        )
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        rc_w = -9
                    log_i.close()
                    if rc_w != 0 or not os.path.exists(out_i):
                        # a dead generator invalidates the run as loudly as
                        # a lost byte — its samples are simply gone
                        lost.append({
                            "fid": None,
                            "why": f"generator worker exited rc={rc_w}",
                        })
                        continue
                    with open(out_i, encoding="utf-8") as f:
                        wout = json.load(f)
                    issued += wout["issued"]
                    rec.merge_dict(wout["cells"])
                    lost.extend(wout["lost"])
            else:
                issued = run_load(
                    args, read_fn, rec, lost, keys, cdf, klass_of, phases,
                    chaos_fn=chaos_fn if args.chaos else None,
                    put_fn=put_one if args.put_fraction > 0 else None,
                )
            guard_stop.set()
            if guard_thread is not None:
                guard_thread.join(timeout=10)
            for t in storm_threads:
                t.join(timeout=10)
            if corrupt_thread is not None:
                corrupt_stop.set()
                corrupt_thread.join(timeout=70)

            # -- heal + final zero-loss verification ----------------------
            for n in nodes:
                if not args.smoke:
                    n.unwedge()
                    if not n.alive:
                        n.start()
            if args.chaos:
                time.sleep(6.0)
            for key, want in client_blobs.items():
                got = None
                for _ in range(12):
                    try:
                        got = read_fn(key)[0]
                        break
                    except Exception:  # noqa: BLE001 — post-chaos settle
                        time.sleep(1.0)
                if got is None:
                    lost.append({"fid": key, "why": "unreadable at end"})
                elif got != want:
                    lost.append({"fid": key, "why": "BYTES DIFFER"})

            if corruption_report is not None:
                # final heal verdict: every injected corruption must have
                # been detected + auto-repaired — shard bytes match the
                # .eci record again everywhere an injection landed (give
                # stragglers whose repair raced the run end one last wait)
                deadline = time.monotonic() + 60
                def _unhealed():
                    return [
                        e for e in corruption_report["injected"]
                        if not shard_clean(nodes[e["node"]], e["shard"])
                    ]
                while time.monotonic() < deadline and _unhealed():
                    time.sleep(1.0)
                for e in corruption_report["injected"]:
                    if not e.get("healed") and shard_clean(
                        nodes[e["node"]], e["shard"]
                    ):
                        e["healed"] = True
                corruption_report["all_healed"] = not _unhealed()
                corruption_report["count"] = len(corruption_report["injected"])

            # -- tracing-overhead gate (smoke): leave-it-on is a design
            # claim, so the smoke MEASURES it — interleaved trace-on vs
            # trace-off healthy reads on the same live cluster ------------
            if args.smoke and args.front == "master":
                healthy_fids = [
                    f for f in client_blobs if klass_of(f) == "healthy"
                ]
                trace_overhead = measure_trace_overhead(client, healthy_fids)

            # in-process smoke nodes SHARE the module-global stats
            # registry — scraping all three would triple-count; one node's
            # /metrics already holds the whole process's counters
            for n in (nodes[:1] if args.smoke else nodes):
                scraper.scrape(n.http)
            if not args.smoke:
                # the in-process master's registry carries the fleet
                # repair scheduler counters (weedtpu_repair_*); smoke
                # runs share ONE process registry already scraped above
                scraper.scrape(master.http_port)
            for n in (nodes[:1] if args.smoke else nodes):
                tracer.scrape(n.http)
            counters = scraper.totals
        finally:
            if filer_client is not None:
                try:
                    filer_client.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            if s3_srv is not None:
                try:
                    s3_srv.stop()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            if filer_srv is not None:
                try:
                    filer_srv.stop()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            if client is not None:
                client.close()
            for n in nodes:
                try:
                    if args.smoke:
                        n.stop()
                    else:
                        n.unwedge()
                        n.kill(hard=False)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            master.stop()

    report = slo.assemble_report(
        rec,
        workload={
            "open_loop": True,
            "arrivals": "poisson",
            "rps": args.rps,
            "seconds": args.seconds,
            "issued": issued,
            "zipf_s": args.zipf,
            "objects": args.objects,
            "objects_by_class": by_klass,
            "dropped_shards": sorted(dropped),
            "ec_volume": ec_vid,
            "concurrency": args.concurrency,
            "procs": args.procs,
            "front": "s3" if args.front == "s3" else "master-http",
            "servers": "in-process" if args.smoke else "subprocess",
            "put_fraction": args.put_fraction,
            "puts_acked": puts_done[0],
        },
        chaos=chaos_report,
        knobs={
            name: config.env(name)
            for name in (
                "WEEDTPU_HEDGE_READS", "WEEDTPU_HEDGE_DELAY_MS",
                "WEEDTPU_COALESCE_READS", "WEEDTPU_REBUILD_MAX_INFLIGHT",
                "WEEDTPU_REBUILD_YIELD_MS", "WEEDTPU_LOOKUP_RETRIES",
                "WEEDTPU_INLINE_EC", "WEEDTPU_INLINE_EC_SEAL_BYTES",
                "WEEDTPU_INLINE_EC_DELTA",
                "WEEDTPU_READ_CACHE_MB", "WEEDTPU_READ_CACHE_TTL_S",
                "WEEDTPU_REPAIR",
            )
        },
        counters=counters,
        lost=lost,
        slo_factor=args.slo_factor,
        corruption=corruption_report,
        classes=("healthy", "ec_intact", "cached", "degraded", "put")
        if args.put_fraction > 0
        else ("healthy", "ec_intact", "cached", "degraded"),
    )
    # hot-set serving evidence: the decoded-interval cache's server-side
    # counters next to the client-observed per-class quantiles. `degraded`
    # now means READS THAT ACTUALLY DECODED (the read-class header routes
    # cache hits into `cached`), so cached-vs-decoded is a true A/B over
    # the same keys under the same load.
    cached_s = rec.merged("cached").summary()
    decoded_s = rec.merged("degraded").summary()
    cache_hits = counters.get("weedtpu_read_cache_hits_total", 0.0)
    cache_misses = counters.get("weedtpu_read_cache_misses_total", 0.0)
    report["cache"] = {
        "budget_mb": config.env("WEEDTPU_READ_CACHE_MB"),
        "ttl_s": config.env("WEEDTPU_READ_CACHE_TTL_S"),
        "hits": int(cache_hits),
        "misses": int(cache_misses),
        "hit_rate": (
            round(cache_hits / (cache_hits + cache_misses), 4)
            if cache_hits + cache_misses
            else None
        ),
        "evictions": int(counters.get("weedtpu_read_cache_evictions_total", 0.0)),
        "invalidations": int(
            counters.get("weedtpu_read_cache_invalidations_total", 0.0)
        ),
        "cached": cached_s,
        "decoded": decoded_s,
        "cached_below_decoded_p99": (
            bool(cached_s["p99"] < decoded_s["p99"])
            if cached_s["count"] and decoded_s["count"]
            else None
        ),
    }
    # tail attribution: which STAGE owns each class's latency. Embedded
    # in the SLO report (summary + slowest exemplars) and committed as
    # its own TRACE_ATTRIB_r* artifact.
    attrib = slo.assemble_trace_attribution(
        list(tracer.traces.values()),
        classes=("healthy", "ec_intact", "cached", "degraded", "put"),
    )
    attrib["workload"] = report["workload"]
    attrib["chaos"] = report["chaos"]
    report["trace_attribution"] = attrib
    if trace_overhead is not None:
        report["trace_overhead"] = trace_overhead
    slo.write_trace_attribution(args.trace_out, attrib)
    slo.write_report(args.out, report)
    print(json.dumps(report, indent=1))
    if report["lost"]:
        return 1
    if args.corrupt and not report["corruption"]["all_healed"]:
        return 1  # an unhealed injection is as disqualifying as a lost byte
    if args.smoke and args.front == "master" and report["cache"]["hits"] < 1:
        # the cache-hit-rate gate: a hot zipf set over a warmed cache that
        # never hits means the decoded-interval cache is broken or off —
        # the smoke exists to catch exactly that before a real run does
        print(
            "SMOKE GATE FAILED: decoded-interval cache never hit "
            f"(hits={report['cache']['hits']} misses={report['cache']['misses']})",
            file=sys.stderr,
        )
        return 1
    if args.require_slo and not report["slo"]["ok"]:
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
