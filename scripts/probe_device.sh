#!/bin/sh
# Probe the axon TPU tunnel in a throwaway child (90s cap) and append the
# result to PROBES_r05.jsonl. Kill-safe: the child only calls
# jax.devices() (init phase), never a dispatch.
cd /root/repo
python - <<'PY'
import json, subprocess, time, datetime
t0 = time.time()
try:
    r = subprocess.run(
        ["python", "-c", "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=90,
    )
    plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    ok = r.returncode == 0 and plat in ("tpu", "axon")
    err = "" if ok else (r.stderr[-200:] or r.stdout[-200:])
except subprocess.TimeoutExpired:
    ok, err = False, "timeout after 90s"
rec = {"when": "round-6-loop", "ts": datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%MZ"),
       "method": "subprocess jax.devices(), 90s cap", "ok": ok, "dt_s": round(time.time()-t0, 1)}
if err: rec["error"] = err
with open("PROBES_r06.jsonl", "a") as f:
    f.write(json.dumps(rec) + "\n")
print("probe ok" if ok else "probe fail")
PY
