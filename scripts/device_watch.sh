#!/bin/bash
# Detached tunnel watcher: probe the axon TPU every 10 min; on the first
# healthy probe run the full window worker (scripts/device_window.py:
# fresh measurement + kernel sweep + e2e encode). Exits after one
# successful window or when the deadline passes. Never SIGTERMs a device
# run mid-flight (that wedges the tunnel): the worker self-budgets.
cd /root/repo
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-6} * 3600 ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if bash scripts/probe_device.sh | grep -q "probe ok"; then
    echo "$(date -u +%FT%TZ) tunnel alive — running device window" >> artifacts/device_watch.log
    PYTHONPATH=/root/repo:/root/.axon_site python scripts/device_window.py >> artifacts/device_watch.log 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) window rc=$rc" >> artifacts/device_watch.log
    # only a COMPLETED window ends the watch: a failed/aborted attempt
    # must not burn the remaining deadline (the next probe retries)
    [ "$rc" -eq 0 ] && exit 0
  fi
  sleep 600
done
echo "$(date -u +%FT%TZ) deadline passed, no tunnel window" >> artifacts/device_watch.log
