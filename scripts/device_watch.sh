#!/bin/bash
# Detached tunnel watcher: probe the axon TPU every 10 min; on the FIRST
# healthy probe, immediately fire the incremental kernel sweep
# (kernel_sweep.py --out artifacts/SWEEP_r06.jsonl — one JSON line
# persists per config AS IT LANDS, so even a window that dies mid-sweep
# leaves committed evidence), then run the full window worker
# (scripts/device_window.py: fresh scan-chain measurement + resumed
# sweep + e2e encode + remote rebuild + assembly of the committed
# DEVICE_MEASUREMENT_r06.json the auto backend reads). Exits after one
# successful window or when the deadline passes. NEVER SIGTERMs a device
# run mid-flight (the r4 lesson: that wedges the tunnel machine-wide) —
# both children self-budget and the sweep is resumable, so an aborted
# attempt costs nothing on the next probe.
cd /root/repo
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-6} * 3600 ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if bash scripts/probe_device.sh | grep -q "probe ok"; then
    echo "$(date -u +%FT%TZ) tunnel alive — firing incremental sweep" >> artifacts/device_watch.log
    # sweep FIRST: evidence starts persisting within the first alive
    # minute; a later wedge cannot take what already landed. Resumable:
    # a re-fire skips configs already in the harvest file.
    PYTHONPATH=/root/repo:/root/.axon_site python scripts/kernel_sweep.py \
      --out artifacts/SWEEP_r06.jsonl >> artifacts/device_watch.log 2>&1
    sweep_rc=$?
    echo "$(date -u +%FT%TZ) sweep rc=$sweep_rc — assembling evidence" >> artifacts/device_watch.log
    # fold whatever landed into the committed measurement artifact even
    # before the window worker runs (new_encoder("auto") reads it)
    PYTHONPATH=/root/repo:/root/.axon_site python scripts/device_window.py \
      --assemble >> artifacts/device_watch.log 2>&1
    echo "$(date -u +%FT%TZ) running device window" >> artifacts/device_watch.log
    PYTHONPATH=/root/repo:/root/.axon_site python scripts/device_window.py >> artifacts/device_watch.log 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) window rc=$rc" >> artifacts/device_watch.log
    # only a COMPLETED window ends the watch: a failed/aborted attempt
    # must not burn the remaining deadline (the next probe retries; the
    # sweep resumes where the harvest file left off)
    [ "$rc" -eq 0 ] && exit 0
  fi
  sleep 600
done
echo "$(date -u +%FT%TZ) deadline passed, no tunnel window" >> artifacts/device_watch.log
