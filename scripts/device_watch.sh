#!/bin/bash
# Detached tunnel watcher: probe the axon TPU every 10 min; on the first
# healthy probe run the kernel sweep (scripts/kernel_sweep.py) and a fresh
# device bench stage, logging everything to artifacts/. Exits after one
# successful sweep or when the deadline passes. Never SIGTERMs a device
# run mid-flight (that wedges the tunnel): the sweep runs unbounded.
cd /root/repo
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-6} * 3600 ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if bash scripts/probe_device.sh | grep -q "probe ok"; then
    echo "$(date -u +%FT%TZ) tunnel alive — running kernel sweep" >> artifacts/device_watch.log
    python scripts/kernel_sweep.py > artifacts/SWEEP_r04.jsonl 2>artifacts/SWEEP_r04.err
    echo "$(date -u +%FT%TZ) sweep rc=$? — running device bench" >> artifacts/device_watch.log
    BENCH_MODE=device BENCH_TRACE_DIR="" python bench.py > artifacts/DEVICE_BENCH_late_r04.json 2>/dev/null
    echo "$(date -u +%FT%TZ) device bench rc=$?" >> artifacts/device_watch.log
    exit 0
  fi
  sleep 600
done
echo "$(date -u +%FT%TZ) deadline passed, no tunnel window" >> artifacts/device_watch.log
