"""Device-side kernel sweep: hunt for encode AND rebuild throughput past
the current 31 GB/s steady-state (target: BASELINE.json 40 GB/s/chip, 10+4).

Variants swept (all byte-exact vs gf8 golden):
  xla              rs_jax.gf_apply (current per-call winner)
  pallas-T         rs_pallas fused kernel at tile T in {8k, 16k, 32k, 64k}
  pallas-auto      the retuned default: auto_tile picks the largest tile
                   whose VMEM working set fits the budget
  pallas-bf16-T    same kernel but the MXU matmul runs in bf16 (products are
                   0/1 and K=80 so every partial sum <= 80 < 256 is exactly
                   representable in bf16's 8-bit mantissa; f32 accumulate is
                   exact a fortiori) — int8 matmul on some TPU generations is
                   emulated at half/quarter bf16 rate, so this can win.
  rebuild-*        the same kernels driven by a fused survivors->missing
                   decode matrix (worst allowed loss: 2 data + 2 parity) —
                   the shape the pipelined rebuild_ec_files dispatches.

Method: scan-chain slope (same as bench.py stage 3) — time K=1 vs K=8
chains in one dispatch; the slope is per-apply device time, immune to the
~65 ms axon-tunnel dispatch floor.

Usage: python scripts/kernel_sweep.py [--quick|--tiny|--smoke]
  --quick  fewer tiles
  --tiny   CPU sanity run: toy sizes, correctness + timing
  --smoke  CI gate: JAX_PLATFORMS=cpu forced, toy sizes, correctness ONLY
           (no scan-chain timing), exits nonzero if ANY variant fails its
           byte-exactness gate — wired into tests so kernel refactors
           cannot silently break the sweep.
Emits one JSON line per variant + a summary line; outside --smoke it exits
nonzero only on harness failure (a variant that fails to compile is
recorded, not fatal).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, ".")

SMOKE = "--smoke" in sys.argv
if SMOKE:
    # the gate must never touch (or hang on) the one-client TPU tunnel —
    # pin cpu BEFORE jax resolves a backend
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from seaweedfs_tpu.ops import gf8, rs_jax, rs_pallas  # noqa: E402

if SMOKE or "--tiny" in sys.argv:  # CPU sanity runs: toy sizes
    B, N = 2, 32768
else:
    B, N = 8, 4 << 20  # same workload as bench.py stage 3
DATA_BYTES = B * 10 * N


def _median_time(fn, iters=3, warmup=1):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def steady_gbps(encode_fn, data, out_rows):
    from seaweedfs_tpu.ops.measure import scan_chain_gbps

    return scan_chain_gbps(encode_fn, data, DATA_BYTES, out_rows=out_rows)


def main():
    quick = "--quick" in sys.argv
    # JAX_PLATFORMS=cpu must win over the axon sitecustomize (a cpu sanity
    # run must never touch — or hang on — the one-client TPU tunnel)
    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    print(json.dumps({"platform": jax.devices()[0].platform, "smoke": SMOKE}), flush=True)

    pm = gf8.parity_matrix(10, 4)
    b_bits = rs_jax.lifted_matrix(pm)

    key = jax.random.PRNGKey(0)
    data = jax.block_until_ready(
        jax.random.randint(key, (B, 10, N), 0, 256, dtype=jnp.uint8)
    )

    # rebuild shape (the second north-star target): ONE fused decode
    # matrix for the worst allowed loss — 2 data + 2 parity shards gone —
    # applied to the (B, 10, N) survivor stack exactly as the pipelined
    # rebuild_ec_files dispatches it. Same kernels, different matrix.
    from seaweedfs_tpu.ops.rs_codec import _reconstruction_matrix  # noqa: E402

    lost = (0, 5, 11, 13)
    surv = tuple(s for s in range(14) if s not in lost)[:10]
    dm = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    dm_bits = rs_jax.lifted_matrix(dm)

    # golden check inputs (small) — verify each variant is byte-exact
    # against its OWN gf8 matrix product (encode variants vs the parity
    # matrix, rebuild variants vs the decode matrix)
    small = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (1, 10, 8192), 0, 256, dtype=jnp.uint8)
    )

    def fused(bits, tile, mxu="int8"):
        # _apply_pm clamps explicit tiles to the (padded) input width, so
        # tiles larger than the 8192-wide golden input are safe to pass
        # through; tile=None lets auto_tile pick.
        return lambda d: rs_pallas.gf_apply_fused(bits, d, tile=tile, mxu=mxu)

    variants = [
        ("xla", lambda d: rs_jax.gf_apply(b_bits, d), pm),
        ("rebuild-xla", lambda d: rs_jax.gf_apply(dm_bits, d), dm),
        ("pallas-auto", fused(b_bits, None), pm),
        ("pallas-bf16-auto", fused(b_bits, None, "bf16"), pm),
        ("rebuild-pallas-auto", fused(dm_bits, None), dm),
    ]
    if SMOKE:
        tiles = [8192]  # one explicit tile proves the tiled path; cheap
    elif quick:
        tiles = [8192, 16384]
    else:
        tiles = [8192, 16384, 32768, 65536]
    for t in tiles:
        variants.append((f"pallas-{t}", fused(b_bits, t), pm))
        variants.append((f"pallas-bf16-{t}", fused(b_bits, t, "bf16"), pm))
        variants.append((f"rebuild-pallas-{t}", fused(dm_bits, t), dm))

    results = {}
    failed = []
    for name, fn, gm in variants:
        rec = {"variant": name}
        try:
            golden = gf8.gf_mat_mul(gm, small[0])
            got = np.asarray(fn(jnp.asarray(small))[0, : golden.shape[0]])
            exact = bool((got == golden).all())
            rec["exact"] = exact
            if not exact:
                raise ValueError("output mismatch vs gf8 golden")
            if not SMOKE:
                t = _median_time(
                    lambda: jax.block_until_ready(fn(data)), iters=5, warmup=2
                )
                rec["per_call_gbps"] = round(DATA_BYTES / t / 1e9, 3)
                rec["steady_gbps"] = round(
                    steady_gbps(fn, data, out_rows=gm.shape[0]), 3
                )
                results[name] = rec["steady_gbps"]
        except Exception as e:  # noqa: BLE001
            rec["error"] = str(e)[:300]
            failed.append(name)
        print(json.dumps(rec), flush=True)

    if SMOKE:
        print(
            json.dumps(
                {"smoke_ok": not failed, "variants": len(variants), "failed": failed}
            ),
            flush=True,
        )
        return 1 if failed else 0
    if results:
        best = max(results, key=results.get)
        print(json.dumps({"best": best, "steady_gbps": results[best]}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
