"""Device-side kernel sweep: hunt for encode throughput past the current
31 GB/s steady-state (target: BASELINE.json 40 GB/s/chip, 10+4).

Variants swept (all byte-exact vs gf8 golden):
  xla            rs_jax.gf_apply (current per-call winner)
  pallas-T       rs_pallas fused kernel at tile T in {8k, 16k, 32k, 64k}
  pallas-bf16-T  same kernel but the MXU matmul runs in bf16 (products are
                 0/1 and K=80 so every partial sum <= 80 < 256 is exactly
                 representable in bf16's 8-bit mantissa; f32 accumulate is
                 exact a fortiori) — int8 matmul on some TPU generations is
                 emulated at half/quarter bf16 rate, so this can win.

Method: scan-chain slope (same as bench.py stage 3) — time K=1 vs K=8
encode chains in one dispatch; the slope is per-encode device time, immune
to the ~65 ms axon-tunnel dispatch floor.

Usage: python scripts/kernel_sweep.py [--quick]
Emits one JSON line per variant + a summary line; exits nonzero only on
harness failure (a variant that fails to compile is recorded, not fatal).
"""

from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

sys.path.insert(0, ".")

from seaweedfs_tpu.ops import gf8, rs_jax, rs_pallas  # noqa: E402

if "--tiny" in sys.argv:  # CPU sanity run: correctness only, toy sizes
    B, N = 2, 32768
else:
    B, N = 8, 4 << 20  # same workload as bench.py stage 3
DATA_BYTES = B * 10 * N


def _median_time(fn, iters=3, warmup=1):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def steady_gbps(encode_fn, data):
    from seaweedfs_tpu.ops.measure import scan_chain_gbps

    return scan_chain_gbps(encode_fn, data, DATA_BYTES)


# --- bf16 variant of the fused kernel -------------------------------------


def _kernel_bf16(b_ref, data_ref, out_ref):
    # r5 layout: plane-major on BOTH sides (matches rs_pallas._kernel and
    # the doubly-permuted matrix from plane_major_matrix) + uint8-native
    # unpack — only the MXU dtype differs from the int8 kernel
    data = data_ref[0]
    bits = jnp.concatenate(
        [((data >> j) & 1) for j in range(8)], axis=0
    ).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        b_ref[...].astype(jnp.bfloat16),
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    acc = acc & 1
    rows8, t = acc.shape
    acc3 = acc.reshape(8, rows8 // 8, t)
    out = acc3[0]
    for i in range(1, 8):
        out = out | (acc3[i] << i)
    out_ref[0] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tile",))
def _apply_bf16(b_pm, data, tile: int):
    batch, c, n = data.shape
    rows = b_pm.shape[0] // 8
    interpret = jax.devices()[0].platform == "cpu"  # --tiny exactness runs
    return pl.pallas_call(
        _kernel_bf16,
        grid=(batch, n // tile),
        in_specs=[
            pl.BlockSpec((b_pm.shape[0], b_pm.shape[1]), lambda b, i: (0, 0)),
            pl.BlockSpec((1, c, tile), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, rows, tile), lambda b, i: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((batch, rows, n), jnp.uint8),
        interpret=interpret,
    )(b_pm, data)


def main():
    quick = "--quick" in sys.argv
    # JAX_PLATFORMS=cpu must win over the axon sitecustomize (a cpu sanity
    # run must never touch — or hang on — the one-client TPU tunnel)
    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    print(json.dumps({"platform": jax.devices()[0].platform}), flush=True)

    pm = gf8.parity_matrix(10, 4)
    b_bits = rs_jax.lifted_matrix(pm)
    b_pm = rs_pallas.plane_major_matrix(pm)

    key = jax.random.PRNGKey(0)
    data = jax.block_until_ready(
        jax.random.randint(key, (B, 10, N), 0, 256, dtype=jnp.uint8)
    )

    # rebuild shape (the second north-star target): ONE fused decode
    # matrix for the worst allowed loss — 2 data + 2 parity shards gone —
    # applied to the (B, 10, N) survivor stack exactly as the pipelined
    # rebuild_ec_files dispatches it. Same kernels, different matrix.
    from seaweedfs_tpu.ops.rs_codec import _reconstruction_matrix  # noqa: E402

    lost = (0, 5, 11, 13)
    surv = tuple(s for s in range(14) if s not in lost)[:10]
    dm = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    dm_bits = rs_jax.lifted_matrix(dm)

    # golden check inputs (small) — verify each variant is byte-exact
    # against its OWN gf8 matrix product (encode variants vs the parity
    # matrix, rebuild variants vs the decode matrix)
    small = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (1, 10, 8192), 0, 256, dtype=jnp.uint8)
    )

    variants = [
        ("xla", lambda d: rs_jax.gf_apply(b_bits, d), pm),
        ("rebuild-xla", lambda d: rs_jax.gf_apply(dm_bits, d), dm),
    ]
    tiles = [8192, 16384] if quick else [8192, 16384, 32768, 65536]
    for t in tiles:
        variants.append(
            (f"pallas-{t}", functools.partial(
                lambda d, tt: rs_pallas.gf_apply_fused(b_bits, d, tile=tt), tt=t), pm)
        )
        variants.append(
            # clamp the tile to the input: the golden gate feeds n=8192,
            # and grid=(batch, n // tile) with tile > n would be an empty
            # grid — all-zero output, every large-tile variant failing the
            # gate before it was ever measured
            (f"pallas-bf16-{t}", functools.partial(
                lambda d, tt: _apply_bf16(b_pm, d, min(tt, d.shape[2])), tt=t), pm)
        )
        variants.append(
            (f"rebuild-pallas-{t}", functools.partial(
                lambda d, tt: rs_pallas.gf_apply_fused(dm_bits, d, tile=tt), tt=t), dm)
        )

    results = {}
    for name, fn, gm in variants:
        rec = {"variant": name}
        try:
            golden = gf8.gf_mat_mul(gm, small[0])
            got = np.asarray(fn(jnp.asarray(small))[0, : golden.shape[0]])
            exact = bool((got == golden).all())
            rec["exact"] = exact
            if not exact:
                raise ValueError("output mismatch vs gf8 golden")
            t = _median_time(lambda: jax.block_until_ready(fn(data)), iters=5, warmup=2)
            rec["per_call_gbps"] = round(DATA_BYTES / t / 1e9, 3)
            rec["steady_gbps"] = round(steady_gbps(fn, data), 3)
            results[name] = rec["steady_gbps"]
        except Exception as e:  # noqa: BLE001
            rec["error"] = str(e)[:300]
        print(json.dumps(rec), flush=True)

    if results:
        best = max(results, key=results.get)
        print(json.dumps({"best": best, "steady_gbps": results[best]}), flush=True)


if __name__ == "__main__":
    main()
