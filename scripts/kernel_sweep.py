"""Device-side kernel sweep: hunt for encode AND rebuild throughput past
the current 31 GB/s steady-state (target: BASELINE.json 40 GB/s/chip, 10+4).

Variants swept (all byte-exact vs gf8 golden; the staged r6 family —
see ops/rs_pallas.py VARIANTS):
  xla               rs_jax.gf_apply (current measured winner)
  pallas[-mxu]-T    rs_pallas fused kernel, T in {8k, 16k, 32k, 64k} or
                    `auto` (VMEM-budget tile chooser); mxu one of
                      int8    r5 baseline (shift+mask unpack, int8 MXU)
                      bf16    bf16 MXU (exact: partial sums <= 80 < 256)
                      u8      shift-free mask+compare unpack
                      mplane  per-plane K=C matmuls, one accumulator —
                              never materializes the (8C, T) bit stack
                      dma     manual double-buffered HBM->VMEM chunk ring
  rebuild-*         the same kernels driven by a fused survivors->missing
                    decode matrix (worst allowed loss: 2 data + 2 parity) —
                    the shape the pipelined rebuild_ec_files dispatches.

Method: scan-chain slope (same as bench.py stage 3) — time K=1 vs K=8
chains in one dispatch; the slope is per-apply device time, immune to the
~65 ms axon-tunnel dispatch floor.

INCREMENTAL HARVESTING (the r5 lesson: a wedged tunnel lost 100% of the
round's device time): with `--out PATH` every config's record is appended
to PATH as one JSON line THE MOMENT it lands (write+flush per record), and
a re-run against the same PATH resumes — configs already persisted are
skipped, so any >=N-minute tunnel-alive window extends the harvest instead
of restarting it. A config that crashed mid-dispatch left no record and is
retried. `--no-resume` forces a fresh sweep (PATH is truncated).
`scripts/device_window.py --assemble` folds the harvest into the committed
DEVICE_MEASUREMENT artifact.

Usage: python scripts/kernel_sweep.py [--quick|--tiny|--smoke]
                                      [--out PATH] [--no-resume]
  --quick  fewer tiles
  --tiny   CPU sanity run: toy sizes, correctness + timing
  --smoke  CI gate: JAX_PLATFORMS=cpu forced, toy sizes, correctness ONLY
           (no scan-chain timing) across EVERY variant in interpret mode,
           exits nonzero if ANY variant fails its byte-exactness gate —
           wired into tests so kernel refactors cannot silently break
           the sweep.
Emits one JSON line per variant + a summary line; outside --smoke it exits
nonzero only on harness failure (a variant that fails to compile is
recorded, not fatal).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")

SMOKE = "--smoke" in sys.argv
if SMOKE:
    # the gate must never touch (or hang on) the one-client TPU tunnel —
    # pin cpu BEFORE jax resolves a backend
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from seaweedfs_tpu.ops import gf8, rs_jax, rs_pallas  # noqa: E402

if SMOKE or "--tiny" in sys.argv:  # CPU sanity runs: toy sizes
    B, N = 2, 32768
else:
    B, N = 8, 4 << 20  # same workload as bench.py stage 3
DATA_BYTES = B * 10 * N

#: the staged kernel family, sweep order = most-promising-first so a short
#: tunnel window harvests the highest-value configs before it closes
MXUS = ("int8", "bf16", "u8", "mplane", "dma")


def _arg_value(flag: str) -> str | None:
    if flag in sys.argv:
        i = sys.argv.index(flag)
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return None


def load_done(
    path: str, platform: str | None = None, tiny: bool | None = None
) -> dict[str, dict]:
    """Variant records already persisted by a previous (interrupted) run.
    Only COMPLETE records exist in the file (each line is written after
    its config finished — success or recorded error), so presence alone
    means done; a mid-dispatch crash left no line and will be retried.

    Records from a DIFFERENT run mode never count as done: a cpu/--tiny
    sanity run landing in the harvest file must not mark configs
    harvested for the real on-chip sweep (the assembler already excludes
    such records from evidence, so skipping on them would leave the
    harvest permanently empty of usable numbers)."""
    done: dict[str, dict] = {}
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # a torn tail line from a crash mid-write
                name = rec.get("variant")
                if not name:
                    continue
                if platform is not None and rec.get("platform") != platform:
                    continue
                if tiny is not None and bool(rec.get("tiny")) != tiny:
                    continue
                done[name] = rec
    except OSError:
        pass
    return done


def open_resume_out(out_path: str, resume: bool):
    """Open the harvest file for the persist discipline. On resume, a
    crash mid-write leaves a torn tail with no newline; appending straight
    after it would glue the next record onto the fragment and corrupt
    BOTH — terminate the tail first (load_done already skips the torn
    fragment either way)."""
    out_f = open(out_path, "a" if resume else "w", encoding="utf-8")
    if resume and out_f.tell() > 0:
        with open(out_path, "rb") as chk:
            chk.seek(-1, os.SEEK_END)
            if chk.read(1) != b"\n":
                out_f.write("\n")
                out_f.flush()
    return out_f


def persist_record(out_f, rec: dict) -> None:
    """One line per config, flushed+fsynced AS IT LANDS: a tunnel wedge
    one variant later must not cost the results already measured."""
    out_f.write(json.dumps(rec) + "\n")
    out_f.flush()
    os.fsync(out_f.fileno())


def _median_time(fn, iters=3, warmup=1):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def steady_gbps(encode_fn, data, out_rows):
    from seaweedfs_tpu.ops.measure import scan_chain_gbps

    return scan_chain_gbps(encode_fn, data, DATA_BYTES, out_rows=out_rows)


def build_variants(quick: bool):
    """-> [(name, fn, gf_matrix)] in harvest-priority order."""
    pm = gf8.parity_matrix(10, 4)
    b_bits = rs_jax.lifted_matrix(pm)

    # rebuild shape (the second north-star target): ONE fused decode
    # matrix for the worst allowed loss — 2 data + 2 parity shards gone —
    # applied to the (B, 10, N) survivor stack exactly as the pipelined
    # rebuild_ec_files dispatches it. Same kernels, different matrix.
    from seaweedfs_tpu.ops.rs_codec import _reconstruction_matrix

    lost = (0, 5, 11, 13)
    surv = tuple(s for s in range(14) if s not in lost)[:10]
    dm = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    dm_bits = rs_jax.lifted_matrix(dm)

    def fused(bits, tile, mxu="int8"):
        # _apply_pm clamps explicit tiles to the (padded) input width, so
        # tiles larger than the golden input are safe to pass through;
        # tile=None lets auto_tile pick.
        return lambda d: rs_pallas.gf_apply_fused(bits, d, tile=tile, mxu=mxu)

    variants = [
        ("xla", lambda d: rs_jax.gf_apply(b_bits, d), pm),
        ("rebuild-xla", lambda d: rs_jax.gf_apply(dm_bits, d), dm),
    ]
    # auto-tiled form of every staged variant first (the production
    # configs), then the explicit-tile grid
    for mxu in MXUS:
        tag = "pallas-auto" if mxu == "int8" else f"pallas-{mxu}-auto"
        variants.append((tag, fused(b_bits, None, mxu), pm))
    variants.append(("rebuild-pallas-auto", fused(dm_bits, None), dm))
    variants.append(("rebuild-pallas-dma-auto", fused(dm_bits, None, "dma"), dm))

    if SMOKE:
        tiles = [8192]  # one explicit tile proves the tiled path; cheap
    elif quick:
        tiles = [8192, 16384]
    else:
        tiles = [8192, 16384, 32768, 65536]
    for t in tiles:
        for mxu in MXUS:
            tag = f"pallas-{t}" if mxu == "int8" else f"pallas-{mxu}-{t}"
            variants.append((tag, fused(b_bits, t, mxu), pm))
        variants.append((f"rebuild-pallas-{t}", fused(dm_bits, t), dm))
    return variants


def main():
    quick = "--quick" in sys.argv
    out_path = _arg_value("--out")
    resume = out_path is not None and "--no-resume" not in sys.argv
    # JAX_PLATFORMS=cpu must win over the axon sitecustomize (a cpu sanity
    # run must never touch — or hang on — the one-client TPU tunnel)
    from seaweedfs_tpu.utils.devices import honor_platform_env

    honor_platform_env()
    platform = jax.devices()[0].platform
    print(json.dumps({"platform": platform, "smoke": SMOKE, "out": out_path}), flush=True)

    done = load_done(out_path, platform=platform, tiny=B == 2) if resume else {}
    out_f = None
    if out_path:
        os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
        out_f = open_resume_out(out_path, resume)

    def persist(rec: dict) -> None:
        if out_f is not None:
            persist_record(out_f, rec)

    key = jax.random.PRNGKey(0)
    data = jax.block_until_ready(
        jax.random.randint(key, (B, 10, N), 0, 256, dtype=jnp.uint8)
    )

    # golden check inputs (small) — verify each variant is byte-exact
    # against its OWN gf8 matrix product (encode variants vs the parity
    # matrix, rebuild variants vs the decode matrix)
    small = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (1, 10, 8192), 0, 256, dtype=jnp.uint8)
    )

    variants = build_variants(quick)
    results = {}
    failed = []
    skipped = []
    for name, fn, gm in variants:
        if name in done:
            skipped.append(name)
            prior = done[name]
            if isinstance(prior.get("steady_gbps"), (int, float)):
                results[name] = prior["steady_gbps"]
            print(json.dumps({"variant": name, "resumed": True}), flush=True)
            continue
        rec = {
            "variant": name,
            "platform": platform,
            "tiny": B == 2,
            "when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        try:
            golden = gf8.gf_mat_mul(gm, small[0])
            got = np.asarray(fn(jnp.asarray(small))[0, : golden.shape[0]])
            exact = bool((got == golden).all())
            rec["exact"] = exact
            if not exact:
                raise ValueError("output mismatch vs gf8 golden")
            if not SMOKE:
                t = _median_time(
                    lambda: jax.block_until_ready(fn(data)), iters=5, warmup=2
                )
                rec["per_call_gbps"] = round(DATA_BYTES / t / 1e9, 3)
                rec["steady_gbps"] = round(
                    steady_gbps(fn, data, out_rows=gm.shape[0]), 3
                )
                results[name] = rec["steady_gbps"]
        except Exception as e:  # noqa: BLE001
            rec["error"] = str(e)[:300]
            failed.append(name)
        print(json.dumps(rec), flush=True)
        persist(rec)

    if out_f is not None:
        out_f.close()
    if SMOKE:
        print(
            json.dumps(
                {
                    "smoke_ok": not failed,
                    "variants": len(variants),
                    "failed": failed,
                    "skipped": len(skipped),
                }
            ),
            flush=True,
        )
        return 1 if failed else 0
    if results:
        best = max(results, key=results.get)
        print(
            json.dumps(
                {"best": best, "steady_gbps": results[best], "skipped": len(skipped)}
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
